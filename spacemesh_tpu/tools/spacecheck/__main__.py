"""spacecheck CLI: ``python -m spacemesh_tpu.tools.spacecheck``.

Exit codes: 0 clean (or everything suppressed/baselined), 1 new
findings or analyzer errors, 2 baseline problems (stale or unjustified
entries — suppression rot is a failure in its own right).

CI runs ``--format=github`` so findings land as inline annotations on
the PR diff; the default text format is for local use.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import baseline as baseline_mod
from .engine import RULE_IDS, run_paths

DEFAULT_BASELINE = "spacecheck_baseline.json"


def _default_paths(root: str) -> list[str]:
    out = []
    for cand in ("spacemesh_tpu", "tests"):
        p = os.path.join(root, cand)
        if os.path.isdir(p):
            out.append(p)
    return out


def _render_text(f) -> str:
    return (f"{f.path}:{f.line}:{f.col + 1}: {f.rule} {f.message}\n"
            f"    {f.snippet}\n    [fingerprint {f.fingerprint}]")


def _render_github(f) -> str:
    # '%0A' is the workflow-command newline escape
    msg = f"{f.rule} {f.message} [fingerprint {f.fingerprint}]"
    msg = msg.replace("%", "%25").replace("\r", "%0D").replace("\n", "%0A")
    return (f"::error file={f.path},line={f.line},"
            f"col={f.col + 1},title=spacecheck {f.rule}::{msg}")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m spacemesh_tpu.tools.spacecheck",
        description="project-specific static analysis "
                    "(docs/STATIC_ANALYSIS.md)")
    ap.add_argument("paths", nargs="*",
                    help="files/directories (default: spacemesh_tpu/ "
                         "and tests/ under --root)")
    ap.add_argument("--root", default=os.getcwd(),
                    help="project root paths are reported relative to")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: <root>/"
                         f"{DEFAULT_BASELINE} when it exists)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore any baseline file (report everything)")
    ap.add_argument("--write-baseline", metavar="FILE",
                    help="write the current findings as a baseline "
                         "(justifications start as TODO, which the "
                         "checker rejects until replaced)")
    ap.add_argument("--select", default=None,
                    help="comma-separated rule ids (default: all)")
    ap.add_argument("--format", choices=("text", "github", "json"),
                    default="text")
    ap.add_argument("--jobs", type=int, default=1, metavar="N",
                    help="fork-parallel rule execution (default: 1)")
    ap.add_argument("--cache", default=None, metavar="FILE",
                    help="incremental findings cache (default: beside "
                         "the autotune cache, $SPACEMESH_SPACECHECK_CACHE "
                         "overrides; full-rule runs only)")
    ap.add_argument("--no-cache", action="store_true",
                    help="always recompute, never read/write the cache")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        from . import rules as rules_pkg

        for rule in rules_pkg.ALL_RULES:
            doc = (rule.__doc__ or "").strip().splitlines()[0]
            doc = doc.removeprefix(f"{rule.RULE} ")
            print(f"{rule.RULE}  {doc}")
        return 0

    select = None
    if args.select:
        select = {s.strip().upper() for s in args.select.split(",")}
        unknown = select - set(RULE_IDS)
        if unknown:
            ap.error(f"unknown rules: {', '.join(sorted(unknown))}")

    root = os.path.abspath(args.root)
    paths = args.paths or _default_paths(root)
    if not paths:
        ap.error("no paths given and none of spacemesh_tpu/, tests/ "
                 f"exist under {root}")
    # the default-path cache holds the FULL tree's findings; a run over
    # an explicit path subset must not overwrite it with a subset doc
    # (an explicit --cache FILE is the caller's own file and is honored)
    cache: str | bool = False
    if not args.no_cache:
        cache = args.cache or (not args.paths)
    findings, errors = run_paths(paths, project_root=root, select=select,
                                 cache=cache, jobs=args.jobs)

    if args.write_baseline:
        baseline_mod.write(args.write_baseline, findings)
        print(f"wrote {len(findings)} finding(s) to "
              f"{args.write_baseline}; replace every TODO justification "
              "before checking it in", file=sys.stderr)
        return 0

    bl_path = args.baseline or os.path.join(root, DEFAULT_BASELINE)
    baseline: dict[str, dict] = {}
    bl_error: str | None = None
    if not args.no_baseline:
        try:
            baseline = baseline_mod.load(bl_path)
        except baseline_mod.BaselineError as e:
            bl_error = str(e)
    new, suppressed, stale = baseline_mod.split(findings, baseline)
    if select is not None:
        # a narrowed run computes no findings for deselected rules, so
        # their baseline entries are not evidence of rot — staleness is
        # only decidable for the rules that actually ran
        stale = [e for e in stale if e.get("rule") in select]

    if args.format == "json":
        print(json.dumps({
            "new": [vars(f) for f in new],
            "suppressed": [vars(f) for f in suppressed],
            "stale_baseline": stale,
            "errors": errors,
            "baseline_error": bl_error,
        }, indent=1))
    else:
        render = _render_github if args.format == "github" else _render_text
        for f in new:
            print(render(f))
        for e in errors:
            print(f"spacecheck: analyzer error: {e}", file=sys.stderr)
        if stale:
            for ent in stale:
                print("spacecheck: STALE baseline entry "
                      f"{ent.get('fingerprint')} ({ent.get('rule')} "
                      f"{ent.get('path')}): no current finding matches "
                      "— delete it or re-justify against the new "
                      "fingerprint", file=sys.stderr)
        if bl_error:
            print(f"spacecheck: {bl_error}", file=sys.stderr)
        print(f"spacecheck: {len(new)} new, {len(suppressed)} "
              f"baselined, {len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'}, "
              f"{len(errors)} error(s)", file=sys.stderr)

    if bl_error or stale:
        return 2
    if new or errors:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
