"""Baseline: grandfathered findings, each carrying its justification.

The baseline exists so the analyzer can land with the tree still
imperfect and immediately block *new* findings, while every accepted
finding stays visible, justified, and rot-checked:

* a finding matched by a baseline entry is reported as suppressed, not
  failed;
* a baseline entry with an empty/placeholder justification fails CI —
  a suppression nobody can explain is a finding, not an exception;
* a baseline entry that no longer matches any current finding fails CI
  as **stale** — the defect was fixed (delete the entry) or the code
  changed in a way that changed the line (re-justify against the new
  fingerprint). Stale suppressions otherwise accumulate until the file
  silently suppresses real regressions.

Fingerprints hash (rule, path, normalized line) — no line number, no
occurrence index — and matching is a **multiset** per fingerprint:
N identical offending lines need N baseline entries. Adding one more
identical violation therefore surfaces exactly one new finding; it can
never steal an existing entry's suppression.

Format (``spacecheck_baseline.json`` at the repo root)::

    {"version": 1,
     "findings": [
        {"fingerprint": "...", "rule": "SC001",
         "path": "spacemesh_tpu/...", "snippet": "...",
         "justification": "why this site is accepted"} ]}
"""

from __future__ import annotations

import json

from .engine import Finding

VERSION = 1
_PLACEHOLDERS = ("", "todo", "fixme", "tbd")


class BaselineError(ValueError):
    pass


def load(path: str) -> dict[str, list[dict]]:
    """{fingerprint: [entries]} (duplicates are the multiset count for
    identical offending lines). Raises BaselineError on malformed files
    or unjustified entries."""
    try:
        with open(path, encoding="utf-8") as fh:
            doc = json.load(fh)
    except FileNotFoundError:
        return {}
    except (OSError, ValueError) as e:
        raise BaselineError(f"unreadable baseline {path}: {e}") from e
    if not isinstance(doc, dict) or doc.get("version") != VERSION \
            or not isinstance(doc.get("findings"), list):
        raise BaselineError(
            f"baseline {path}: expected {{version: {VERSION}, "
            "findings: [...]}}")
    out: dict[str, list[dict]] = {}
    for i, ent in enumerate(doc["findings"]):
        if not isinstance(ent, dict) or not isinstance(
                ent.get("fingerprint"), str):
            raise BaselineError(f"baseline {path}: entry {i} malformed")
        just = ent.get("justification")
        if not isinstance(just, str) \
                or just.strip().lower() in _PLACEHOLDERS:
            raise BaselineError(
                f"baseline {path}: entry {i} "
                f"({ent.get('rule')} {ent.get('path')}) has no "
                "justification — every grandfathered finding must say "
                "why it is accepted")
        out.setdefault(ent["fingerprint"], []).append(ent)
    return out


def split(findings: list[Finding], baseline: dict[str, list[dict]]
          ) -> tuple[list[Finding], list[Finding], list[dict]]:
    """Multiset match -> (new findings, suppressed findings, stale
    baseline entries). Per fingerprint with n current findings and m
    baseline entries: min(n, m) suppress, extras past m are new,
    entries past n are stale."""
    budget = {fp: len(ents) for fp, ents in baseline.items()}
    new: list[Finding] = []
    suppressed: list[Finding] = []
    for f in findings:
        if budget.get(f.fingerprint, 0) > 0:
            budget[f.fingerprint] -= 1
            suppressed.append(f)
        else:
            new.append(f)
    stale = [ent for fp, ents in baseline.items()
             for ent in ents[:budget.get(fp, 0)]]
    return new, suppressed, stale


def write(path: str, findings: list[Finding],
          justification: str = "TODO") -> None:
    """Emit a baseline for the current findings. Justifications already
    present in the file at ``path`` are PRESERVED (matched per
    fingerprint, multiset order) — regenerating after fixing one
    finding must not reset the others to TODO. New entries default to a
    placeholder that load() REJECTS: the author must replace each one
    before the file passes CI (that is the point)."""
    try:
        existing = load(path)
    except BaselineError:
        existing = {}
    remaining = {fp: [e.get("justification") for e in ents]
                 for fp, ents in existing.items()}
    entries = []
    for f in findings:
        kept = remaining.get(f.fingerprint)
        just = kept.pop(0) if kept else justification
        entries.append(
            {"fingerprint": f.fingerprint, "rule": f.rule, "path": f.path,
             "snippet": f.snippet, "justification": just})
    doc = {"version": VERSION, "findings": entries}
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1, sort_keys=False)
        fh.write("\n")
