"""spacecheck: project-specific static analysis for recurring defects.

Eight PRs of review fixes in CHANGES.md form a near-periodic catalog of
the same defect families — event-loop-blocking calls in async code,
donated-buffer reuse after a failed dispatch, wall-clock reads in
virtual-time-aware modules, register/unregister pairing bugs, metrics
misregistration, and swallowed errors in consensus-critical paths.
Hand review re-finds them one at a time; this package encodes each as a
machine-checked AST rule, run over the tree by CI as a blocking job
(``python -m spacemesh_tpu.tools.spacecheck``).

Rules (each docstring cites the shipped review fix it generalizes):

==========  ===========================================================
SC001       clock discipline: no wall-clock reads or literal sleeps in
            virtual-time-aware modules (rules/sc001_clock.py)
SC002       no blocking calls lexically inside ``async def``
            (rules/sc002_async_blocking.py)
SC003       no reads of a donated buffer after the donating jit call
            (rules/sc003_donation.py)
SC004       register/unregister, span enter/exit, collector and
            executor/fd lifecycles pair on all paths
            (rules/sc004_pairing.py)
SC005       metrics hygiene: module-scope creation, unique names,
            literal label names, bounded label values
            (rules/sc005_metrics.py)
SC006       no bare/swallowing excepts in consensus-critical packages
            (rules/sc006_excepts.py)
==========  ===========================================================

Suppression is explicit and justified, never silent: a line pragma
(``# spacecheck: ok=SC001 <why>``), a module pragma for SC001
(``# spacecheck: wall-clock-ok <why>``), or a checked-in baseline entry
carrying a per-finding justification (``spacecheck_baseline.json``;
stale entries fail CI — see baseline.py and docs/STATIC_ANALYSIS.md).

The runtime-sanitizer complement — what AST cannot see — lives in
``spacemesh_tpu/utils/sanitize.py`` (``SPACEMESH_SANITIZE=1``).
"""

from .engine import Finding, run_paths  # noqa: F401

__all__ = ["Finding", "run_paths"]
