"""Standalone poet daemon (the reference's external poet service).

  python -m spacemesh_tpu.tools.poet_server --listen 127.0.0.1:9500 \
      [--ticks 64] [--id-seed poet-1] [--round-every SECONDS]

Collects member challenges per round, performs the sequential hash-chain
work, serves proofs + membership (reference: spacemeshos/poet service;
client side activation/poet.go). With --round-every it closes the open
round on a cadence; otherwise the node drives rounds explicitly.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="spacemesh_tpu.tools.poet_server")
    p.add_argument("--listen", default="127.0.0.1:0")
    p.add_argument("--ticks", type=int, default=64)
    p.add_argument("--id-seed", default="poet")
    p.add_argument("--round-every", type=float, default=0.0,
                   help="close the open round every N seconds (0 = only "
                        "on explicit execute_round)")
    a = p.parse_args(argv)

    from ..consensus.poet import PoetService
    from ..consensus.poet_remote import PoetServerDaemon
    from ..core.hashing import sum256

    # no persistent-cache wiring here on purpose: the poet's sequential
    # hash chain is pure hashlib — this process never JITs
    service = PoetService(poet_id=sum256(a.id_seed.encode()),
                          ticks=a.ticks)

    async def go():
        daemon = PoetServerDaemon(service, listen=a.listen)
        host, port = await daemon.start()
        print(json.dumps({"event": "Serving", "host": host, "port": port,
                          "poet_id": service.poet_id.hex()}), flush=True)

        async def round_driver():
            n = 0
            while True:
                await asyncio.sleep(a.round_every)
                open_rounds = list(service._open)
                for rid in open_rounds:
                    result = await service.execute_round(rid)
                    print(json.dumps({
                        "event": "RoundDone", "round": rid,
                        "members": len(result.members)}), flush=True)
                n += 1

        driver = (asyncio.ensure_future(round_driver())
                  if a.round_every > 0 else None)
        try:
            await asyncio.Event().wait()
        finally:
            if driver:
                driver.cancel()
            await daemon.stop()

    try:
        asyncio.run(go())
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
