"""merge-nodes: fold one node's smeshing identities into another node.

Reference cmd/merge-nodes: an operator combining two smeshers into one
multi-identity node moves the FROM node's identity keys and POST data
directories into the TO node's data dir; the node then smeshes for all
identities (smeshing.num_identities picks how many to load/create, and
existing key files are always loaded).

  python -m spacemesh_tpu.tools.merge_nodes --from-dir A --to-dir B
"""

from __future__ import annotations

import argparse
import json
import shutil
import sys
from pathlib import Path


def merge(from_dir: Path, to_dir: Path) -> dict:
    if from_dir.resolve() == to_dir.resolve():
        # neutralize-on-skip would otherwise rename EVERY key away
        raise SystemExit("from-dir and to-dir are the same directory")
    moved_keys, moved_post, skipped = [], [], []
    to_keys = to_dir / "identities"
    to_keys.mkdir(parents=True, exist_ok=True)
    existing = {p.read_text().strip() for p in to_keys.glob("*.key")}

    src_keys = sorted((from_dir / "identities").glob("*.key"))
    if not src_keys:
        raise SystemExit(f"no identity keys under {from_dir}/identities")
    next_idx = len(list(to_keys.glob("*.key")))
    for key_file in src_keys:
        seed = key_file.read_text().strip()
        if seed in existing:
            # the target already holds this identity (e.g. an interrupted
            # earlier merge): still NEUTRALIZE the source copy — leaving
            # it usable means two nodes smeshing one identity
            key_file.rename(key_file.with_suffix(".key.merged"))  # spacecheck: ok=SC009 archival move of an already-durable key file, not a publish-by-rename
            skipped.append(key_file.name)
            continue
        existing.add(seed)  # duplicate seeds within from-dir merge once
        # never overwrite: existing names may be non-contiguous (deleted
        # keys, partial merges) — an overwritten identity key is an
        # irrecoverable loss
        dest = to_keys / f"local_{next_idx:02d}.key"
        while dest.exists():
            next_idx += 1
            dest = to_keys / f"local_{next_idx:02d}.key"
        shutil.copy2(key_file, dest)
        dest.chmod(0o600)
        moved_keys.append(dest.name)
        next_idx += 1
        # MOVE semantics (reference cmd/merge-nodes): the source must not
        # keep a usable copy — two nodes smeshing the same identity is
        # self-equivocation and gets the identity slashed
        key_file.rename(key_file.with_suffix(".key.merged"))  # spacecheck: ok=SC009 archival move of an already-durable key file, not a publish-by-rename

    src_post = from_dir / "post"
    if src_post.is_dir():
        dst_post = to_dir / "post"
        dst_post.mkdir(parents=True, exist_ok=True)
        for d in sorted(src_post.iterdir()):
            if not d.is_dir():
                continue
            target = dst_post / d.name
            if target.exists():
                skipped.append(f"post/{d.name}")
                continue
            shutil.move(str(d), str(target))  # move, not copy (see keys)
            moved_post.append(d.name)

    return {"keys_merged": moved_keys, "post_dirs_merged": moved_post,
            "skipped": skipped,
            "total_identities": len(list(to_keys.glob("*.key")))}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="spacemesh_tpu.tools.merge_nodes")
    p.add_argument("--from-dir", required=True,
                   help="data dir whose identities move")
    p.add_argument("--to-dir", required=True,
                   help="data dir that will host them")
    a = p.parse_args(argv)
    result = merge(Path(a.from_dir), Path(a.to_dir))
    print(json.dumps(result))
    print(f"note: set smeshing.num_identities>="
          f"{result['total_identities']} on the target node",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
