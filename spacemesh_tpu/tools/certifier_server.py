"""certifier daemon CLI: issue poet certificates against POST proofs.

The reference poet deployments front registration with a certifier
service (reference activation/certifier.go:246 Certify); this serves
consensus/certifier.py's CertifierService standalone:

  python -m spacemesh_tpu.tools.certifier_server --listen 127.0.0.1:0 \
      --scrypt-n 8192 --k1 26 --k2 37 --k3 37
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="spacemesh_tpu.tools.certifier_server")
    p.add_argument("--listen", default="127.0.0.1:0")
    p.add_argument("--scrypt-n", type=int, default=8192)
    p.add_argument("--k1", type=int, default=26)
    p.add_argument("--k2", type=int, default=37)
    p.add_argument("--k3", type=int, default=37)
    p.add_argument("--pow-difficulty", default="00ff" + "ff" * 30)
    p.add_argument("--validity", type=float, default=0.0,
                   help="cert lifetime seconds (0 = no expiry)")
    p.add_argument("--key-seed", default=None,
                   help="hex seed for a deterministic certifier key "
                   "(default: fresh key)")
    a = p.parse_args(argv)

    from ..consensus.certifier import CertifierDaemon, CertifierService
    from ..core.signing import EdSigner
    from ..post.prover import ProofParams
    from ..utils import accel

    # cert issuance recomputes POST labels (a JIT'd scrypt pass): the
    # persistent cache turns the per-shape compile into a one-time cost
    accel.enable_persistent_cache()

    signer = EdSigner(seed=bytes.fromhex(a.key_seed) if a.key_seed else None)
    service = CertifierService(
        signer,
        ProofParams(k1=a.k1, k2=a.k2, k3=a.k3,
                    pow_difficulty=bytes.fromhex(a.pow_difficulty)),
        scrypt_n=a.scrypt_n, validity=a.validity)

    async def go():
        daemon = CertifierDaemon(service, listen=a.listen)
        host, port = await daemon.start()
        print(json.dumps({"event": "Serving", "host": host, "port": port,
                          "pubkey": service.pubkey.hex()}), flush=True)
        try:
            await asyncio.Event().wait()
        finally:
            await daemon.stop()

    try:
        asyncio.run(go())
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
