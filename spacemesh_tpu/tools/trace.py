"""Tortoise trace replayer (reference cmd/trace/main.go:19 -> RunTrace).

  python -m spacemesh_tpu.tools.trace TRACE.jsonl

Replays a recorded tortoise trace offline — deterministic consensus
debugging: the trace is self-contained (ballot events carry full opinions
and weights), so a node's exact vote-counting history can be re-executed
and inspected without its database.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="spacemesh_tpu.tools.trace")
    p.add_argument("trace", help="JSON-lines trace file (- for stdin)")
    p.add_argument("--verbose", action="store_true",
                   help="echo replayed events to stderr")
    a = p.parse_args(argv)

    from ..consensus.tortoise import replay_trace

    fh = sys.stdin if a.trace == "-" else open(a.trace)
    try:
        echo = (lambda line: print(line, file=sys.stderr)) if a.verbose else None
        t = replay_trace(fh, tracer=echo)
    finally:
        if fh is not sys.stdin:
            fh.close()

    print(json.dumps({
        "verified": t.verified,
        "processed": t.processed,
        "mode": t.mode,
        "ballots": len(t._ballots),
        "blocks": sum(len(v) for v in t._blocks.values()),
        "valid_blocks": sum(1 for v in t._validity.values() if v),
        "invalid_blocks": sum(1 for v in t._validity.values() if not v),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
