"""Bootstrapper: generate epoch fallback documents from a node's state.

Reference cmd/bootstrapper (generator.go): an operator-run tool that
produces the per-epoch JSON the bootstrap updater consumes — a fallback
beacon and/or active set for epochs where the live protocols might not
deliver (network halts, emergency restarts). Entropy for a synthesized
beacon comes from the epoch's ATX id set (the reference uses a bitcoin
block hash; any operator-auditable public entropy works — pass
--entropy-hex to override).

  python -m spacemesh_tpu.tools.bootstrapper --state S.db --epoch N \
      [--out fallback.json] [--beacon] [--activeset] [--entropy-hex H]
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path


def generate(db, epoch: int, *, with_beacon: bool, with_activeset: bool,
             entropy: bytes = b"") -> dict:
    from ..core.hashing import sum256
    from ..storage import atxs as atxstore
    from ..storage import misc as miscstore

    doc: dict = {"epoch": epoch}
    ids = sorted(atxstore.ids_in_epoch(db, epoch - 1))  # targeting `epoch`
    if with_beacon:
        stored = miscstore.get_beacon(db, epoch)
        if stored is not None:
            beacon = stored
        else:
            beacon = sum256(b"fallback-beacon", entropy, *ids)[:4]
        doc["beacon"] = beacon.hex()
    if with_activeset:
        doc["activeset"] = [i.hex() for i in ids]
    return doc


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="spacemesh_tpu.tools.bootstrapper")
    p.add_argument("--state", required=True, help="path to state.db")
    p.add_argument("--epoch", type=int, required=True)
    p.add_argument("--out", help="write/merge the doc into this JSON file")
    p.add_argument("--beacon", action="store_true")
    p.add_argument("--activeset", action="store_true")
    p.add_argument("--entropy-hex", default="",
                   help="public entropy for a synthesized beacon")
    a = p.parse_args(argv)
    if not (a.beacon or a.activeset):
        p.error("pick at least one of --beacon / --activeset")

    from ..storage import db as dbmod

    db = dbmod.open_state(a.state)
    try:
        doc = generate(db, a.epoch, with_beacon=a.beacon,
                       with_activeset=a.activeset,
                       entropy=bytes.fromhex(a.entropy_hex))
    finally:
        db.close()

    if a.out:
        path = Path(a.out)
        docs = []
        if path.exists():
            existing = json.loads(path.read_text())
            docs = existing if isinstance(existing, list) else [existing]
        docs = [d for d in docs if d.get("epoch") != a.epoch] + [doc]
        path.write_text(json.dumps(docs, indent=1))
    print(json.dumps(doc))
    return 0


if __name__ == "__main__":
    sys.exit(main())
