"""Operator command-line tools (reference cmd/: trace replayer, …)."""
