"""gen-p2p-identity: mint a node identity key file.

Mirrors the reference tool (reference cmd/gen-p2p-identity): generates
an ed25519 identity and writes it where the node looks for it
(data-dir/identities/local.key — node/app.py _load_or_create_identities;
the node id doubles as the p2p peer id, transport.py binds it to the
noise channel).

  python -m spacemesh_tpu.tools.gen_p2p_identity --data-dir ./node
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="spacemesh_tpu.tools.gen_p2p_identity")
    p.add_argument("--data-dir", required=True,
                   help="node data dir (key lands in identities/)")
    p.add_argument("--name", default="local.key",
                   help="key file name (local.key = the primary identity; "
                   "local_NN.key adds a smesher)")
    p.add_argument("--genesis-extra", default="tpu-mainnet")
    p.add_argument("--genesis-time", type=float, default=0.0,
                   help="unix seconds (with --genesis-extra derives the "
                   "signing prefix — must match the network config)")
    a = p.parse_args(argv)

    from ..core.signing import EdSigner
    from ..node.config import GenesisConfig

    prefix = GenesisConfig(time=a.genesis_time,
                           extra_data=a.genesis_extra).genesis_id
    key_dir = Path(a.data_dir) / "identities"
    key_dir.mkdir(parents=True, exist_ok=True)
    key_file = key_dir / a.name
    if key_file.exists():
        print(f"refusing to overwrite {key_file}", file=sys.stderr)
        return 1
    s = EdSigner(prefix=prefix)
    key_file.write_text(s.private_bytes().hex())
    key_file.chmod(0o600)
    print(json.dumps({"path": str(key_file), "node_id": s.node_id.hex()}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
