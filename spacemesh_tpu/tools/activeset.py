"""activeset: query the ATXs published in an epoch from a state db.

Mirrors the reference tool (reference cmd/activeset/activeset.go: ids +
total weight for a publish epoch, read straight from state.sql).

  python -m spacemesh_tpu.tools.activeset 3 ./node/state.db
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="spacemesh_tpu.tools.activeset")
    p.add_argument("publish_epoch", type=int)
    p.add_argument("db_path")
    a = p.parse_args(argv)

    from ..storage import atxs as atxstore
    from ..storage import db as dbmod

    db = dbmod.open_state(a.db_path)
    try:
        ids = atxstore.ids_in_epoch(db, a.publish_epoch)
        total_weight = 0
        entries = []
        for atx_id in ids:
            atx = atxstore.get(db, atx_id)
            height = atxstore.tick_height(db, atx_id) or 0
            prev_height = 0
            if atx is not None and atx.prev_atx:
                prev_height = atxstore.tick_height(db, atx.prev_atx) or 0
            weight = (atx.num_units if atx else 0) * \
                max(height - prev_height, 0)
            total_weight += weight
            entries.append({"id": atx_id.hex(),
                            "node_id": atx.node_id.hex() if atx else None,
                            "num_units": atx.num_units if atx else 0,
                            "weight": weight})
        print(json.dumps({"epoch": a.publish_epoch, "count": len(ids),
                          "total_weight": total_weight, "atxs": entries}))
    finally:
        db.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
