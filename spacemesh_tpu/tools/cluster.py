"""Systest cluster harness: N subprocess nodes + chaos, one command.

The reference's systest framework spins a cluster in k8s and injects
faults with chaos-mesh (reference systest/cluster/, systest/chaos/
fail.go:31 kill, partition.go:14 iptables split, timeskew.go:12 clock
shift); scenario watchers assert liveness from the public API
(systest/tests/common.go).  Here the cluster is subprocess-per-node over
real TCP + noise, faults ride the admin API (transport chaos_block,
time_offset), and the watchers poll each node's JSON API.

One command:

  python -m spacemesh_tpu.tools.cluster --nodes 6 --smeshers 2 \
      --scenario partition --layers 14

prints a JSON verdict line per scenario phase and exits non-zero on
failure.  The same ``Cluster`` class is the fixture behind
tests/test_cluster_chaos.py.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent.parent


def _reserve_port() -> tuple[socket.socket, int]:
    """Bind-and-HOLD: the socket stays open until just before the node
    spawns, shrinking the reuse window from the whole spinup to the
    node's own startup (ports handed out then instantly released can be
    re-assigned by the OS to another node or process)."""
    s = socket.socket()
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind(("127.0.0.1", 0))
    return s, s.getsockname()[1]


class NodeProc:
    def __init__(self, idx: int, base: Path, smesh: bool):
        self.idx = idx
        self.name = f"node{idx}"
        self.dir = base / self.name
        self.smesh = smesh
        self._port_holds: list[socket.socket] = []
        hold, self.listen_port = _reserve_port()
        self._port_holds.append(hold)
        hold, self.api_port = _reserve_port()
        self._port_holds.append(hold)
        self.proc: subprocess.Popen | None = None
        self.log_path = base / f"{self.name}.log"
        self._log = None

    def release_ports(self) -> None:
        for s in self._port_holds:
            s.close()
        self._port_holds = []

    @property
    def listen(self) -> str:
        return f"127.0.0.1:{self.listen_port}"

    def api(self, path: str, body: dict | None = None, timeout=5.0,
            attempts: int = 4):
        """One API call with transient-failure retries: on a machine
        loaded with N JAX subprocesses a node's accept queue can stall
        for a beat — a single refused connection must not fail a chaos
        scenario."""
        url = f"http://127.0.0.1:{self.api_port}{path}"
        data = json.dumps(body).encode() if body is not None else None
        last: Exception | None = None
        for attempt in range(attempts):
            req = urllib.request.Request(
                url, data=data,
                headers={"Content-Type": "application/json"} if data else {})
            try:
                with urllib.request.urlopen(req, timeout=timeout) as r:
                    return json.loads(r.read())
            except (urllib.error.URLError, OSError, TimeoutError) as e:
                last = e
                if attempt + 1 < attempts:
                    time.sleep(1.0)
        raise last

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


class Cluster:
    """Spin N nodes (the first ``smeshers`` of them smeshing), watch and
    shake them."""

    def __init__(self, base_dir: str | Path, n: int, smeshers: int = 1,
                 layer_sec: float = 1.5, lpe: int = 3,
                 spinup: float = 75.0, until_layer: int | None = None,
                 hare_round: float = 0.1):
        self.base = Path(base_dir)
        self.base.mkdir(parents=True, exist_ok=True)
        self.layer_sec = layer_sec
        self.lpe = lpe
        self.spinup = spinup
        self.until_layer = until_layer
        self.hare_round = hare_round
        self.genesis_time: float | None = None
        self.nodes = [NodeProc(i, self.base, i < smeshers)
                      for i in range(n)]

    # -- lifecycle ----------------------------------------------------

    def _config(self, node: NodeProc) -> Path:
        cfg = {
            "data_dir": str(node.dir),
            "layer_duration": self.layer_sec,
            "layers_per_epoch": self.lpe,
            "slots_per_layer": 2,
            "genesis": {"time": self.genesis_time},
            "post": {"labels_per_unit": 256, "scrypt_n": 2, "k1": 64,
                     "k2": 8, "k3": 4, "min_num_units": 1,
                     "pow_difficulty": "20" + "ff" * 31},
            "smeshing": {"start": node.smesh, "num_units": 1,
                         "init_batch": 128},
            "hare": {"committee_size": 20,
                     "round_duration": self.hare_round,
                     "preround_delay": 0.35, "iteration_limit": 2},
            "beacon": {"proposal_duration": 0.1},
            "tortoise": {"hdist": 4, "window_size": 50},
            "api": {"private_listener": f"127.0.0.1:{node.api_port}"},
        }
        path = self.base / f"{node.name}.json"
        path.write_text(json.dumps(cfg))
        return path

    def start(self) -> None:
        # one shared genesis AFTER every node's prepare budget — per-node
        # "now" genesis would put them on different networks
        self.genesis_time = time.time() + self.spinup
        boot = self.nodes[0].listen
        for node in self.nodes:
            cfg_path = self._config(node)
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            env.pop("PALLAS_AXON_POOL_IPS", None)
            env["PYTHONPATH"] = str(REPO) + os.pathsep + \
                env.get("PYTHONPATH", "")
            # every node compiles the same tiny POST shapes: share one
            # persistent XLA cache (utils/accel.py honors the override;
            # the node enables the cache itself inside initialize())
            env.setdefault("SPACEMESH_JAX_CACHE",
                           os.path.expanduser(
                               "~/.cache/spacemesh_tpu/jax_cache"))
            cmd = [sys.executable, "-u", "-m", "spacemesh_tpu.node",
                   "--preset", "standalone", "--config", str(cfg_path),
                   "--listen", node.listen, "--api"]
            if node.idx > 0:
                cmd += ["--bootnode", boot]
            if self.until_layer is not None:
                cmd += ["--until-layer", str(self.until_layer)]
            node._log = open(node.log_path, "w")
            node.release_ports()  # the node binds them itself now
            node.proc = subprocess.Popen(
                cmd, stdout=node._log, stderr=subprocess.STDOUT, env=env,
                cwd=str(REPO))

    def stop(self) -> None:
        for node in self.nodes:
            if node.alive():
                node.proc.terminate()
        deadline = time.time() + 15
        for node in self.nodes:
            if node.proc is not None:
                try:
                    node.proc.wait(max(0.1, deadline - time.time()))
                except subprocess.TimeoutExpired:
                    node.proc.kill()
            if node._log:
                node._log.close()

    # -- watchers (public API only, like systest/tests/common.go) -----

    def wait_api(self, timeout: float = 120.0) -> None:
        deadline = time.time() + timeout
        pending = list(self.nodes)
        while pending and time.time() < deadline:
            pending = [n for n in pending if not self._api_up(n)]
            time.sleep(0.5)
        if pending:
            raise TimeoutError(
                f"API never came up on {[n.name for n in pending]}")

    @staticmethod
    def _api_up(node: NodeProc) -> bool:
        try:
            node.api("/v1/node/status", attempts=1)  # polled: no retry
            return True
        except (urllib.error.URLError, OSError, TimeoutError):
            return False

    def wait_layer(self, layer: int, timeout: float = 120.0,
                   nodes: list[NodeProc] | None = None) -> None:
        deadline = time.time() + timeout
        for node in nodes or self.nodes:
            while True:
                if not node.alive():
                    # a node that EXITED CLEANLY ran through its
                    # configured until_layer — if that covers the
                    # requested layer, it reached it (its API is just
                    # gone); anything else is a real death
                    if node.proc is not None and node.proc.poll() == 0 \
                            and self.until_layer is not None \
                            and self.until_layer >= layer:
                        break
                    raise RuntimeError(f"{node.name} died "
                                       f"(log: {node.log_path})")
                try:
                    st = node.api("/v1/node/status")["status"]
                    if st["top_layer"] >= layer:
                        break
                except (urllib.error.URLError, OSError, TimeoutError):
                    pass
                if time.time() > deadline:
                    raise TimeoutError(
                        f"{node.name} never reached layer {layer}")
                time.sleep(self.layer_sec / 3)

    def state_hashes(self, layer: int,
                     nodes: list[NodeProc] | None = None) -> dict[str, str]:
        out = {}
        for node in nodes or self.nodes:
            info = node.api(f"/v1/mesh/layer/{layer}")
            out[node.name] = info.get("state_hash")
        return out

    def db_state_hashes(self, layer: int,
                        nodes: list[NodeProc] | None = None
                        ) -> dict[str, str | None]:
        """State hashes straight from each node's state.db — the
        post-mortem convergence check once nodes have exited cleanly
        and their APIs are gone (WAL files persist the applied state)."""
        from ..storage import db as dbmod
        from ..storage import layers as layerstore

        out: dict[str, str | None] = {}
        for node in nodes or self.nodes:
            d = dbmod.open_state(node.dir / "state.db")
            try:
                h = layerstore.state_hash(d, layer)
                out[node.name] = h.hex() if h else None
            finally:
                d.close()
        return out

    def converged(self, layer: int,
                  nodes: list[NodeProc] | None = None) -> bool:
        hashes = self.state_hashes(layer, nodes)
        vals = set(hashes.values())
        return len(vals) == 1 and None not in vals

    def wait_converged(self, layer: int, timeout: float = 90.0,
                       nodes: list[NodeProc] | None = None) -> None:
        deadline = time.time() + timeout
        while time.time() < deadline:
            try:
                if self.converged(layer, nodes):
                    return
            except (urllib.error.URLError, OSError, TimeoutError):
                pass
            time.sleep(self.layer_sec / 2)
        raise TimeoutError(
            f"no convergence at layer {layer}: {self.state_hashes(layer, nodes)}")

    # -- chaos (reference systest/chaos/) -----------------------------

    def partition(self, *groups: list[NodeProc]) -> None:
        """Split the cluster: every node blocks every node outside its
        group (chaos/partition.go:14)."""
        for group in groups:
            others = [n.listen for n in self.nodes if n not in group]
            for node in group:
                if node.alive():
                    node.api("/v1/admin/chaos/block", {"addrs": others})

    def heal(self) -> None:
        for node in self.nodes:
            if node.alive():
                node.api("/v1/admin/chaos/clear", {})

    def timeskew(self, node: NodeProc, offset: float) -> None:
        """Shift one node's clock (chaos/timeskew.go:12)."""
        node.api("/v1/admin/chaos/timeskew", {"offset": offset})

    def kill(self, node: NodeProc) -> None:
        """SIGKILL, no shutdown (chaos/fail.go:31)."""
        if node.alive():
            node.proc.send_signal(signal.SIGKILL)
            node.proc.wait(10)


# -- scenarios -------------------------------------------------------------


def scenario_partition(c: Cluster, report) -> None:
    c.wait_layer(2 * c.lpe, timeout=c.spinup + 2 * c.lpe * c.layer_sec + 120)
    half = len(c.nodes) // 2
    a, b = c.nodes[:half], c.nodes[half:]
    c.partition(a, b)
    report("partitioned", groups=[len(a), len(b)])
    split_until = 3 * c.lpe
    c.wait_layer(split_until, timeout=120)
    c.heal()
    report("healed", at_layer=split_until)
    target = split_until + c.lpe
    c.wait_layer(target + 2, timeout=180)
    c.wait_converged(target, timeout=180)
    report("converged", layer=target)


def scenario_timeskew(c: Cluster, report) -> None:
    c.wait_layer(c.lpe, timeout=c.spinup + c.lpe * c.layer_sec + 120)
    victim = c.nodes[-1]
    c.timeskew(victim, 3 * c.layer_sec)
    report("skewed", node=victim.name, offset=3 * c.layer_sec)
    c.wait_layer(2 * c.lpe + 1, timeout=120)
    c.timeskew(victim, 0.0)
    report("unskewed", node=victim.name)
    target = 3 * c.lpe
    c.wait_layer(target + 1, timeout=120)
    c.wait_converged(target, timeout=120)
    report("converged", layer=target)


def scenario_kill(c: Cluster, report) -> None:
    c.wait_layer(c.lpe, timeout=c.spinup + c.lpe * c.layer_sec + 120)
    victim = c.nodes[-1]
    c.kill(victim)
    report("killed", node=victim.name)
    survivors = [n for n in c.nodes if n is not victim]
    target = 2 * c.lpe + 2
    c.wait_layer(target + 1, timeout=120, nodes=survivors)
    c.wait_converged(target, timeout=120, nodes=survivors)
    report("converged_without_victim", layer=target)


SCENARIOS = {"partition": scenario_partition,
             "timeskew": scenario_timeskew,
             "kill": scenario_kill}


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="spacemesh_tpu.tools.cluster")
    p.add_argument("--nodes", type=int, default=6)
    p.add_argument("--smeshers", type=int, default=2)
    p.add_argument("--scenario", choices=[*SCENARIOS, "all"],
                   default="partition")
    p.add_argument("--base-dir", default=None)
    p.add_argument("--layer-sec", type=float, default=1.5)
    p.add_argument("--spinup", type=float, default=75.0)
    a = p.parse_args(argv)

    import tempfile

    base = a.base_dir or tempfile.mkdtemp(prefix="smcluster-")
    names = list(SCENARIOS) if a.scenario == "all" else [a.scenario]
    rc = 0
    for name in names:
        c = Cluster(Path(base) / name, a.nodes, smeshers=a.smeshers,
                    layer_sec=a.layer_sec, spinup=a.spinup)

        def report(phase, **kw):
            print(json.dumps({"scenario": name, "phase": phase, **kw}),
                  flush=True)

        c.start()
        try:
            c.wait_api(timeout=a.spinup + 120)
            report("api_up")
            SCENARIOS[name](c, report)
            report("PASS")
        except Exception as e:  # noqa: BLE001 — verdict, not traceback
            report("FAIL", error=f"{type(e).__name__}: {e}")
            rc = 1
        finally:
            c.stop()
    return rc


if __name__ == "__main__":
    sys.exit(main())
