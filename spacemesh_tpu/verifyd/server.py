"""verifyd network front-end: JSON-over-HTTP + gRPC admission surface.

Follows the api/ conventions: aiohttp routes shaped like api/http.py
(hex-encoded bytes, typed 4xx bodies, /metrics + /healthz + /readyz),
and a hand-wired grpc.aio service like api/rpc.py (the environment
ships grpcio without grpc_tools, so the four methods are registered
with ``method_handlers_generic_handler`` and carry the SAME JSON docs
as message bytes — one codec, two transports; verifyd/protocol.py).

Routes:

  POST /v1/client/register    {"client", "weight"?, "rate"?, "burst"?,
                               "max_queued"?, "max_inflight"?}
  POST /v1/client/unregister  {"client"}
  POST /v1/verify             {"client", "lane"?, "deadline_s"?,
                               "items": [request docs]}
                              -> {"status": "OK", "verdicts": [bool]}
                              |  429/503 {"status": "SHED", ...}
  GET  /v1/stats              service + farm + tuner counters
  GET  /v1/tune               measured batch-rate model rows
  GET  /metrics               Prometheus exposition
  GET  /healthz, /readyz      liveness / per-component readiness

Shed mapping: admission rejections are HTTP 429 (overload family) or
503 (``shutting_down``) with the typed doc — a client always learns WHY
and when to retry.  gRPC returns the same doc with 200-style status
(the doc's ``status`` field discriminates), so both transports shed
loudly and identically.
"""

from __future__ import annotations

import asyncio
import json

from aiohttp import web

from ..utils.metrics import REGISTRY
from . import protocol
from .service import Shed, VerifydClosed, VerifydService

_GRPC_SERVICE = "spacemesh.verifyd.Verifyd"

# HTTP status per shed reason: 503 only for a terminal condition the
# client should fail over from; everything else is retryable 429
_SHED_STATUS = {
    protocol.SHED_SHUTTING_DOWN: 503,
    protocol.SHED_UNREGISTERED: 403,
    protocol.SHED_REGISTRY_FULL: 429,
}


def _shed_response(exc: Shed) -> web.Response:
    return web.json_response(exc.to_doc(),
                             status=_SHED_STATUS.get(exc.reason, 429))


class VerifydServer:
    """Sockets around a :class:`VerifydService`.

    ``listen`` is the HTTP bind ("host:port", port 0 picks); pass
    ``grpc_listen`` to also serve the gRPC surface (None disables, and
    a missing grpcio disables it with a log line rather than an import
    error).  Always close in a ``finally`` — ``close()`` drains the
    service before the sockets go away (spacecheck SC004 checks the
    start/close pairing on package code).
    """

    def __init__(self, service: VerifydService | None = None,
                 listen: str = "127.0.0.1:0",
                 grpc_listen: str | None = None,
                 health_engine: bool = True, **service_kw):
        self.service = service if service is not None \
            else VerifydService(**service_kw)
        self.health_engine = None
        if health_engine:
            from ..obs import health as health_mod
            from ..obs import sli as sli_mod

            # /readyz integration (obs/): the engine ticks the verifyd
            # SLI window and evaluates the service SLOs on the same
            # injectable clock admission runs on, so readiness reflects
            # windowed truth, not instantaneous luck
            self.health_engine = health_mod.HealthEngine(
                slis=sli_mod.verifyd_slis(),
                slos=health_mod.verifyd_slos(),
                time_source=self.service._now)
        host, _, port = listen.rpartition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port or 0)
        self.grpc_listen = grpc_listen
        self.web_app = web.Application()
        self._routes()
        self.runner: web.AppRunner | None = None
        self.actual_port: int | None = None
        self.grpc_port: int | None = None
        self._grpc_server = None
        self._closed = False

    def _routes(self) -> None:
        r = self.web_app.router
        r.add_post("/v1/client/register", self.client_register)
        r.add_post("/v1/client/unregister", self.client_unregister)
        r.add_post("/v1/verify", self.verify)
        r.add_get("/v1/stats", self.stats)
        r.add_get("/v1/tune", self.tune)
        r.add_get("/metrics", self.metrics)
        r.add_get("/healthz", self.healthz)
        r.add_get("/readyz", self.readyz)
        # span-trace capture, same surface as api/http.py — this is what
        # FleetRouter.pull_captures() scrapes to build the merged fleet
        # timeline (docs/OBSERVABILITY.md § Fleet observability)
        r.add_get("/debug/trace/start", self.trace_start)
        r.add_get("/debug/trace/stop", self.trace_stop)
        r.add_get("/debug/trace/export", self.trace_export)

    # -- lifecycle ------------------------------------------------------

    async def start(self) -> int:
        """Start the service and both listeners; returns the HTTP port
        (``grpc_port`` is set when gRPC is enabled)."""
        await self.service.start()
        if self.health_engine is not None:
            self.health_engine.ensure_running()
        self.runner = web.AppRunner(self.web_app)
        await self.runner.setup()
        site = web.TCPSite(self.runner, self.host, self.port)
        await site.start()
        self.actual_port = site._server.sockets[0].getsockname()[1]
        if self.grpc_listen is not None:
            await self._start_grpc()
        return self.actual_port

    async def _start_grpc(self) -> None:
        try:
            import grpc
        except ImportError:
            import sys

            print("verifyd: grpcio unavailable; gRPC surface disabled",
                  file=sys.stderr)
            return

        def handler(method):
            async def unary(request_doc, context):
                del context
                return await method(request_doc)

            return grpc.unary_unary_rpc_method_handler(
                unary,
                request_deserializer=lambda b: json.loads(b or b"{}"),
                response_serializer=lambda d: json.dumps(d).encode())

        generic = grpc.method_handlers_generic_handler(_GRPC_SERVICE, {
            "Register": handler(self._grpc_register),
            "Unregister": handler(self._grpc_unregister),
            "Verify": handler(self._grpc_verify),
            "Stats": handler(self._grpc_stats),
        })
        server = grpc.aio.server()
        server.add_generic_rpc_handlers((generic,))
        self.grpc_port = server.add_insecure_port(self.grpc_listen)
        await server.start()
        self._grpc_server = server

    async def close(self) -> None:
        """Drain the service, then tear the sockets down. Idempotent."""
        if self._closed:
            return
        self._closed = True
        await self.service.aclose()
        if self.health_engine is not None:
            self.health_engine.close()
        if self._grpc_server is not None:
            await self._grpc_server.stop(grace=1.0)
            self._grpc_server = None
        if self.runner is not None:
            await self.runner.cleanup()
            self.runner = None

    # -- shared handler bodies ------------------------------------------

    def _do_register(self, body: dict) -> dict:
        if not isinstance(body, dict) or "client" not in body:
            raise protocol.ProtocolError('expected {"client": id, ...}')
        kwargs = {}
        for field, conv in (("weight", float), ("rate", float),
                            ("burst", float), ("max_queued", int),
                            ("max_inflight", int)):
            if body.get(field) is not None:
                try:
                    kwargs[field] = conv(body[field])
                except (TypeError, ValueError):
                    raise protocol.ProtocolError(
                        f"{field}: expected a number") from None
        return self.service.register_client(str(body["client"]), **kwargs)

    async def _do_verify(self, body: dict) -> dict:
        if not isinstance(body, dict):
            raise protocol.ProtocolError("expected a JSON object")
        cid = body.get("client")
        if cid is None:
            raise protocol.ProtocolError('expected {"client": id, ...}')
        items = body.get("items")
        if not isinstance(items, list):
            raise protocol.ProtocolError('items: expected a list')
        reqs = [protocol.request_from_doc(doc) for doc in items]
        lane = protocol.parse_lane(body.get("lane"))
        deadline = body.get("deadline_s")
        if deadline is not None:
            try:
                deadline = float(deadline)
            except (TypeError, ValueError):
                raise protocol.ProtocolError(
                    "deadline_s: expected a number") from None
        trace_parent = body.get("trace_parent")
        verdicts = await self.service.verify(
            str(cid), reqs, lane=lane, deadline_s=deadline,
            trace_parent=(str(trace_parent) if trace_parent else None))
        return {"status": "OK", "verdicts": [bool(v) for v in verdicts]}

    # -- HTTP handlers --------------------------------------------------

    @staticmethod
    async def _body(req) -> dict:
        try:
            return await req.json()
        except json.JSONDecodeError:
            raise web.HTTPBadRequest(text="body must be JSON")

    async def client_register(self, req) -> web.Response:
        body = await self._body(req)
        try:
            return web.json_response(self._do_register(body))
        except protocol.ProtocolError as e:
            raise web.HTTPBadRequest(text=str(e))
        except Shed as e:
            return _shed_response(e)
        except VerifydClosed as e:
            return web.json_response(
                Shed(protocol.SHED_SHUTTING_DOWN, str(e),
                     replica_hint=self.service.replica_hint).to_doc(),
                status=503)

    async def client_unregister(self, req) -> web.Response:
        body = await self._body(req)
        if not isinstance(body, dict) or "client" not in body:
            raise web.HTTPBadRequest(text='expected {"client": id}')
        gone = self.service.unregister_client(str(body["client"]))
        return web.json_response({"client": str(body["client"]),
                                  "unregistered": bool(gone)})

    async def verify(self, req) -> web.Response:
        body = await self._body(req)
        try:
            return web.json_response(await self._do_verify(body))
        except protocol.ProtocolError as e:
            raise web.HTTPBadRequest(text=str(e))
        except Shed as e:
            return _shed_response(e)
        except VerifydClosed as e:
            return web.json_response(
                Shed(protocol.SHED_SHUTTING_DOWN, str(e),
                     replica_hint=self.service.replica_hint).to_doc(),
                status=503)

    async def stats(self, req) -> web.Response:
        del req
        return web.json_response(self.service.stats_doc())

    async def tune(self, req) -> web.Response:
        del req
        tuner = self.service.tuner
        kinds = ("sig", "vrf", "membership", "post", "pow")
        return web.json_response({
            "targets": {k: tuner.target_batch(k) for k in kinds},
            "rates": {k: {str(b): round(r, 1)
                          for b, r in tuner.rates(k).items()}
                      for k in kinds},
            "stats": dict(tuner.stats),
        })

    async def metrics(self, req) -> web.Response:
        del req
        from ..obs.federate import FEDERATION

        # local registry first, then every federated proc= series (a
        # router replica also federating its own children re-exports
        # them — provenance survives one hop)
        return web.Response(text=REGISTRY.expose() + FEDERATION.expose(),
                            content_type="text/plain")

    # -- span-trace capture (mirror of api/http.py; the fleet pull
    # plane's scrape surface) ------------------------------------------

    async def trace_start(self, req) -> web.Response:
        from ..utils import metrics, tracing

        try:
            capacity = req.query.get("capacity")
            capacity = int(capacity) if capacity else None
        except ValueError:
            raise web.HTTPBadRequest(text="capacity must be an integer")
        role = req.query.get("role")
        if role:
            tracing.set_process_identity(role)
        tracing.start(capacity=capacity, jax_bridge=False)
        metrics.trace_enabled_gauge.set(1)
        metrics.trace_spans_gauge.set(0)
        return web.json_response({
            "enabled": True,
            "capacity": tracing.TRACER.capacity,
            "role": tracing.process_identity()["role"],
        })

    async def trace_stop(self, req) -> web.Response:
        from ..utils import metrics, tracing

        retained = tracing.stop()
        metrics.trace_enabled_gauge.set(0)
        metrics.trace_spans_gauge.set(tracing.TRACER.recorded())
        return web.json_response({
            "enabled": False,
            "spans_retained": retained,
            "spans_recorded": tracing.TRACER.recorded(),
        })

    async def trace_export(self, req) -> web.Response:
        del req
        from ..utils import metrics, tracing

        metrics.trace_spans_gauge.set(tracing.TRACER.recorded())
        # a big ring materializes AND serializes slowly; do both off
        # the loop (export() tolerates concurrent recording)
        body = await asyncio.to_thread(
            lambda: json.dumps(tracing.export()))
        return web.Response(text=body, content_type="application/json")

    async def healthz(self, req) -> web.Response:
        del req
        # liveness: the process serves; stalls are /readyz's job
        return web.json_response({"status": "ok",
                                  "closed": self.service._closed})

    async def readyz(self, req) -> web.Response:
        del req
        if self.health_engine is not None:
            report = dict(self.health_engine.current_report())
        else:
            from ..obs import health as health_mod

            components = health_mod.HEALTH.report()
            report = {"ready": all(e["healthy"]
                                   for e in components.values()),
                      "components": components, "slos": {}, "slis": {}}
        report["ready"] = bool(report["ready"]) and not self.service._closed
        report["service"] = self.service.stats_doc()
        return web.json_response(
            report, status=200 if report["ready"] else 503)

    # -- gRPC handlers (same docs, same semantics) ----------------------

    async def _grpc_register(self, doc: dict) -> dict:
        try:
            return {"status": "OK", **self._do_register(doc)}
        except protocol.ProtocolError as e:
            return {"status": "ERROR", "error": str(e)}
        except Shed as e:
            return e.to_doc()
        except VerifydClosed as e:
            return Shed(protocol.SHED_SHUTTING_DOWN, str(e),
                        replica_hint=self.service.replica_hint).to_doc()

    async def _grpc_unregister(self, doc: dict) -> dict:
        cid = doc.get("client")
        if cid is None:
            return {"status": "ERROR", "error": 'expected {"client": id}'}
        return {"status": "OK", "client": str(cid),
                "unregistered": self.service.unregister_client(str(cid))}

    async def _grpc_verify(self, doc: dict) -> dict:
        try:
            return await self._do_verify(doc)
        except protocol.ProtocolError as e:
            return {"status": "ERROR", "error": str(e)}
        except Shed as e:
            return e.to_doc()
        except VerifydClosed as e:
            return Shed(protocol.SHED_SHUTTING_DOWN, str(e),
                        replica_hint=self.service.replica_hint).to_doc()

    async def _grpc_stats(self, doc: dict) -> dict:
        del doc
        return {"status": "OK", **self.service.stats_doc()}
