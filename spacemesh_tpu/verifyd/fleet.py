"""verifyd fleet: sharded replicas behind one verification surface.

One verifyd process with one farm is a service ceiling (ROADMAP open
item #3).  This module is the fleet control plane that lifts it:

* :class:`FleetRouter` — places client identities on N replicas with
  the seeded consistent-hash bounded-load table (routing.py), holds one
  :class:`~..obs.remediate.CircuitBreaker` per replica, turns the
  windowed SLIs (per-replica queue-wait p99 + shed rate, obs/sli.py
  ``fleet_slis``) into load scores, a work-steal set for hot kinds, and
  the autoscaling gauges ``fleet_desired_replicas`` /
  ``fleet_replica_load_score``.
* :class:`FleetVerifier` — PR-15's :class:`~.failover.FailoverVerifier`
  generalized from remote→local to remote→remote→…→local.  It exposes
  the same farm-compatible surface (``await submit(req, lane)`` plus
  ``verify_batch``), walks the client's ring chain replica by replica
  under each replica's breaker, re-routes typed sheds instead of
  surfacing them (a ``registry_full`` replica re-places the client on
  its next ring choice; a draining replica trips and the chain moves
  on), and always has the node's local farm as the bit-identical last
  resort — admission is scheduling, never semantics, so a verdict from
  any replica or from the farm is the same verdict.

Per-shard admission state: every replica runs its own client registry,
token buckets and fair-share tenant weights (service.py ``shard=``), so
fleet capacity is the SUM of the replicas' ``max_clients`` — the router
sheds ``registry_full`` only past that fleet-wide bound.

node/app.py wires a fleet behind ``SPACEMESH_VERIFYD_URLS`` (comma-
separated endpoints) via :func:`fleet_from_urls`; the ``fleet`` sim
engine (sim/fleet.py) drives the whole plane deterministically.
"""

from __future__ import annotations

import asyncio
import math
import time
from typing import Callable, Optional

from ..obs import remediate as remediate_mod
from ..utils import logging as slog
from ..utils import metrics, tracing
from ..verify.farm import Lane
from . import protocol
from .routing import Placement
from .service import Shed, VerifydClosed

_log = slog.get("fleet")

# mirrors failover.py: these shed reasons say "this client is
# misconfigured ON THIS REPLICA", not "the replica is unhealthy" — they
# never trip the replica's breaker, they re-route
_NON_TRIPPING_SHEDS = frozenset({protocol.SHED_UNREGISTERED,
                                 protocol.SHED_REGISTRY_FULL})

PATH_LOCAL = "local"
PATH_LOCAL_FASTFAIL = "local_fastfail"  # every breaker open: no attempt


class _Replica:
    """One fleet member: endpoint + breaker + registration cache."""

    __slots__ = ("name", "endpoint", "breaker", "own_endpoint",
                 "registered", "max_clients", "ok", "failed")

    def __init__(self, name: str, endpoint, breaker, *,
                 own_endpoint: bool, max_clients: int):
        self.name = name
        self.endpoint = endpoint
        self.breaker = breaker
        self.own_endpoint = own_endpoint
        self.registered: set[str] = set()   # client ids registered here
        self.max_clients = max_clients
        self.ok = 0
        self.failed = 0


class FleetRouter:
    """Fleet membership, placement, breakers, and load signals.

    Lifecycle: construct → :meth:`start` (registers every replica
    breaker on the global registry) → ``register_replica`` /
    ``unregister_replica`` → :meth:`close` or ``await aclose()`` in a
    ``finally`` — SC004 pairs start/close and the replica
    register/unregister calls like every other long-lived component.
    """

    def __init__(self, *, seed: int = 0, vnodes: int = 64,
                 load_factor: float = 1.0,
                 hot_score: float = 1.0,
                 steal_margin: float = 0.25,
                 kind_heat_tau_s: float = 30.0,
                 kind_heat_threshold: float = 3.0,
                 target_utilization: float = 0.7,
                 min_replicas: int = 1, max_replicas: int = 64,
                 breaker_kw: dict | None = None,
                 time_source: Callable[[], float] = time.monotonic):
        self.placement = Placement(seed=seed, vnodes=vnodes,
                                   load_factor=load_factor)
        self.replicas: dict[str, _Replica] = {}
        self.hot_score = float(hot_score)
        self.steal_margin = float(steal_margin)
        self.kind_heat_tau_s = max(float(kind_heat_tau_s), 1e-6)
        self.kind_heat_threshold = float(kind_heat_threshold)
        self.target_utilization = min(max(float(target_utilization),
                                          1e-3), 1.0)
        self.min_replicas = max(int(min_replicas), 0)
        self.max_replicas = max(int(max_replicas), 1)
        self._breaker_kw = dict(breaker_kw or {})
        self._now = time_source
        self._started = False
        self._scores: dict[str, float] = {}
        # (replica, kind) -> (heat, t_last): decayed shed pressure that
        # drives per-kind stealing between SLI windows
        self._kind_heat: dict[tuple[str, str], tuple[float, float]] = {}
        # (replica, client) pairs whose registration went stale when the
        # client moved shards; drained best-effort by flush_stale so the
        # OLD replica's unregister_client drops its per-client series
        self._stale: list[tuple[str, str]] = []
        self.stats = {"steals": 0, "reroutes": 0, "replicas_added": 0,
                      "replicas_removed": 0}

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        """Register every replica breaker (idempotent)."""
        if not self._started:
            self._started = True
            for rep in self.replicas.values():
                remediate_mod.BREAKERS.register(rep.breaker)

    def close(self) -> None:
        """Synchronous teardown: unregister breakers and drop every
        fleet/per-replica metric series this router created."""
        if self._started:
            for rep in self.replicas.values():
                remediate_mod.BREAKERS.unregister(rep.breaker)
            self._started = False
        for name in list(self.replicas):
            metrics.fleet_replica_load.remove(replica=name)
        metrics.fleet_replicas.set(0)
        metrics.fleet_clients.set(0)

    async def aclose(self) -> None:
        self.close()
        for rep in self.replicas.values():
            if rep.own_endpoint:
                aclose = getattr(rep.endpoint, "aclose", None)
                if aclose is not None:
                    await aclose()

    # -- membership ------------------------------------------------------

    def register_replica(self, name: str, endpoint, *,
                         breaker: remediate_mod.CircuitBreaker | None
                         = None,
                         own_endpoint: bool = False,
                         max_clients: int = 64) -> list:
        """Add a replica; pair with :meth:`unregister_replica` when it
        leaves the fleet (SC004 enforces the pairing on package code).
        Returns the ``(client, old, new)`` moves the bounded-load
        rebalance made (≤ ceil(K/N); routing.py)."""
        name = str(name)
        if name in self.replicas:
            return []
        if breaker is None:
            kw = dict(failure_budget=3, window_s=60.0, cooldown_s=5.0,
                      cooldown_cap_s=120.0)
            kw.update(self._breaker_kw)
            breaker = remediate_mod.CircuitBreaker(
                f"verifyd.replica.{name}", time_source=self._now, **kw)
        rep = _Replica(name, endpoint, breaker,
                       own_endpoint=own_endpoint,
                       max_clients=max(int(max_clients), 1))
        self.replicas[name] = rep
        if self._started:
            remediate_mod.BREAKERS.register(breaker)
        moved = self.placement.add_replica(name)
        self._record_moves(moved, reason="replica_added")
        self.stats["replicas_added"] += 1
        metrics.fleet_replicas.set(len(self.replicas))
        return moved

    def unregister_replica(self, name: str) -> list:
        """Drop a replica: its breaker and per-replica series go away,
        and its clients spill to the survivors (≤ one replica's
        capacity moves)."""
        name = str(name)
        rep = self.replicas.pop(name, None)
        if rep is None:
            return []
        if self._started:
            remediate_mod.BREAKERS.unregister(rep.breaker)
        moved = self.placement.remove_replica(name)
        # the moved clients' old registrations died with the replica —
        # nothing to flush; drop any stale pairs pointing at it
        self._stale = [(r, c) for r, c in self._stale if r != name]
        self._record_moves(
            [m for m in moved if m[2]], reason="replica_removed",
            flush=False)
        self._scores.pop(name, None)
        self._kind_heat = {k: v for k, v in self._kind_heat.items()
                           if k[0] != name}
        metrics.fleet_replica_load.remove(replica=name)
        metrics.fleet_replica_verify_seconds.remove_matching(replica=name)
        metrics.fleet_replica_sheds.remove_matching(replica=name)
        # federation cardinality hygiene: a replica that LEAVES takes
        # its proc= series with it (crashed replicas are never
        # unregistered here — their snapshots stay flagged)
        from ..obs.federate import FEDERATION
        FEDERATION.drop(f"replica-{name}")
        self.stats["replicas_removed"] += 1
        metrics.fleet_replicas.set(len(self.replicas))
        return moved

    def _record_moves(self, moved, *, reason: str,
                      flush: bool = True) -> None:
        for cid, old, _new in moved:
            self.stats["reroutes"] += 1
            metrics.fleet_reroutes.inc(reason=reason)
            if flush and old in self.replicas:
                self._stale.append((old, cid))

    # -- placement / admission -------------------------------------------

    def fleet_max_clients(self) -> int:
        return sum(r.max_clients for r in self.replicas.values())

    def place_client(self, cid: str) -> str:
        """The client's replica, assigning it on first sight; raises a
        typed ``registry_full`` Shed past the FLEET-WIDE client bound
        (the per-shard registries scale admission past any single
        ``max_clients``)."""
        cid = str(cid)
        got = self.placement.replica_of(cid)
        if got is not None:
            return got
        if not self.replicas:
            raise LookupError("fleet has no replicas")
        bound = self.fleet_max_clients()
        if len(self.placement.assign) >= bound:
            raise Shed(protocol.SHED_REGISTRY_FULL,
                       f"{len(self.placement.assign)} clients placed "
                       f">= fleet capacity {bound}")
        placed = self.placement.place(cid)
        metrics.fleet_clients.set(len(self.placement.assign))
        return placed

    def forget_client(self, cid: str) -> None:
        old = self.placement.forget(cid)
        if old is not None:
            rep = self.replicas.get(old)
            if rep is not None:
                rep.registered.discard(str(cid))
        metrics.fleet_clients.set(len(self.placement.assign))

    def reroute(self, cid: str, *, avoid: str, reason: str) -> str | None:
        """Move a client off a replica that typed-shed it; the old
        registration is flushed so its per-client series drop."""
        cid = str(cid)
        target = self.placement.reroute(cid, avoid)
        if target is None or target == avoid:
            return None
        self.stats["reroutes"] += 1
        metrics.fleet_reroutes.inc(reason=reason)
        rep = self.replicas.get(avoid)
        if rep is not None and cid in rep.registered:
            self._stale.append((avoid, cid))
        metrics.fleet_clients.set(len(self.placement.assign))
        return target

    async def flush_stale(self) -> None:
        """Best-effort unregister of moved clients from their OLD
        replicas, so a re-routed identity's per-client metric series
        and tenant state do not linger on a shard it left (the PR-12
        series-leak pattern; regression-tested with a churn loop)."""
        while self._stale:
            name, cid = self._stale.pop()
            rep = self.replicas.get(name)
            if rep is None or cid not in rep.registered:
                continue
            rep.registered.discard(cid)
            if rep.breaker.state == remediate_mod.OPEN:
                continue       # dead replica: its registry dies with it
            try:
                await rep.endpoint.unregister(cid)
            except Exception:  # noqa: BLE001 — best-effort: the old
                # replica may be mid-outage; its own max_clients bound
                # and restart are the backstop
                pass

    # -- routing chain + work stealing -----------------------------------

    def chain(self, cid: str, kinds=()) -> list[str]:
        """Replica names to try in order: the client's sticky placement
        first (or a steal target when the placement is hot for these
        kinds), then the rest of its ring preference chain."""
        cid = str(cid)
        primary = self.placement.replica_of(cid)
        order: list[str] = []
        if primary is not None:
            order.append(primary)
        for member in self.placement.ring.walk(cid):
            if member != primary:
                order.append(member)
        if primary is None or len(order) < 2:
            return order
        if self._is_hot(primary, kinds):
            target = self.steal_target(primary)
            if target is not None:
                order.remove(target)
                order.insert(0, target)
                self.stats["steals"] += 1
                metrics.fleet_steals.inc(src=primary, dst=target)
        return order

    def _is_hot(self, name: str, kinds) -> bool:
        if self._scores.get(name, 0.0) >= self.hot_score:
            return True
        now = self._now()
        for kind in kinds:
            heat, t = self._kind_heat.get((name, kind), (0.0, now))
            if heat * math.exp(-(now - t) / self.kind_heat_tau_s) \
                    >= self.kind_heat_threshold:
                return True
        return False

    def steal_target(self, src: str) -> str | None:
        """The coolest healthy replica, when it is meaningfully cooler
        than ``src`` — otherwise stealing just moves the hot spot."""
        best, best_score = None, None
        for name, rep in self.replicas.items():
            if name == src \
                    or rep.breaker.state == remediate_mod.OPEN:
                continue
            score = self._scores.get(name, 0.0)
            if best_score is None or score < best_score \
                    or (score == best_score and name < best):
                best, best_score = name, score
        if best is None:
            return None
        src_score = self._scores.get(src, self.hot_score)
        if best_score + self.steal_margin > src_score:
            return None
        return best

    def note_shed(self, name: str, reason: str, kinds=()) -> None:
        """A typed shed from a replica: pressure signal for stealing."""
        metrics.fleet_replica_sheds.inc(replica=name, reason=reason)
        now = self._now()
        for kind in set(kinds):
            heat, t = self._kind_heat.get((name, kind), (0.0, now))
            heat = heat * math.exp(-(now - t) / self.kind_heat_tau_s)
            self._kind_heat[(name, kind)] = (heat + 1.0, now)

    # -- autoscaling signals ---------------------------------------------

    def update_signals(self, sli_values: dict,
                       *, queue_wait_slo_s: float = 0.25,
                       shed_slo_per_sec: float = 1.0) -> dict:
        """Fold the windowed SLIs (obs/sli.py ``fleet_slis``) into
        per-replica load scores and the ``fleet_desired_replicas``
        autoscaling gauge.  A score of 1.0 means "at target": the
        replica's queue-wait p99 sits at its SLO share or its shed rate
        at the tolerated rate; ≥ ``hot_score`` marks it stealable-from.
        """
        scores: dict[str, float] = {}
        for name in self.replicas:
            qwait = sli_values.get(f"fleet_replica_{name}_queue_p99")
            sheds = sli_values.get(f"fleet_replica_{name}_shed_per_sec")
            score = 0.0
            if qwait is not None:
                score = max(score, float(qwait) / queue_wait_slo_s)
            if sheds is not None:
                score = max(score, float(sheds) / shed_slo_per_sec)
            scores[name] = score
            metrics.fleet_replica_load.set(score, replica=name)
        self._scores = scores
        n = len(self.replicas)
        if n == 0:
            desired = 0
        else:
            # utilization autoscaling: enough replicas that the mean
            # score lands back at the target utilization
            mean = sum(scores.values()) / n
            desired = max(self.min_replicas,
                          min(self.max_replicas,
                              math.ceil(n * mean
                                        / self.target_utilization)
                              if mean > 0 else self.min_replicas))
        metrics.fleet_desired_replicas.set(desired)
        return {"scores": scores, "desired_replicas": desired}

    # -- fleet observability: the pull-and-merge plane -------------------

    async def start_captures(self, *, capacity: int | None = None) -> dict:
        """Start a span capture on every replica that exposes the
        /debug/trace surface (endpoints without it — fakes, legacy —
        are skipped). Returns {replica: start doc | None}."""
        out: dict = {}
        for name, rep in sorted(self.replicas.items()):
            ep = rep.endpoint
            if not hasattr(ep, "trace_start"):
                continue
            try:
                out[name] = await ep.trace_start(
                    capacity=capacity, role=f"replica-{name}")
            except Exception:  # noqa: BLE001 — a dead replica is not news
                out[name] = None
        return out

    async def pull_captures(self) -> dict:
        """Pull every reachable replica's trace capture AND metrics
        exposition into the federation under ``replica-<name>``;
        returns {proc: capture doc} for the pulled captures. A replica
        that cannot be scraped is skipped (its breaker already tells
        that story) — federation only ever holds real snapshots."""
        from ..obs.federate import FEDERATION

        pulled: dict = {}
        for name, rep in sorted(self.replicas.items()):
            ep = rep.endpoint
            if not hasattr(ep, "trace_export"):
                continue
            proc = f"replica-{name}"
            try:
                doc = await ep.trace_export()
                text = await ep.metrics_text()
            except Exception:  # noqa: BLE001 — unreachable replica
                continue
            FEDERATION.parse_and_update(proc, text, trace=doc)
            pulled[proc] = doc
        return pulled

    def merged_capture(self, parent: dict | None = None) -> dict | None:
        """One validate-clean timeline over the parent capture and every
        federated replica capture (``tracing.merge_captures``)."""
        from ..obs.federate import FEDERATION

        return FEDERATION.merged_capture(parent=parent)

    # -- introspection ---------------------------------------------------

    def state_doc(self) -> dict:
        return {
            "replicas": {
                name: {"breaker": rep.breaker.state_doc(),
                       "registered_clients": len(rep.registered),
                       "max_clients": rep.max_clients,
                       "load_score": round(
                           self._scores.get(name, 0.0), 4),
                       "ok": rep.ok, "failed": rep.failed}
                for name, rep in sorted(self.replicas.items())},
            "placement": self.placement.doc(),
            "fleet_max_clients": self.fleet_max_clients(),
            "stats": dict(self.stats),
        }


class FleetVerifier:
    """Replica-aware failover verifier over a :class:`FleetRouter`.

    The farm-compatible surface (``submit`` / ``verify_batch``) walks
    the client's chain — steal target, sticky placement, ring spills —
    under per-replica breakers, and lands on the local farm when the
    whole fleet is unreachable.  Every routing decision is visible:
    ``fleet_requests_total{path,lane}``, the per-replica latency/shed
    signals the router's autoscaler reads, and an optional observer the
    fleet sim uses for its replay-stable digest.

    Lifecycle: construct → :meth:`start` → :meth:`aclose` in a
    ``finally`` (SC004), closing an owned router (and its owned
    endpoints) with it.
    """

    def __init__(self, *, router: FleetRouter, farm,
                 client_id: str = "node",
                 deadline_s: float | None = None,
                 own_router: bool = False,
                 bus=None,
                 observer: Optional[Callable[..., None]] = None,
                 time_source: Callable[[], float] = time.monotonic,
                 audit_k: int = 0):
        self.router = router
        self.farm = farm
        self.client_id = str(client_id)
        self.deadline_s = deadline_s
        self._own_router = own_router
        self.bus = bus
        self.observer = observer
        self._now = time_source
        # audit_k > 0: spot-check that many items of every successful
        # remote batch against the local farm (byzantine detection)
        self.audit_k = int(audit_k)
        self.stats = {"remote_ok": 0, "remote_failed": 0,
                      "local": 0, "local_fastfail": 0,
                      "remote_attempts": 0, "failbacks": 0,
                      "audits": 0, "audit_divergence": 0}

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        self.router.start()

    async def aclose(self) -> None:
        self.shutdown()
        if self._own_router:
            await self.router.aclose()

    def shutdown(self) -> None:
        """Synchronous teardown half (App.close runs after the loop has
        exited): the router's breakers and series unregister; owned
        endpoints need the loop, so only :meth:`aclose` closes them."""
        if self._own_router:
            self.router.close()

    # -- the farm-compatible surface -------------------------------------

    async def submit(self, req, lane: Lane = Lane.GOSSIP) -> bool:
        return (await self.verify_batch([req], lane))[0]

    async def verify_batch(self, reqs: list, lane: Lane = Lane.GOSSIP,
                           *, client_id: str | None = None) -> list[bool]:
        """Verify a batch through the fleet: the client's replica chain
        first (typed sheds re-route, transport errors and draining
        replicas trip that replica's breaker and the chain moves on),
        the local farm as the bit-identical last resort — ALWAYS an
        answer for every failure mode the breakers model."""
        lane = Lane(lane)
        lname = lane.name.lower()
        cid = str(client_id) if client_id is not None else self.client_id
        t0 = self._now()
        await self.router.flush_stale()
        kinds = sorted({getattr(r, "kind", "?") for r in reqs})
        chain: list[str] = []
        if self.router.replicas:
            # a fleet-wide registry_full surfaces TYPED: placement is
            # admission, and a client past the fleet bound must hear a
            # shed, not be silently served off the books
            self.router.place_client(cid)
            chain = self.router.chain(cid, kinds)
        attempted = False
        for name in chain:
            rep = self.router.replicas.get(name)
            if rep is None or not rep.breaker.allow():
                continue
            attempted = True
            verdicts = await self._try_replica(rep, cid, reqs, lane)
            if verdicts is not None:
                dt = max(self._now() - t0, 0.0)
                metrics.fleet_replica_verify_seconds.observe(
                    dt, replica=name, lane=lname)
                return self._done(name, "remote", lname, t0, len(reqs),
                                  verdicts)
        path = PATH_LOCAL if attempted else PATH_LOCAL_FASTFAIL
        self.stats["local" if attempted else "local_fastfail"] += 1
        async with tracing.span("fleet.local",
                                {"lane": lname, "n": len(reqs),
                                 "fastfail": not attempted}
                                if tracing.is_enabled() else None):
            verdicts = list(await asyncio.gather(
                *(self.farm.submit(r, lane) for r in reqs)))
        return self._done(path, path, lname, t0, len(reqs), verdicts)

    # -- internals -------------------------------------------------------

    async def _try_replica(self, rep: _Replica, cid: str, reqs: list,
                           lane: Lane) -> list[bool] | None:
        """One replica's turn on the chain: verdicts on success, None
        when the chain should move on (breaker bookkeeping done)."""
        was_probe = rep.breaker.state == remediate_mod.HALF_OPEN
        self.stats["remote_attempts"] += 1
        kinds = [getattr(r, "kind", "?") for r in reqs]
        for retry in (False, True):
            try:
                async with tracing.span(
                        "fleet.remote",
                        {"replica": rep.name, "n": len(reqs)}
                        if tracing.is_enabled() else None):
                    verdicts = await self._remote_verify(rep, cid, reqs,
                                                         lane)
            except Shed as e:
                if e.reason == protocol.SHED_UNREGISTERED and not retry:
                    # replica restarted and lost the registration:
                    # re-register and retry THIS replica once before
                    # moving down the chain
                    rep.registered.discard(cid)
                    continue
                self._on_shed(rep, cid, e, kinds)
                return None
            except (asyncio.TimeoutError, TimeoutError) as e:
                self._trip(rep, f"deadline:{e!r}")
                return None
            except VerifydClosed as e:
                self._trip(rep, f"closed:{e!r}")
                return None
            except Exception as e:  # noqa: BLE001 — any transport/protocol failure moves down the chain
                self._trip(rep, f"transport:{e!r}")
                return None
            except BaseException:
                # cancelled mid-attempt: no verdict either way — the
                # probe slot must not stay held
                rep.breaker.abort_probe()
                raise
            else:
                if self.audit_k and reqs and \
                        not await self._audit(rep, reqs, lane, verdicts):
                    return None  # byzantine: tripped, chain moves on
                rep.ok += 1
                self.stats["remote_ok"] += 1
                if was_probe:
                    self.stats["failbacks"] += 1
                    _log.info("replica %s probe ok: failing back",
                              rep.name)
                rep.breaker.record_success()
                return verdicts
        return None

    async def _audit(self, rep: _Replica, reqs: list, lane: Lane,
                     verdicts: list[bool]) -> bool:
        """Spot-check a deterministic sample (first/last items) of a
        successful remote batch against the local farm — verdicts are
        bit-identical by construction, so ANY divergence means the
        replica is answering from a stale or hostile state.  The
        replica is tripped as byzantine and its whole batch discarded:
        a wrong verdict must never reach the caller even when the
        transport and the admission plane look perfectly healthy."""
        idxs = sorted({0, len(reqs) - 1})[:max(self.audit_k, 1)]
        self.stats["audits"] += 1
        for i in idxs:
            local = await self.farm.submit(reqs[i], lane)
            if bool(local) != bool(verdicts[i]):
                self.stats["audit_divergence"] += 1
                metrics.fleet_audit_divergence.inc(replica=rep.name)
                _log.warning("replica %s verdict diverges from local "
                             "farm on item %d: tripping as byzantine",
                             rep.name, i)
                if self.observer is not None:
                    self.observer("audit_divergence", replica=rep.name,
                                  index=i)
                self._trip(rep, "byzantine:audit_divergence")
                return False
        return True

    def _on_shed(self, rep: _Replica, cid: str, e: Shed,
                 kinds: list) -> None:
        self.router.note_shed(rep.name, e.reason, kinds)
        if e.reason in _NON_TRIPPING_SHEDS:
            # config-class: release a held probe slot (this outcome says
            # nothing about the replica's health) and re-place the
            # client when the REPLICA is full — its next ring choice has
            # per-shard headroom this registry does not
            rep.breaker.abort_probe()
            rep.registered.discard(cid)
            if e.reason == protocol.SHED_REGISTRY_FULL:
                self.router.reroute(cid, avoid=rep.name,
                                    reason=e.reason)
            _log.warning("replica %s shed %s (%s): re-routing",
                         rep.name, e.reason, e.detail)
        else:
            if e.reason == protocol.SHED_SHUTTING_DOWN:
                # a draining replica will keep shedding until it is
                # gone: move the client now instead of re-paying it
                self.router.reroute(cid, avoid=rep.name,
                                    reason=e.reason)
            self._trip(rep, f"shed:{e.reason}",
                       retry_after_s=e.retry_after_s)

    async def _remote_verify(self, rep: _Replica, cid: str, reqs: list,
                             lane: Lane) -> list[bool]:
        if cid not in rep.registered:
            await rep.endpoint.register(cid)
            rep.registered.add(cid)
        lname = lane.name.lower()
        if self.deadline_s is not None:
            return await asyncio.wait_for(
                rep.endpoint.verify(reqs, client=cid, lane=lname,
                                    deadline_s=self.deadline_s),
                timeout=self.deadline_s)
        return await rep.endpoint.verify(reqs, client=cid, lane=lname)

    def _trip(self, rep: _Replica, why: str,
              retry_after_s: float | None = None) -> None:
        rep.failed += 1
        self.stats["remote_failed"] += 1
        before = rep.breaker.state
        rep.breaker.record_failure(retry_after_s=retry_after_s)
        after = rep.breaker.state
        if self.observer is not None:
            self.observer("replica_failure", replica=rep.name, why=why,
                          state=after)
        if after != before and after == remediate_mod.OPEN:
            _log.warning("replica %s unhealthy (%s): breaker open, "
                         "chain continues without it", rep.name, why)
            if self.bus is not None:
                from ..node import events as events_mod

                self.bus.emit(events_mod.RemediationAction(
                    component=rep.breaker.component,
                    action="failover_replica", outcome="ok", detail=why))
            metrics.remediation_actions.inc(
                component=rep.breaker.component,
                action="failover_replica", outcome="ok")

    def _done(self, served_by: str, path: str, lname: str, t0: float,
              n: int, verdicts: list[bool]) -> list[bool]:
        """``path`` is the serving CLASS (remote/local/local_fastfail —
        the label the fleet SLIs rate over); ``served_by`` names the
        actual server (a replica, or the path itself for the farm)."""
        metrics.fleet_requests.inc(path=path, lane=lname)
        metrics.fleet_verify_seconds.observe(
            max(self._now() - t0, 0.0), path=path, lane=lname)
        if self.observer is not None:
            self.observer("served", served_by=served_by, path=path,
                          lane=lname, n=n)
        return verdicts

    def state_doc(self) -> dict:
        return {"client_id": self.client_id,
                "stats": dict(self.stats),
                "router": self.router.state_doc()}


class HttpReplicaEndpoint:
    """Multi-client HTTP endpoint for one replica (the fleet-side twin
    of client.py's single-identity :class:`VerifydClient`: same wire
    docs, ``client`` chosen per call)."""

    def __init__(self, base_url: str, *, session=None):
        self.base_url = base_url.rstrip("/")
        self._session = session
        self._own_session = session is None

    async def _sess(self):
        if self._session is None:
            import aiohttp

            self._session = aiohttp.ClientSession()
        return self._session

    async def _post(self, path: str, body: dict) -> dict:
        from .client import VerifydClient

        sess = await self._sess()
        async with sess.post(self.base_url + path, json=body) as resp:
            if resp.content_type == "application/json":
                doc = await resp.json()
            else:
                doc = {"status": "ERROR", "error": await resp.text()}
        VerifydClient._raise_typed(doc)
        return doc

    async def register(self, client: str, **kwargs) -> dict:
        doc = await self._post("/v1/client/register",
                               {"client": str(client), **kwargs})
        if doc.get("status") == "ERROR":
            raise protocol.ProtocolError(f"register failed: {doc}")
        return doc

    async def unregister(self, client: str) -> None:
        await self._post("/v1/client/unregister",
                         {"client": str(client)})

    async def verify(self, reqs: list, *, client: str,
                     lane: str = "gossip",
                     deadline_s: float | None = None) -> list[bool]:
        body = {"client": str(client), "lane": lane,
                "items": [protocol.request_to_doc(r) for r in reqs]}
        if deadline_s is not None:
            body["deadline_s"] = deadline_s
        # ship this caller's span identity so the replica's
        # verifyd.request span can parent to our fleet.remote span in
        # the merged fleet timeline
        token = tracing.link_token()
        if token:
            body["trace_parent"] = token
        doc = await self._post("/v1/verify", body)
        verdicts = doc.get("verdicts")
        if doc.get("status") != "OK" or not isinstance(verdicts, list):
            raise protocol.ProtocolError(f"verify failed: {doc}")
        return [bool(v) for v in verdicts]

    async def stats(self) -> dict:
        sess = await self._sess()
        async with sess.get(self.base_url + "/v1/stats") as resp:
            return await resp.json()

    # -- fleet observability pulls (server.py /debug/trace/*) ----------

    async def trace_start(self, *, capacity: int | None = None,
                          role: str | None = None) -> dict:
        """Start (or restart) a capture on the replica, stamping its
        process identity so the merged timeline shows provenance."""
        q = []
        if capacity is not None:
            q.append(f"capacity={int(capacity)}")
        if role:
            q.append(f"role={role}")
        sess = await self._sess()
        url = (self.base_url + "/debug/trace/start"
               + ("?" + "&".join(q) if q else ""))
        async with sess.get(url) as resp:
            return await resp.json()

    async def trace_export(self) -> dict:
        sess = await self._sess()
        async with sess.get(self.base_url + "/debug/trace/export") as resp:
            return await resp.json()

    async def metrics_text(self) -> str:
        sess = await self._sess()
        async with sess.get(self.base_url + "/metrics") as resp:
            return await resp.text()

    async def aclose(self) -> None:
        if self._own_session and self._session is not None:
            await self._session.close()
            self._session = None


def fleet_from_urls(urls, *, farm, client_id: str = "node",
                    deadline_s: float | None = None,
                    seed: int = 0, max_clients: int = 64,
                    bus=None,
                    time_source: Callable[[], float] = time.monotonic
                    ) -> FleetVerifier:
    """Build a FleetVerifier over HTTP replicas (node/app.py wires this
    behind ``SPACEMESH_VERIFYD_URLS``; replica names are r0..rN in URL
    order, so a restarted node reproduces the same ring)."""
    router = FleetRouter(seed=seed, time_source=time_source)
    for i, url in enumerate(u.strip() for u in urls):
        if not url:
            continue
        router.register_replica(  # spacecheck: ok=SC004 the router escapes into the FleetVerifier (own_router=True), whose aclose tears every replica down
            f"r{i}", HttpReplicaEndpoint(url), own_endpoint=True,
            max_clients=max_clients)
    return FleetVerifier(router=router, farm=farm, client_id=client_id,
                         deadline_s=deadline_s, own_router=True,
                         bus=bus, time_source=time_source)
