"""Speculative batch sizing for the verification service.

The farm's static policy — dispatch at ``max_batch`` or when a 2-10 ms
lane deadline expires — is tuned for ONE node's gossip ingest.  A
network verification service (verifyd) sees workloads whose optimal
batch size varies by orders of magnitude per kind: a k2pow witness
batch amortizes device dispatch across thousands of lanes, a pure-Python
ed25519 MSM check peaks around a few hundred signatures, a POST
recompute is already near-flat past a handful of proofs.  Guessing those
numbers per host is exactly the problem ops/autotune.py already solved
for the ROMix kernel, so this module reuses its **race-and-persist**
pattern:

* :meth:`BatchTuner.ensure_raced` measures each kind's REAL backend at
  a ladder of candidate batch sizes on a deterministic calibration
  workload (once per host), and persists the measured ``batch ->
  items/sec`` rows to ``<cache root>/verifyd_batchtune.json`` beside the
  ROMix winners file — a second process skips the race entirely.
  ``SPACEMESH_VERIFYD_TUNE=off`` disables racing (static defaults +
  online refinement only); ``SPACEMESH_VERIFYD_TUNE_CACHE`` moves the
  file.  A corrupt or unreadable file is ignored and re-raced.
* Live batches keep the model honest: the farm calls
  :meth:`observe` after every dispatch (an EWMA into the nearest
  measured row), so kinds too expensive to race (POST) converge on real
  numbers anyway.

The **speculative dispatch decision** (:meth:`dispatch_now`): with
``n`` items pending and a measured arrival rate, dispatching now costs
``service(n) / n`` seconds per item; waiting to fill the tuned target
batch costs ``(fill_wait + service(target)) / target``.  The batch goes
NOW as soon as the marginal wait exceeds the predicted throughput gain
— a partially-full batch is dispatched the moment waiting stops paying,
and the farm's lane deadlines remain a hard latency cap on top
(verify/farm.py consumes this through its ``tuner`` hook).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

SCHEMA = 1
ENV_TUNE = "SPACEMESH_VERIFYD_TUNE"
ENV_CACHE = "SPACEMESH_VERIFYD_TUNE_CACHE"
_OFF = ("0", "off", "none", "false")

# candidate batch-size ladders per kind: the raced grid, and the
# buckets live observations EWMA into (a raw-occupancy key per batch
# would fragment the model into noise). post is deliberately absent
# from the RACED set — building a real POST store for calibration is a
# multi-second affair — so it starts from the static target and
# converges through observe() alone.
CANDIDATES: dict[str, tuple[int, ...]] = {
    "sig": (1, 8, 32, 128, 256),
    "vrf": (1, 4, 16),
    "membership": (1, 16, 64),
    "pow": (1, 32, 256, 1024),
    "post": (1, 4, 8, 32),
}

# static fallbacks when no measurement exists yet (race disabled or a
# cold in-process start): the shapes PR 2's bench measured as near-peak
STATIC_TARGETS: dict[str, int] = {
    "sig": 256, "vrf": 64, "membership": 64, "post": 8, "pow": 1024,
}

_EWMA = 0.3           # weight of a fresh observation
_ARRIVAL_EWMA = 0.2   # weight of a fresh interarrival sample
CAL_REPS = 2


def _log(*a) -> None:
    print(*a, file=sys.stderr, flush=True)


def race_enabled() -> bool:
    return (os.environ.get(ENV_TUNE) or "").lower() not in _OFF


def cache_path() -> str:
    """The measured-rates file, colocated with the XLA compile cache
    (the same placement rule as ops/autotune.cache_path)."""
    explicit = os.environ.get(ENV_CACHE)
    if explicit:
        return os.path.expanduser(explicit)
    from ..utils import accel

    jax_cache = os.environ.get("SPACEMESH_JAX_CACHE")
    if not jax_cache or jax_cache in _OFF:
        jax_cache = accel.DEFAULT_CACHE_DIR
    root = os.path.dirname(os.path.expanduser(jax_cache))
    return os.path.join(root, "verifyd_batchtune.json")


def _load_cache(path: str | None = None) -> dict:
    path = path or cache_path()
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        if not isinstance(doc, dict):
            raise ValueError("batchtune cache root is not an object")
        return doc
    except FileNotFoundError:
        return {}
    except (OSError, ValueError) as e:
        # a corrupt rates file must never break admission — re-race
        _log(f"verifyd batchtune: ignoring unreadable cache {path} ({e})")
        return {}


def _store(key: str, entry: dict) -> None:
    path = cache_path()
    doc = _load_cache(path)
    doc[key] = entry
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        # durable write (tmp + fsync + rename + dir-fsync): a power cut
        # mid-save must never leave a half-written rates file that the
        # corrupt-cache-ignored path above silently re-measures away
        from ..utils import fsio

        fsio.atomic_write_text(
            path, json.dumps(doc, indent=1, sort_keys=True))
    except OSError as e:
        # persistence is an optimization (read-only HOME, sandboxed CI)
        _log(f"verifyd batchtune: cannot persist rates ({e})")


def _key(platform: str, kind: str) -> str:
    return f"v{SCHEMA}:{platform}:{kind}"


def _valid_rows(raw) -> dict[int, float]:
    out: dict[int, float] = {}
    if not isinstance(raw, dict):
        return out
    for b, rate in raw.items():
        try:
            bi = int(b)
        except (TypeError, ValueError):
            continue
        if bi >= 1 and isinstance(rate, (int, float)) and rate > 0:
            out[bi] = float(rate)
    return out


# --- calibration workloads ----------------------------------------------
#
# Deterministic, cheap, and REAL: each builder returns farm request
# objects the backend under test actually dispatches, so the race
# measures the code path production runs (the autotune lesson: race with
# the production jit key or the compile is repaid).


def _cal_sigs(count: int) -> list:
    import hashlib

    from ..core.signing import Domain, EdSigner
    from ..verify.farm import SigRequest

    s = EdSigner(seed=hashlib.sha256(b"batchtune-sig").digest())
    return [SigRequest(int(Domain.BALLOT), s.public_key,
                       b"cal-%d" % i, s.sign(Domain.BALLOT, b"cal-%d" % i))
            for i in range(count)]


def _cal_vrfs(count: int) -> list:
    import hashlib

    from ..core.signing import EdSigner
    from ..verify.farm import VrfRequest

    vs = EdSigner(seed=hashlib.sha256(b"batchtune-vrf").digest()
                  ).vrf_signer()
    return [VrfRequest(vs.public_key, b"cal-alpha-%d" % i,
                       vs.prove(b"cal-alpha-%d" % i))
            for i in range(count)]


def _cal_memberships(count: int) -> list:
    from ..consensus.poet import merkle_path, merkle_root
    from ..verify.farm import MembershipRequest

    members = [b"cal-member-%d" % k for k in range(16)]
    root = merkle_root(members)
    return [MembershipRequest(members[i % 16],
                              merkle_path(members, i % 16), root, 16)
            for i in range(count)]


def _cal_pows(count: int) -> list:
    import hashlib

    from ..verify.farm import PowRequest

    challenge = hashlib.sha256(b"batchtune-pow-c").digest()
    node = hashlib.sha256(b"batchtune-pow-n").digest()
    # all-ones difficulty: every nonce is a hit, so calibration measures
    # pure hash+compare throughput, no search
    return [PowRequest(challenge, node, bytes([0xFF]) * 32, i)
            for i in range(count)]


_CAL_BUILDERS = {
    "sig": _cal_sigs,
    "vrf": _cal_vrfs,
    "membership": _cal_memberships,
    "pow": _cal_pows,
}


class BatchTuner:
    """Measured per-kind batch-rate model + the speculative dispatch
    policy (module docstring).  Plugs into VerificationFarm via its
    ``tuner=`` hook: the farm calls :meth:`note_arrival` per submit,
    :meth:`observe` per dispatched batch, and consults
    :meth:`target_batch` / :meth:`dispatch_now` when coalescing.

    ``backend(kind, requests) -> verdicts`` is the callable raced by
    :meth:`ensure_raced` (verifyd passes the farm's ``_run_backend``);
    without one, racing is skipped and the model starts from the static
    targets, refined online.  All state is lock-guarded — the farm
    drives it from the event loop, races run on a worker thread.
    """

    def __init__(self, *, backend=None, platform: str | None = None,
                 max_batch: int = 1024,
                 time_source=time.monotonic):
        self._backend = backend
        self._platform = platform
        self.max_batch = max(int(max_batch), 1)
        self._now = time_source
        self._lock = threading.Lock()
        # kind -> {batch: items/s} (persisted rows + online EWMA)
        self._rates: dict[str, dict[int, float]] = {}
        self._loaded: set[str] = set()
        self._raced: set[str] = set()
        # kind -> (last arrival t, EWMA interarrival s)
        self._arrivals: dict[str, tuple[float, float | None]] = {}
        # (kind, bucket) pairs whose FIRST live observation was
        # discarded: the first dispatch at a shape pays its XLA
        # compile/trace, and feeding that wall time to the model once
        # convinced it batching was 100x slower than reality (the
        # autotune lesson: never time the compile run)
        self._warmed: set[tuple[str, int]] = set()
        self.stats = {"races": 0, "observations": 0,
                      "discarded_cold": 0,
                      "speculative_dispatches": 0}

    # -- persistence ---------------------------------------------------

    def platform(self) -> str:
        if self._platform is None:
            import jax

            self._platform = jax.default_backend()
        return self._platform

    def _rows(self, kind: str) -> dict[int, float]:
        """The model rows for ``kind``, loading persisted measurements
        on first touch (never racing — see ensure_raced)."""
        rows = self._rates.get(kind)
        if rows is None:
            rows = self._rates[kind] = {}
        if kind not in self._loaded:
            self._loaded.add(kind)
            entry = _load_cache().get(_key(self.platform(), kind), {})
            for b, r in _valid_rows(entry.get("raced")).items():
                rows.setdefault(b, r)
        return rows

    def ensure_raced(self, kinds=None) -> dict:
        """Race any kind with no persisted measurements, persist the
        rows, and return ``{kind: {batch: rate}}`` for the raced set.

        Blocking (one backend run per candidate batch): call it from a
        worker thread at service start, never from the event loop.  A
        no-op per kind once measurements exist (persisted or from a
        prior call), when racing is disabled (``SPACEMESH_VERIFYD_TUNE=
        off``), or without a backend."""
        out: dict = {}
        if self._backend is None or not race_enabled():
            return out
        for kind in (kinds if kinds is not None else sorted(CANDIDATES)):
            builder = _CAL_BUILDERS.get(kind)
            if builder is None:
                continue
            with self._lock:
                rows = dict(self._rows(kind))
                if rows or kind in self._raced:
                    continue  # measured already (here or a prior process)
                self._raced.add(kind)
            raced = self._race_kind(kind, builder)
            if not raced:
                continue
            with self._lock:
                self._rows(kind).update(raced)
            _store(_key(self.platform(), kind),
                   {"raced": {str(b): round(r, 1)
                              for b, r in raced.items()},
                    "tuned_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                              time.gmtime())})
            out[kind] = raced
        return out

    def _race_kind(self, kind: str, builder) -> dict[int, float]:
        from ..utils import metrics, tracing

        metrics.verifyd_batchtune_races.inc()
        self.stats["races"] += 1
        cands = [b for b in CANDIDATES[kind] if b <= self.max_batch] or [1]
        items = builder(max(cands))
        raced: dict[int, float] = {}
        sp = tracing.span("verifyd.batchtune_race", {"kind": kind}
                          if tracing.is_enabled() else None)
        try:
            sp.__enter__()
            from ..core.signing import clear_verify_cache

            for b in cands:
                reqs = items[:b]
                try:
                    best = float("inf")
                    for _ in range(CAL_REPS):
                        # the verdict LRU must not subsidize a rep: a
                        # cached race would model cache-hit throughput,
                        # not verification
                        clear_verify_cache()
                        t0 = time.perf_counter()
                        self._backend(kind, reqs)
                        best = min(best, time.perf_counter() - t0)
                    raced[b] = b / max(best, 1e-9)
                except Exception as e:  # noqa: BLE001 — a failing candidate loses the race, it must not kill service start
                    _log(f"verifyd batchtune: {kind}/b={b} failed "
                         f"({type(e).__name__}: {e})")
            if raced:
                best_b = max(raced, key=lambda b: raced[b])
                _log(f"verifyd batchtune: {kind}: "
                     + ", ".join(f"b{b}={raced[b]:,.0f}/s"
                                 for b in sorted(raced))
                     + f" -> target {best_b} (persisted)")
        finally:
            sp.__exit__(None, None, None)
        return raced

    # -- the live model -------------------------------------------------

    def note_arrival(self, kind: str, now: float) -> None:
        """One submitted item (farm submit hook): EWMA interarrival."""
        with self._lock:
            last = self._arrivals.get(kind)
            if last is None:
                self._arrivals[kind] = (now, None)
                return
            t_prev, ia = last
            dt = max(now - t_prev, 1e-6)
            ia = dt if ia is None else (_ARRIVAL_EWMA * dt
                                        + (1 - _ARRIVAL_EWMA) * ia)
            self._arrivals[kind] = (now, ia)

    def arrival_rate(self, kind: str) -> float:
        """Items/s from the interarrival EWMA; 0.0 before two arrivals."""
        with self._lock:
            last = self._arrivals.get(kind)
        if last is None or last[1] is None or last[1] <= 0:
            return 0.0
        return 1.0 / last[1]

    def observe(self, kind: str, batch: int, seconds: float) -> None:
        """One dispatched batch's measured wall cost (farm hook): EWMA
        into the nearest candidate row, so the model tracks the live
        workload even for kinds that were never raced."""
        if batch < 1 or seconds <= 0:
            return
        rate = batch / seconds
        cands = CANDIDATES.get(kind)
        near = (min(cands, key=lambda b: abs(b - batch)) if cands
                else batch)
        with self._lock:
            if (kind, near) not in self._warmed:
                # first observation at this bucket: likely a compile —
                # discard it (module comment on _warmed)
                self._warmed.add((kind, near))
                self.stats["discarded_cold"] += 1
                return
            rows = self._rows(kind)
            old = rows.get(near)
            rows[near] = rate if old is None else (
                _EWMA * rate + (1 - _EWMA) * old)
            self.stats["observations"] += 1

    def rates(self, kind: str) -> dict[int, float]:
        with self._lock:
            return dict(self._rows(kind))

    NOISE_BAND = 0.90  # rows within 10% of the best rate count as tied

    def target_batch(self, kind: str) -> int:
        """The measured-throughput-optimal batch size for ``kind`` (the
        static default while no measurement exists), capped at
        ``max_batch``.  Among rows within the noise band of the best
        rate the LARGEST batch wins — the inverse of the autotuner's
        fewer-devices tie-break, for the same reason mirrored: small
        calibration batches flatter fixed-overhead amortization, so a
        near-tie at calibration is a real win for the fuller batch at
        service scale (and fewer dispatches is itself a win under
        load)."""
        with self._lock:
            rows = self._rows(kind)
            if rows:
                best_rate = max(rows.values())
                best = max(b for b, r in rows.items()
                           if r >= self.NOISE_BAND * best_rate)
            else:
                best = STATIC_TARGETS.get(kind, self.max_batch)
        return max(1, min(int(best), self.max_batch))

    def service_s(self, kind: str, n: int) -> float | None:
        """Predicted backend seconds for a batch of ``n`` (linear
        interpolation of the measured rate between the bracketing
        rows, clamped outside); None with no measurements."""
        n = max(int(n), 1)
        with self._lock:
            rows = sorted(self._rows(kind).items())
        if not rows:
            return None
        if n <= rows[0][0]:
            return n / rows[0][1]
        if n >= rows[-1][0]:
            return n / rows[-1][1]
        for (b0, r0), (b1, r1) in zip(rows, rows[1:]):
            if b0 <= n <= b1:
                frac = (n - b0) / (b1 - b0)
                return n / (r0 + frac * (r1 - r0))
        return n / rows[-1][1]

    def dispatch_now(self, kind: str, n: int, oldest_age_s: float) -> bool:
        """True when a batch of ``n`` should go NOW rather than linger
        for more arrivals: per-item latency of dispatching immediately
        is no worse than the predicted per-item latency of waiting to
        fill the target batch (fill wait estimated from the arrival
        EWMA).  False defers to the farm's deadline policy — this hook
        only ever dispatches EARLIER."""
        del oldest_age_s  # the lane deadline stays the hard latency cap
        if n <= 0:
            return False
        target = self.target_batch(kind)
        if n >= target:
            return True
        svc_n = self.service_s(kind, n)
        svc_t = self.service_s(kind, target)
        if svc_n is None or svc_t is None:
            # no model yet: dispatch now (the latency-safe default —
            # the first observed batch creates the model)
            self.stats["speculative_dispatches"] += 1
            return True
        arr = self.arrival_rate(kind)
        if arr <= 0.0:
            # no arrival estimate — assume nothing else is coming
            self.stats["speculative_dispatches"] += 1
            return True
        fill_wait = (target - n) / arr
        per_now = svc_n / n
        per_wait = (fill_wait + svc_t) / target
        go = per_now <= per_wait
        if go:
            self.stats["speculative_dispatches"] += 1
        return go
