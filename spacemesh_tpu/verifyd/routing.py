"""Consistent-hash placement for the verifyd fleet (docs/VERIFYD.md).

Two layers, both DETERMINISTIC functions of (seed, member names,
client ids) — never of process identity:

* :class:`HashRing` — the classic vnode ring, hashed with seeded
  sha256.  Python's builtin ``hash()`` is salted per process
  (PYTHONHASHSEED), which would silently break the fleet's core
  contract: two routers built from the same seed and member set MUST
  place the same client on the same replica, or a restarted router
  would scatter every client's admission state (token bucket level,
  fair-share vtime, per-client series) across the fleet.
* :class:`Placement` — a STICKY bounded-load assignment table over the
  ring (Mirrokni et al.'s consistent hashing with bounded loads).  Each
  replica holds at most ``ceil(load_factor * K / N)`` clients; a client
  whose ring owner is full spills clockwise to the next replica with
  headroom.  Membership changes move only the clients they must:
  *remove* re-places exactly the dead replica's clients (≤ capacity of
  one replica), *add* moves only clients whose FIRST ring choice is the
  new replica, hard-capped at ``ceil(K / N)`` — so with the default
  ``load_factor=1.0`` any single membership change relocates at most
  ``ceil(K / N)`` clients (tests/test_fleet_routing.py pins both the
  bound and the cross-process determinism).
"""

from __future__ import annotations

import bisect
import hashlib
import math

DEFAULT_VNODES = 64


def ring_hash(seed: int, *parts) -> int:
    """64-bit seeded sha256 point — the ONLY hash the ring uses."""
    h = hashlib.sha256(str(int(seed)).encode("ascii"))
    for p in parts:
        h.update(b"\x00")
        h.update(str(p).encode("utf-8"))
    return int.from_bytes(h.digest()[:8], "big")


class HashRing:
    """Seeded vnode ring over replica names."""

    def __init__(self, members=(), *, seed: int = 0,
                 vnodes: int = DEFAULT_VNODES):
        self.seed = int(seed)
        self.vnodes = max(int(vnodes), 1)
        self._points: list[tuple[int, str]] = []   # sorted (hash, member)
        self._members: set[str] = set()
        for m in members:
            self.add(m)

    def __len__(self) -> int:
        return len(self._members)

    def __contains__(self, member: str) -> bool:
        return str(member) in self._members

    def members(self) -> list[str]:
        return sorted(self._members)

    def add(self, member: str) -> None:
        member = str(member)
        if member in self._members:
            return
        self._members.add(member)
        for v in range(self.vnodes):
            # the member name disambiguates equal hashes so ring order
            # never depends on insertion order
            bisect.insort(self._points,
                          (ring_hash(self.seed, member, v), member))

    def remove(self, member: str) -> None:
        member = str(member)
        if member not in self._members:
            return
        self._members.discard(member)
        self._points = [p for p in self._points if p[1] != member]

    def key_point(self, key: str) -> int:
        return ring_hash(self.seed, "key", key)

    def walk(self, key: str):
        """Members in ring order clockwise from ``key``'s point, each
        yielded once — the client's full preference chain."""
        if not self._points:
            return
        i = bisect.bisect_left(self._points, (self.key_point(key), ""))
        seen: set[str] = set()
        n = len(self._points)
        for off in range(n):
            member = self._points[(i + off) % n][1]
            if member not in seen:
                seen.add(member)
                yield member

    def owner(self, key: str) -> str:
        """First ring choice, ignoring load (raises on an empty ring)."""
        for member in self.walk(key):
            return member
        raise LookupError("hash ring has no members")


class Placement:
    """Sticky bounded-load client→replica assignment over a HashRing."""

    def __init__(self, *, seed: int = 0, vnodes: int = DEFAULT_VNODES,
                 load_factor: float = 1.0):
        self.ring = HashRing(seed=seed, vnodes=vnodes)
        self.load_factor = max(float(load_factor), 1.0)
        self.assign: dict[str, str] = {}           # client -> replica
        self.loads: dict[str, int] = {}            # replica -> #clients

    # -- introspection ---------------------------------------------------

    def replicas(self) -> list[str]:
        return self.ring.members()

    def capacity(self, clients: int | None = None) -> int:
        """Per-replica client cap for ``clients`` total (bounded load)."""
        n = len(self.loads)
        if n == 0:
            raise LookupError("placement has no replicas")
        k = len(self.assign) if clients is None else int(clients)
        return max(math.ceil(self.load_factor * k / n), 1)

    def replica_of(self, cid: str) -> str | None:
        return self.assign.get(str(cid))

    # -- membership ------------------------------------------------------

    def add_replica(self, name: str) -> list[tuple[str, str, str]]:
        """Add a replica; -> [(client, old, new)] for every client moved
        onto it (≤ ceil(K/N) — the hard rebalance budget)."""
        name = str(name)
        if name in self.loads:
            return []
        self.ring.add(name)
        self.loads[name] = 0
        if not self.assign:
            return []
        k, n = len(self.assign), len(self.loads)
        cap = self.capacity()
        budget = math.ceil(k / n)
        moved: list[tuple[str, str, str]] = []
        # deterministic sweep order: ring order of the clients, so two
        # routers replaying the same membership history agree
        for cid in sorted(self.assign,
                          key=lambda c: (self.ring.key_point(c), c)):
            if len(moved) >= budget or self.loads[name] >= cap:
                break
            if self.ring.owner(cid) != name:
                continue
            old = self.assign[cid]
            if old == name:
                continue
            self.loads[old] -= 1
            self.loads[name] += 1
            self.assign[cid] = name
            moved.append((cid, old, name))
        return moved

    def remove_replica(self, name: str) -> list[tuple[str, str, str]]:
        """Drop a replica; its clients (≤ one replica's capacity) spill
        clockwise to survivors with headroom."""
        name = str(name)
        if name not in self.loads:
            return []
        self.ring.remove(name)
        del self.loads[name]
        displaced = sorted(
            (c for c, r in self.assign.items() if r == name),
            key=lambda c: (self.ring.key_point(c), c))
        for cid in displaced:
            del self.assign[cid]
        moved: list[tuple[str, str, str]] = []
        if not self.loads:
            return [(cid, name, "") for cid in displaced]
        for cid in displaced:
            moved.append((cid, name, self.place(cid)))
        return moved

    # -- clients ---------------------------------------------------------

    def place(self, cid: str) -> str:
        """The client's replica (assigning it on first sight): first
        ring choice with bounded-load headroom, spilling clockwise."""
        cid = str(cid)
        got = self.assign.get(cid)
        if got is not None:
            return got
        cap = self.capacity(len(self.assign) + 1)
        last = None
        for member in self.ring.walk(cid):
            last = member
            if self.loads[member] < cap:
                break
        if last is None:
            raise LookupError("placement has no replicas")
        self.assign[cid] = last
        self.loads[last] += 1
        return last

    def reroute(self, cid: str, avoid: str) -> str | None:
        """Move ``cid`` off ``avoid`` to its next ring choice with
        headroom (a typed registry_full shed re-routes instead of
        surfacing); None when no other replica exists."""
        cid = str(cid)
        current = self.assign.get(cid)
        cap = self.capacity()
        best = None
        for member in self.ring.walk(cid):
            if member == avoid:
                continue
            if best is None:
                best = member            # last resort: everyone full
            if self.loads[member] < cap:
                best = member
                break
        if best is None:
            return None
        if current is not None:
            self.loads[current] -= 1
        self.assign[cid] = best
        self.loads[best] += 1
        return best

    def forget(self, cid: str) -> str | None:
        """Drop a client (it unregistered); -> the replica it held."""
        old = self.assign.pop(str(cid), None)
        if old is not None and old in self.loads:
            self.loads[old] -= 1
        return old

    def doc(self) -> dict:
        return {"replicas": self.replicas(),
                "clients": len(self.assign),
                "loads": dict(sorted(self.loads.items())),
                "capacity": (self.capacity() if self.loads else 0),
                "load_factor": self.load_factor}
