"""verifyd — standalone verification-as-a-service (docs/VERIFYD.md).

The in-process farm (spacemesh_tpu/verify/) promoted to a network
service: a gRPC + HTTP admission front-end that verifies signatures,
VRF proofs, NIPoST proofs, poet memberships, and k2pow witnesses for
REMOTE nodes — per-client token-bucket admission with typed load
shedding, stride fair share + EDF deadlines through the device runtime
(one tenant per client), and continuous batching with speculative
batch sizing (batchtune.py) into the farm's device batchers.

    python -m spacemesh_tpu.verifyd --listen 127.0.0.1:9443

Layout: service.py (admission core), server.py (sockets), client.py
(cookbook client), batchtune.py (measured batch-size model),
protocol.py (wire codec), routing.py (consistent-hash placement),
fleet.py (multi-replica router/verifier), failover.py (remote→local).
"""

from .batchtune import BatchTuner
from .client import RetryPolicy, VerifydClient
from .failover import FailoverVerifier
from .fleet import (FleetRouter, FleetVerifier, HttpReplicaEndpoint,
                    fleet_from_urls)
from .protocol import ProtocolError, request_from_doc, request_to_doc
from .routing import HashRing, Placement
from .server import VerifydServer
from .service import Shed, VerifydClosed, VerifydService

__all__ = [
    "BatchTuner",
    "FailoverVerifier",
    "FleetRouter",
    "FleetVerifier",
    "HashRing",
    "HttpReplicaEndpoint",
    "Placement",
    "ProtocolError",
    "RetryPolicy",
    "Shed",
    "VerifydClient",
    "VerifydClosed",
    "VerifydServer",
    "VerifydService",
    "fleet_from_urls",
    "request_from_doc",
    "request_to_doc",
]
