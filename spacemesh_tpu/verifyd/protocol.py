"""verifyd wire protocol: JSON docs <-> farm request objects.

One codec shared by the HTTP front-end, the gRPC front-end (which
carries the same JSON docs as message bytes — the environment ships
grpcio without grpc_tools, so messages are explicit, exactly like
api/rpc.py's hand-wired services), the client library, and the sim
load scenario.  Byte fields travel as hex; every decode error raises
:class:`ProtocolError` with a path-qualified message so a client sees
WHICH field was malformed, never a bare 500.

Request doc shapes (``kind`` selects):

  sig        {"kind": "sig", "domain": int, "public_key": hex,
              "msg": hex, "signature": hex}
  vrf        {"kind": "vrf", "public_key": hex, "alpha": hex,
              "proof": hex}
  membership {"kind": "membership", "member": hex, "root": hex,
              "leaf_count": int,
              "proof": {"leaf_index": int, "nodes": [hex]}}
  pow        {"kind": "pow", "challenge": hex, "node_id": hex,
              "difficulty": hex, "nonce": int}
  post       {"kind": "post", "challenge": hex, "node_id": hex,
              "commitment": hex, "scrypt_n": int, "total_labels": int,
              "proof": {"nonce": int, "indices": [int],
                        "pow_nonce": int, "k2": int}}

A verify call: {"client": id, "lane": "block"|"gossip"|"sync",
"deadline_s": seconds | null, "items": [request docs]}.  A shed
response: {"status": "SHED", "reason": ..., "detail": ...,
"retry_after_s": seconds | null} — typed, never a silent drop
(docs/VERIFYD.md).
"""

from __future__ import annotations

from ..verify.farm import (
    Lane,
    MembershipRequest,
    PostRequest,
    PowRequest,
    SigRequest,
    VrfRequest,
)

LANES = {"block": Lane.BLOCK, "gossip": Lane.GOSSIP, "sync": Lane.SYNC}

# typed shed reasons (admission policy in service.py; docs/VERIFYD.md)
SHED_RATE = "rate"                    # token bucket empty
SHED_QUOTA = "quota"                  # scheduler per-tenant max_queued
SHED_OVERLOAD = "overload"            # client above fair share at the bound
SHED_QUEUE_FULL = "queue_full"        # global pending bound, client in share
SHED_DEADLINE = "deadline"            # predicted wait exceeds the deadline
SHED_UNREGISTERED = "unregistered"
SHED_REGISTRY_FULL = "registry_full"  # max_clients reached
SHED_SHUTTING_DOWN = "shutting_down"

SHED_REASONS = (SHED_RATE, SHED_QUOTA, SHED_OVERLOAD, SHED_QUEUE_FULL,
                SHED_DEADLINE, SHED_UNREGISTERED, SHED_REGISTRY_FULL,
                SHED_SHUTTING_DOWN)


class ProtocolError(ValueError):
    """Malformed request doc (field-qualified message)."""


def _hex(b: bytes) -> str:
    return b.hex()


def _unhex(doc: dict, field: str, length: int | None = None) -> bytes:
    raw = doc.get(field)
    if not isinstance(raw, str):
        raise ProtocolError(f"{field}: expected a hex string")
    try:
        b = bytes.fromhex(raw)
    except ValueError:
        raise ProtocolError(f"{field}: not valid hex") from None
    if length is not None and len(b) != length:
        raise ProtocolError(f"{field}: expected {length} bytes, "
                            f"got {len(b)}")
    return b


def _int(doc: dict, field: str) -> int:
    v = doc.get(field)
    if not isinstance(v, int) or isinstance(v, bool):
        raise ProtocolError(f"{field}: expected an integer")
    return v


def _u64(doc: dict, field: str) -> int:
    """A remote-supplied u64 (nonces): JSON ints are unbounded, and an
    out-of-range value must be a typed 400 at the protocol boundary —
    deep inside the farm it would raise mid-batch and poison every
    co-batched client's dispatch."""
    v = _int(doc, field)
    if not 0 <= v < 1 << 64:
        raise ProtocolError(f"{field}: expected an unsigned 64-bit "
                            f"integer")
    return v


def parse_lane(name) -> Lane:
    if name is None:
        return Lane.GOSSIP
    lane = LANES.get(str(name).lower())
    if lane is None:
        raise ProtocolError(
            f"lane: expected one of {sorted(LANES)}, got {name!r}")
    return lane


def request_from_doc(doc) -> object:
    """One wire doc -> the farm request object it describes."""
    if not isinstance(doc, dict):
        raise ProtocolError("item: expected an object")
    kind = doc.get("kind")
    if kind == "sig":
        return SigRequest(_int(doc, "domain"),
                          _unhex(doc, "public_key"),
                          _unhex(doc, "msg"), _unhex(doc, "signature"))
    if kind == "vrf":
        return VrfRequest(_unhex(doc, "public_key"),
                          _unhex(doc, "alpha"), _unhex(doc, "proof"))
    if kind == "membership":
        from ..core.types import MerkleProof

        p = doc.get("proof")
        if not isinstance(p, dict) or not isinstance(p.get("nodes"), list):
            raise ProtocolError(
                "proof: expected {leaf_index, nodes: [hex]}")
        nodes = [_unhex({"node": n}, "node") for n in p["nodes"]]
        return MembershipRequest(
            _unhex(doc, "member"),
            MerkleProof(leaf_index=_int(p, "leaf_index"), nodes=nodes),
            _unhex(doc, "root"), _int(doc, "leaf_count"))
    if kind == "pow":
        return PowRequest(_unhex(doc, "challenge", 32),
                          _unhex(doc, "node_id", 32),
                          _unhex(doc, "difficulty", 32),
                          _u64(doc, "nonce"))
    if kind == "post":
        from ..post.prover import Proof
        from ..post.verifier import VerifyItem

        p = doc.get("proof")
        if not isinstance(p, dict) or not isinstance(p.get("indices"),
                                                     list):
            raise ProtocolError(
                "proof: expected {nonce, indices, pow_nonce, k2}")
        if not all(isinstance(i, int) and not isinstance(i, bool)
                   for i in p["indices"]):
            raise ProtocolError("proof.indices: expected integers")
        return PostRequest(VerifyItem(
            proof=Proof(nonce=_int(p, "nonce"),
                        indices=list(p["indices"]),
                        pow_nonce=_u64(p, "pow_nonce"),
                        k2=_int(p, "k2")),
            challenge=_unhex(doc, "challenge"),
            node_id=_unhex(doc, "node_id"),
            commitment=_unhex(doc, "commitment"),
            scrypt_n=_int(doc, "scrypt_n"),
            total_labels=_int(doc, "total_labels")))
    raise ProtocolError(f"kind: unknown request kind {kind!r}")


def request_to_doc(req) -> dict:
    """A farm request object -> its wire doc (the client half)."""
    if isinstance(req, SigRequest):
        return {"kind": "sig", "domain": req.domain,
                "public_key": _hex(req.public_key),
                "msg": _hex(req.msg), "signature": _hex(req.signature)}
    if isinstance(req, VrfRequest):
        return {"kind": "vrf", "public_key": _hex(req.public_key),
                "alpha": _hex(req.alpha), "proof": _hex(req.proof)}
    if isinstance(req, MembershipRequest):
        return {"kind": "membership", "member": _hex(req.member),
                "root": _hex(req.root), "leaf_count": req.leaf_count,
                "proof": {"leaf_index": req.proof.leaf_index,
                          "nodes": [_hex(n) for n in req.proof.nodes]}}
    if isinstance(req, PowRequest):
        return {"kind": "pow", "challenge": _hex(req.challenge),
                "node_id": _hex(req.node_id),
                "difficulty": _hex(req.difficulty), "nonce": req.nonce}
    if isinstance(req, PostRequest):
        it = req.item
        return {"kind": "post", "challenge": _hex(it.challenge),
                "node_id": _hex(it.node_id),
                "commitment": _hex(it.commitment),
                "scrypt_n": it.scrypt_n,
                "total_labels": it.total_labels,
                "proof": {"nonce": it.proof.nonce,
                          "indices": list(it.proof.indices),
                          "pow_nonce": it.proof.pow_nonce,
                          "k2": it.proof.k2}}
    raise ProtocolError(f"unknown request type {type(req).__name__}")
