"""CLI: boot a verifyd server.

    python -m spacemesh_tpu.verifyd [--listen 127.0.0.1:0]
        [--grpc-listen 127.0.0.1:0] [--max-clients N]
        [--max-pending N] [--rate R] [--burst B] [--workers N]
        [--max-batch N]

Prints one JSON line with the bound ports on stdout once serving, then
runs until SIGINT/SIGTERM; shutdown drains admitted work before the
sockets close (docs/VERIFYD.md).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import signal
import sys

from .server import VerifydServer


def _post_params(args):
    """POST proof params are CONSENSUS parameters: the server must
    verify with the same k1/k2/k3/pow-difficulty its clients prove
    under, or honest proofs fail. None = the mainnet defaults."""
    from ..post.prover import ProofParams

    defaults = ProofParams()
    if (args.post_k1 is None and args.post_k2 is None
            and args.post_k3 is None
            and args.post_pow_difficulty is None):
        return None
    return ProofParams(
        k1=args.post_k1 if args.post_k1 is not None else defaults.k1,
        k2=args.post_k2 if args.post_k2 is not None else defaults.k2,
        k3=args.post_k3 if args.post_k3 is not None else defaults.k3,
        pow_difficulty=(bytes.fromhex(args.post_pow_difficulty)
                        if args.post_pow_difficulty is not None
                        else defaults.pow_difficulty))


async def serve(args) -> int:
    server = VerifydServer(
        listen=args.listen, grpc_listen=args.grpc_listen,
        max_clients=args.max_clients,
        max_pending_items=args.max_pending,
        default_rate=args.rate, default_burst=args.burst,
        workers=args.workers, max_batch=args.max_batch,
        post_params=_post_params(args),
        genesis_id=(bytes.fromhex(args.genesis_id)
                    if args.genesis_id is not None else None))
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:  # non-unix embedders
            pass
    try:
        port = await server.start()
        print(json.dumps({"listening": f"{server.host}:{port}",
                          "grpc": server.grpc_port}), flush=True)
        await stop.wait()
    finally:
        await server.close()
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m spacemesh_tpu.verifyd",
        description="verification-as-a-service front-end "
                    "(docs/VERIFYD.md)")
    ap.add_argument("--listen", default="127.0.0.1:0",
                    help="HTTP bind host:port (port 0 picks)")
    ap.add_argument("--grpc-listen", default=None,
                    help="also serve gRPC on host:port (default: off)")
    ap.add_argument("--max-clients", type=int, default=64)
    ap.add_argument("--max-pending", type=int, default=1 << 15,
                    help="global admitted-items bound")
    ap.add_argument("--rate", type=float, default=5000.0,
                    help="default per-client weighted items/s")
    ap.add_argument("--burst", type=float, default=10000.0,
                    help="default per-client token-bucket depth")
    ap.add_argument("--workers", type=int, default=4,
                    help="scheduler worker threads")
    ap.add_argument("--max-batch", type=int, default=256,
                    help="farm device batch cap")
    ap.add_argument("--post-k1", type=int, default=None,
                    help="POST k1 (default: mainnet)")
    ap.add_argument("--post-k2", type=int, default=None,
                    help="POST k2 (default: mainnet)")
    ap.add_argument("--post-k3", type=int, default=None,
                    help="POST k3 spot-check count (default: mainnet)")
    ap.add_argument("--post-pow-difficulty", default=None,
                    help="POST k2pow difficulty, 64 hex chars "
                         "(default: mainnet)")
    ap.add_argument("--genesis-id", default=None,
                    help="network genesis id, hex: signatures are made "
                         "over genesis_id||domain||msg, so a replica "
                         "must verify under its clients' network "
                         "prefix (default: empty prefix)")
    args = ap.parse_args(argv)
    try:
        return asyncio.run(serve(args))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
