"""verifyd client library: the cookbook's reference implementation.

:class:`VerifydClient` talks JSON-over-HTTP to a verifyd server
(aiohttp; one session reused across calls), re-raising the server's
typed shed docs as :class:`~.service.Shed` so an embedding node can
react to ``reason``/``retry_after_s`` instead of parsing bodies.  The
gRPC transport carries the identical docs — ``grpc_verify`` shows the
two-line difference for callers who prefer HTTP/2 framing.

``serial_verify`` is the one-item-at-a-time driver: it exists as the
honest BASELINE the bench's open-loop load is compared against (the
pre-service shape: every remote check pays a full round trip and a
solo batch), and as the simplest possible integration example.
"""

from __future__ import annotations

import asyncio
import dataclasses

from ..obs.remediate import backoff_delay
from . import protocol
from .service import Shed

# shed reasons worth retrying: the condition clears on its own (tokens
# refill, the queue drains). A config/lifecycle shed (unregistered,
# registry_full, shutting_down) never clears by waiting — re-raise it
# immediately, whatever the retry policy says.
RETRYABLE_SHEDS = frozenset({protocol.SHED_RATE, protocol.SHED_OVERLOAD,
                             protocol.SHED_QUEUE_FULL,
                             protocol.SHED_DEADLINE})

# lifecycle sheds that never clear by waiting on THIS replica but may
# clear instantly on ANOTHER: the server attaches a ``replica_hint``
# (Retry-After in space — docs/VERIFYD.md) and a fleet-aware client
# hops to it instead of backing off against the dead replica.
HOP_SHEDS = frozenset({protocol.SHED_REGISTRY_FULL,
                       protocol.SHED_SHUTTING_DOWN})


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded shed-retry budget with capped, seeded-jitter backoff.

    The wait for attempt ``k`` is :func:`~..obs.remediate.backoff_delay`
    — the SAME rule that times the failover breaker's half-open probes,
    so the cookbook client and the breaker cannot drift — floored at
    the server's ``retry_after_s`` hint.  A hint beyond ``cap_s`` means
    the condition will not clear within this client's patience: the
    shed re-raises immediately instead of sleeping toward a foregone
    conclusion.  ``max_attempts`` counts verify attempts, not waits
    (``max_attempts=1`` disables retrying).
    """

    max_attempts: int = 3
    base_s: float = 0.05
    cap_s: float = 2.0
    seed: int = 0

    def should_retry(self, exc: Shed, attempt: int) -> bool:
        if attempt + 1 >= max(int(self.max_attempts), 1):
            return False
        if exc.reason not in RETRYABLE_SHEDS:
            return False
        return not (exc.retry_after_s is not None
                    and exc.retry_after_s > self.cap_s)

    def delay(self, exc: Shed, attempt: int) -> float:
        return backoff_delay(attempt, base_s=self.base_s,
                             cap_s=self.cap_s,
                             retry_after_s=exc.retry_after_s,
                             seed=self.seed)


class VerifydClient:
    """HTTP client for one verifyd endpoint.

    Lifecycle: construct -> ``register()`` -> ``verify(...)`` ->
    ``aclose()`` in a ``finally`` (unregisters by default, so the
    server's per-client series and tenant state go away with us —
    the lifecycle spacecheck SC004 pins on package code).

    ``retry`` honors the server's typed-shed ``retry_after_s``: a
    retryable shed waits out a capped seeded-jitter backoff (floored at
    the hint) and re-verifies, up to the policy's attempt budget; pass
    ``retry=None`` for the raw one-shot behavior.  ``sleep`` injects
    the wait primitive so tests assert the exact delays with zero real
    sleeping.
    """

    def __init__(self, base_url: str, client_id: str, *,
                 session=None, unregister_on_close: bool = True,
                 retry: RetryPolicy | None = RetryPolicy(),
                 fallback_urls=(), sleep=asyncio.sleep):
        self.base_url = base_url.rstrip("/")
        self.client_id = str(client_id)
        self._session = session
        self._own_session = session is None
        self._unregister_on_close = unregister_on_close
        self._registered = False
        self.retry = retry
        self.fallback_urls = tuple(u.rstrip("/") for u in fallback_urls)
        self._register_kwargs: dict = {}
        self._sleep = sleep

    async def _sess(self):
        if self._session is None:
            import aiohttp

            self._session = aiohttp.ClientSession()
        return self._session

    async def _post(self, path: str, body: dict) -> tuple[int, dict]:
        sess = await self._sess()
        async with sess.post(self.base_url + path, json=body) as resp:
            if resp.content_type == "application/json":
                return resp.status, await resp.json()
            return resp.status, {"status": "ERROR",
                                 "error": await resp.text()}

    @staticmethod
    def _raise_typed(doc: dict) -> None:
        if doc.get("status") == "SHED":
            raise Shed(doc.get("reason", "unknown"),
                       doc.get("detail", ""), doc.get("retry_after_s"),
                       replica_hint=doc.get("replica_hint"))
        if doc.get("status") == "ERROR":
            raise protocol.ProtocolError(doc.get("error", "bad request"))

    async def register(self, **kwargs) -> dict:
        """Register this client id (weight/rate/burst/max_queued/
        max_inflight keywords forward to the server)."""
        self._register_kwargs = dict(kwargs)
        status, doc = await self._post(
            "/v1/client/register", {"client": self.client_id, **kwargs})
        self._raise_typed(doc)
        if status != 200:
            raise protocol.ProtocolError(f"register failed: {doc}")
        self._registered = True
        return doc

    async def unregister(self) -> None:
        self._registered = False
        await self._post("/v1/client/unregister",
                         {"client": self.client_id})

    def _next_replica(self, hint: str | None,
                      tried: set[str]) -> str | None:
        """Next untried replica URL: the server's hint first, then this
        client's configured ring of fallbacks, each at most once."""
        candidates = ([hint] if hint else []) + list(self.fallback_urls)
        for url in candidates:
            url = str(url).rstrip("/")
            if url and url not in tried:
                return url
        return None

    async def _hop(self, url: str) -> None:
        """Re-home to ``url``: re-register there (same knobs as the
        original registration) so the next verify lands registered."""
        self.base_url = url
        self._registered = False
        await self.register(**self._register_kwargs)

    async def verify(self, reqs: list, *, lane: str = "gossip",
                     deadline_s: float | None = None) -> list[bool]:
        """Verify a batch of farm request objects; raises the server's
        typed Shed on rejection (after the retry policy's budget of
        ``retry_after_s``-honoring backoff waits, when one is set).

        A ``registry_full``/``shutting_down`` shed carrying a
        ``replica_hint`` (or arriving when ``fallback_urls`` names other
        fleet replicas) does NOT back off: the client re-registers on
        the hinted/next replica and retries immediately — waiting out a
        replica that is full or dying is time spent toward a foregone
        conclusion.  Each replica is hopped to at most once per call.
        """
        attempt = 0
        tried = {self.base_url}
        while True:
            try:
                return await self._verify_once(reqs, lane=lane,
                                               deadline_s=deadline_s)
            except Shed as e:
                exc = e
                if exc.reason in HOP_SHEDS:
                    hopped = False
                    nxt = self._next_replica(exc.replica_hint, tried)
                    while nxt is not None:
                        tried.add(nxt)
                        try:
                            await self._hop(nxt)
                            hopped = True
                            break
                        except Shed as e2:  # hop target shed us too:
                            exc = e2        # chase ITS hint next
                            nxt = self._next_replica(
                                e2.replica_hint, tried)
                    if hopped:
                        continue    # no sleep, no attempt consumed
                if self.retry is None \
                        or not self.retry.should_retry(exc, attempt):
                    raise exc
                await self._sleep(self.retry.delay(exc, attempt))
                attempt += 1

    async def _verify_once(self, reqs: list, *, lane: str,
                           deadline_s: float | None) -> list[bool]:
        body = {"client": self.client_id, "lane": lane,
                "items": [protocol.request_to_doc(r) for r in reqs]}
        if deadline_s is not None:
            body["deadline_s"] = deadline_s
        _status, doc = await self._post("/v1/verify", body)
        self._raise_typed(doc)
        verdicts = doc.get("verdicts")
        if doc.get("status") != "OK" or not isinstance(verdicts, list):
            raise protocol.ProtocolError(f"verify failed: {doc}")
        return [bool(v) for v in verdicts]

    async def serial_verify(self, reqs: list, *,
                            lane: str = "gossip") -> list[bool]:
        """One item per request, awaited one at a time — the serial
        baseline shape (bench.py compares open-loop load against this).
        """
        out: list[bool] = []
        for r in reqs:
            out.extend(await self.verify([r], lane=lane))
        return out

    async def stats(self) -> dict:
        sess = await self._sess()
        async with sess.get(self.base_url + "/v1/stats") as resp:
            return await resp.json()

    async def aclose(self) -> None:
        try:
            if self._registered and self._unregister_on_close:
                try:
                    await self.unregister()
                except Exception:  # noqa: BLE001 — best-effort: a client
                    # closing BECAUSE the server died must not raise out
                    # of the caller's finally; the server's own client
                    # registry bound (max_clients) is the backstop
                    pass
        finally:
            if self._own_session and self._session is not None:
                await self._session.close()
                self._session = None


async def grpc_verify(target: str, client_id: str, reqs: list, *,
                      lane: str = "gossip",
                      deadline_s: float | None = None) -> list[bool]:
    """One verify call over the gRPC surface (same docs as HTTP; see
    server.py).  ``target`` is "host:port" of the gRPC listener."""
    import json

    import grpc

    body = {"client": str(client_id), "lane": lane,
            "items": [protocol.request_to_doc(r) for r in reqs]}
    if deadline_s is not None:
        body["deadline_s"] = deadline_s
    async with grpc.aio.insecure_channel(target) as channel:
        call = channel.unary_unary(
            "/spacemesh.verifyd.Verifyd/Verify",
            request_serializer=lambda d: json.dumps(d).encode(),
            response_deserializer=lambda b: json.loads(b or b"{}"))
        doc = await call(body)
    VerifydClient._raise_typed(doc)
    if doc.get("status") != "OK":
        raise protocol.ProtocolError(f"verify failed: {doc}")
    return [bool(v) for v in doc.get("verdicts", [])]


def run_serial_baseline(base_url: str, client_id: str, reqs: list,
                        *, lane: str = "gossip") -> list[bool]:
    """Synchronous convenience wrapper (bench.py, CLI smoke): register,
    verify one item at a time, unregister."""

    async def go():
        client = VerifydClient(base_url, client_id)
        try:
            await client.register()
            return await client.serial_verify(reqs, lane=lane)
        finally:
            await client.aclose()

    return asyncio.run(go())
