"""verifyd client library: the cookbook's reference implementation.

:class:`VerifydClient` talks JSON-over-HTTP to a verifyd server
(aiohttp; one session reused across calls), re-raising the server's
typed shed docs as :class:`~.service.Shed` so an embedding node can
react to ``reason``/``retry_after_s`` instead of parsing bodies.  The
gRPC transport carries the identical docs — ``grpc_verify`` shows the
two-line difference for callers who prefer HTTP/2 framing.

``serial_verify`` is the one-item-at-a-time driver: it exists as the
honest BASELINE the bench's open-loop load is compared against (the
pre-service shape: every remote check pays a full round trip and a
solo batch), and as the simplest possible integration example.
"""

from __future__ import annotations

import asyncio

from . import protocol
from .service import Shed


class VerifydClient:
    """HTTP client for one verifyd endpoint.

    Lifecycle: construct -> ``register()`` -> ``verify(...)`` ->
    ``aclose()`` in a ``finally`` (unregisters by default, so the
    server's per-client series and tenant state go away with us —
    the lifecycle spacecheck SC004 pins on package code).
    """

    def __init__(self, base_url: str, client_id: str, *,
                 session=None, unregister_on_close: bool = True):
        self.base_url = base_url.rstrip("/")
        self.client_id = str(client_id)
        self._session = session
        self._own_session = session is None
        self._unregister_on_close = unregister_on_close
        self._registered = False

    async def _sess(self):
        if self._session is None:
            import aiohttp

            self._session = aiohttp.ClientSession()
        return self._session

    async def _post(self, path: str, body: dict) -> tuple[int, dict]:
        sess = await self._sess()
        async with sess.post(self.base_url + path, json=body) as resp:
            if resp.content_type == "application/json":
                return resp.status, await resp.json()
            return resp.status, {"status": "ERROR",
                                 "error": await resp.text()}

    @staticmethod
    def _raise_typed(doc: dict) -> None:
        if doc.get("status") == "SHED":
            raise Shed(doc.get("reason", "unknown"),
                       doc.get("detail", ""), doc.get("retry_after_s"))
        if doc.get("status") == "ERROR":
            raise protocol.ProtocolError(doc.get("error", "bad request"))

    async def register(self, **kwargs) -> dict:
        """Register this client id (weight/rate/burst/max_queued/
        max_inflight keywords forward to the server)."""
        status, doc = await self._post(
            "/v1/client/register", {"client": self.client_id, **kwargs})
        self._raise_typed(doc)
        if status != 200:
            raise protocol.ProtocolError(f"register failed: {doc}")
        self._registered = True
        return doc

    async def unregister(self) -> None:
        self._registered = False
        await self._post("/v1/client/unregister",
                         {"client": self.client_id})

    async def verify(self, reqs: list, *, lane: str = "gossip",
                     deadline_s: float | None = None) -> list[bool]:
        """Verify a batch of farm request objects; raises the server's
        typed Shed on rejection."""
        body = {"client": self.client_id, "lane": lane,
                "items": [protocol.request_to_doc(r) for r in reqs]}
        if deadline_s is not None:
            body["deadline_s"] = deadline_s
        _status, doc = await self._post("/v1/verify", body)
        self._raise_typed(doc)
        verdicts = doc.get("verdicts")
        if doc.get("status") != "OK" or not isinstance(verdicts, list):
            raise protocol.ProtocolError(f"verify failed: {doc}")
        return [bool(v) for v in verdicts]

    async def serial_verify(self, reqs: list, *,
                            lane: str = "gossip") -> list[bool]:
        """One item per request, awaited one at a time — the serial
        baseline shape (bench.py compares open-loop load against this).
        """
        out: list[bool] = []
        for r in reqs:
            out.extend(await self.verify([r], lane=lane))
        return out

    async def stats(self) -> dict:
        sess = await self._sess()
        async with sess.get(self.base_url + "/v1/stats") as resp:
            return await resp.json()

    async def aclose(self) -> None:
        try:
            if self._registered and self._unregister_on_close:
                await self.unregister()
        finally:
            if self._own_session and self._session is not None:
                await self._session.close()
                self._session = None


async def grpc_verify(target: str, client_id: str, reqs: list, *,
                      lane: str = "gossip",
                      deadline_s: float | None = None) -> list[bool]:
    """One verify call over the gRPC surface (same docs as HTTP; see
    server.py).  ``target`` is "host:port" of the gRPC listener."""
    import json

    import grpc

    body = {"client": str(client_id), "lane": lane,
            "items": [protocol.request_to_doc(r) for r in reqs]}
    if deadline_s is not None:
        body["deadline_s"] = deadline_s
    async with grpc.aio.insecure_channel(target) as channel:
        call = channel.unary_unary(
            "/spacemesh.verifyd.Verifyd/Verify",
            request_serializer=lambda d: json.dumps(d).encode(),
            response_deserializer=lambda b: json.loads(b or b"{}"))
        doc = await call(body)
    VerifydClient._raise_typed(doc)
    if doc.get("status") != "OK":
        raise protocol.ProtocolError(f"verify failed: {doc}")
    return [bool(v) for v in doc.get("verdicts", [])]


def run_serial_baseline(base_url: str, client_id: str, reqs: list,
                        *, lane: str = "gossip") -> list[bool]:
    """Synchronous convenience wrapper (bench.py, CLI smoke): register,
    verify one item at a time, unregister."""

    async def go():
        client = VerifydClient(base_url, client_id)
        try:
            await client.register()
            return await client.serial_verify(reqs, lane=lane)
        finally:
            await client.aclose()

    return asyncio.run(go())
