"""The verifyd failover client: remote service first, local farm always.

ROADMAP #3 named the residual this module closes: "a node-side
auto-failover client — fall back to the local farm when the service
sheds — would close the operator loop."  :class:`FailoverVerifier` is
that client.  It exposes the farm's own submission surface
(``await submit(req, lane) -> bool`` plus a batch form), so every
handler seam that takes ``farm=`` can take the failover verifier
instead (node/app.py wires it behind ``SPACEMESH_VERIFYD_URL``):

* **Remote path** — batches go to a verifyd endpoint (any object with
  ``async verify(reqs, lane=..., deadline_s=...)``: the cookbook
  :class:`~.client.VerifydClient` in production, an in-process
  transport in the sim).  Verdicts are bit-identical to the farm's by
  the verifyd contract (admission is scheduling, never semantics).
* **Breaker** — typed sheds, transport errors and deadline misses trip
  a :class:`~..obs.remediate.CircuitBreaker`; once open, requests go
  STRAIGHT to the local farm without re-paying the failing round trip.
  A shed's ``retry_after_s`` floors the half-open probe timing (the
  shared :func:`~..obs.remediate.backoff_delay` rule), so a service
  that said "come back in 30s" is probed then, not sooner.
* **Local path** — the node's in-process farm (verify/farm.py) carries
  the load during the outage; when a half-open probe finds the service
  back, traffic fails back to remote.

Every routing decision is visible: ``failover_requests_total
{path,lane}``, the ``failover_verify_seconds{path,lane}`` latency
histogram (the BLOCK-lane SLO signal that must stay green THROUGH an
outage — the verifyd-outage sim scenario asserts it), breaker state on
``/debug/remediation``, and an optional observer callback the sim uses
to build its replay-stable event digest.
"""

from __future__ import annotations

import asyncio
import time
from typing import Callable, Optional

from ..obs import remediate as remediate_mod
from ..utils import logging as slog
from ..utils import metrics, tracing
from ..verify.farm import Lane
from .service import Shed

_log = slog.get("failover")

# shed reasons that mean "this request is malformed / this client is
# misconfigured", not "the service is unhealthy": they do NOT trip the
# breaker (failing over would just re-verify locally forever while the
# real bug — an unregistered client id — goes unnoticed) but the single
# request still falls back to the farm for an answer
_NON_TRIPPING_SHEDS = frozenset({"unregistered", "registry_full"})

PATH_REMOTE = "remote"
PATH_LOCAL = "local"
PATH_LOCAL_FASTFAIL = "local_fastfail"  # breaker open: no remote attempt


class FailoverVerifier:
    """Remote verifyd with transparent local-farm fallback.

    Lifecycle: construct → :meth:`start` (registers the breaker on the
    global registry) → ``submit``/``verify_batch`` → :meth:`aclose`
    (unregisters the breaker, closes an owned remote client) — SC004
    pairs start/close like every other long-lived component.
    """

    def __init__(self, *, remote, farm,
                 breaker: remediate_mod.CircuitBreaker | None = None,
                 component: str = "verifyd.remote",
                 deadline_s: float | None = None,
                 own_remote: bool = False,
                 bus=None,
                 observer: Optional[Callable[..., None]] = None,
                 time_source: Callable[[], float] = time.monotonic):
        self.remote = remote
        self.farm = farm
        self.component = component
        self.deadline_s = deadline_s
        self._own_remote = own_remote
        self.bus = bus
        self.observer = observer
        self._now = time_source
        self.breaker = breaker if breaker is not None else \
            remediate_mod.CircuitBreaker(
                component, failure_budget=3, window_s=60.0,
                cooldown_s=5.0, cooldown_cap_s=120.0,
                time_source=time_source)
        self._registered = False
        self._remote_registered = False
        self.stats = {"remote_ok": 0, "remote_failed": 0,
                      "local": 0, "local_fastfail": 0,
                      "remote_attempts": 0, "failbacks": 0}

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        """Register the breaker (idempotent)."""
        if not self._registered:
            remediate_mod.BREAKERS.register(self.breaker)
            self._registered = True

    async def aclose(self) -> None:
        self.shutdown()
        if self._own_remote:
            aclose = getattr(self.remote, "aclose", None)
            if aclose is not None:
                await aclose()

    def shutdown(self) -> None:
        """Synchronous teardown half (App.close runs after the loop has
        exited): drop the breaker's registry entry and its per-component
        metric series; an owned remote client's transport needs the
        loop, so only :meth:`aclose` can close it."""
        if self._registered:
            remediate_mod.BREAKERS.unregister(self.breaker)
            self._registered = False

    # -- the farm-compatible surface -------------------------------------

    async def submit(self, req, lane: Lane = Lane.GOSSIP) -> bool:
        """One request, one verdict — the handler seam (same signature
        as ``VerificationFarm.submit``)."""
        return (await self.verify_batch([req], lane))[0]

    async def verify_batch(self, reqs: list,
                           lane: Lane = Lane.GOSSIP) -> list[bool]:
        """Verify a batch: remote while the breaker allows, local farm
        otherwise — ALWAYS an answer, never an error, for every failure
        mode the breaker models (a farm failure still propagates: when
        the local path is broken there is nothing left to fall back
        to)."""
        lane = Lane(lane)
        lname = lane.name.lower()
        t0 = self._now()
        attempted_remote = False
        if self.breaker.allow():
            attempted_remote = True
            was_probe = self.breaker.state == remediate_mod.HALF_OPEN
            self.stats["remote_attempts"] += 1
            try:
                async with tracing.span(
                        "failover.remote", {"lane": lname, "n": len(reqs)}
                        if tracing.is_enabled() else None):
                    verdicts = await self._remote_verify(reqs, lane)
            except Shed as e:
                if e.reason in _NON_TRIPPING_SHEDS:
                    # a config problem, not an outage: answer locally,
                    # force re-registration before the next remote
                    # attempt, and RELEASE a held probe slot — this
                    # outcome says nothing about the peer's health, and
                    # a probe that neither succeeds nor fails would
                    # wedge the breaker half-open forever
                    self._remote_registered = False
                    self.breaker.abort_probe()
                    _log.warning("verifyd shed %s (%s); serving locally "
                                 "without tripping the breaker",
                                 e.reason, e.detail)
                else:
                    self._trip(f"shed:{e.reason}",
                               retry_after_s=e.retry_after_s)
            except (asyncio.TimeoutError, TimeoutError) as e:
                self._trip(f"deadline:{e!r}")
            except Exception as e:  # noqa: BLE001 — any transport/protocol failure fails over
                self._trip(f"transport:{e!r}")
            except BaseException:
                # cancelled mid-attempt: no verdict either way — the
                # probe slot must not stay held
                self.breaker.abort_probe()
                raise
            else:
                self.stats["remote_ok"] += 1
                if was_probe:
                    self.stats["failbacks"] += 1
                    _log.info("verifyd probe ok: failing back to remote")
                self.breaker.record_success()
                return self._done(PATH_REMOTE, lname, t0, len(reqs),
                                  verdicts)
        # local farm fallback (or fast-fail: breaker open, no attempt)
        path = PATH_LOCAL if attempted_remote else PATH_LOCAL_FASTFAIL
        self.stats["local" if attempted_remote else "local_fastfail"] += 1
        async with tracing.span("failover.local",
                                {"lane": lname, "n": len(reqs),
                                 "fastfail": not attempted_remote}
                                if tracing.is_enabled() else None):
            verdicts = list(await asyncio.gather(
                *(self.farm.submit(r, lane) for r in reqs)))
        return self._done(path, lname, t0, len(reqs), verdicts)

    # -- internals -------------------------------------------------------

    async def _remote_verify(self, reqs: list, lane: Lane) -> list[bool]:
        if (not self._remote_registered
                and hasattr(self.remote, "register")):
            await self.remote.register()
            self._remote_registered = True
        lname = lane.name.lower()
        if self.deadline_s is not None:
            return await asyncio.wait_for(
                self.remote.verify(reqs, lane=lname,
                                   deadline_s=self.deadline_s),
                timeout=self.deadline_s)
        return await self.remote.verify(reqs, lane=lname)

    def _trip(self, why: str, retry_after_s: float | None = None) -> None:
        self.stats["remote_failed"] += 1
        before = self.breaker.state
        self.breaker.record_failure(retry_after_s=retry_after_s)
        after = self.breaker.state
        if self.observer is not None:
            self.observer("remote_failure", why=why, state=after)
        if after != before and after in (remediate_mod.OPEN,):
            _log.warning("verifyd remote unhealthy (%s): breaker open, "
                         "verifying on the local farm", why)
            if self.bus is not None:
                from ..node import events as events_mod

                self.bus.emit(events_mod.RemediationAction(
                    component=self.component, action="failover_remote",
                    outcome="ok", detail=why))
            metrics.remediation_actions.inc(
                component=self.component, action="failover_remote",
                outcome="ok")

    def _done(self, path: str, lname: str, t0: float, n: int,
              verdicts: list[bool]) -> list[bool]:
        metrics.failover_requests.inc(path=path, lane=lname)
        metrics.failover_verify_seconds.observe(
            max(self._now() - t0, 0.0), path=path, lane=lname)
        if self.observer is not None:
            self.observer("served", path=path, lane=lname, n=n)
        return verdicts

    def state_doc(self) -> dict:
        return {"component": self.component,
                "breaker": self.breaker.state_doc(),
                "stats": dict(self.stats)}
