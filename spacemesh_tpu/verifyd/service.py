"""verifyd core: per-client admission over the shared device runtime.

The in-process farm (verify/farm.py) batches ONE node's verification
work; this service verifies proofs for OTHER nodes (ROADMAP #3, the
second BASELINE.json metric).  Composition, front to back:

1. **Admission** (``verify``): per-client token buckets (weighted by
   item kind — a POST recompute costs more than a signature), a global
   pending-items bound with heaviest-client-first shedding (above a
   half-full high-water mark a client over its fair share sheds
   ``overload`` while lighter clients keep being admitted; the global
   bound sheds ``queue_full``), deadline-aware
   rejection (a request predicted to miss its deadline is shed NOW,
   not verified late), and a bounded client registry (``max_clients``).
   Every rejection is a typed :class:`Shed` — reason, detail,
   retry-after — never a silent drop.
2. **Fair share** (runtime/scheduler.py): each client is a tenant;
   every admitted request is one scheduler job, so stride fair share +
   EDF deadlines decide WHICH client's work reaches the device next,
   and the scheduler's ``max_queued`` quota is the per-client job bound
   (``quota`` sheds).
3. **Continuous batching** (verify/farm.py): released requests from
   all clients coalesce in the farm's per-kind batchers, sized by the
   measured-rate model in batchtune.py (speculative batch sizing: a
   partially-full batch dispatches the moment the marginal wait
   exceeds the predicted throughput gain).

Verdicts are bit-identical to inline verification — admission and
batching are scheduling, never semantics (the farm contract).  Tracing:
each admitted request opens a ``verifyd.request`` span; the drain
coroutine re-parents into it across the scheduler's worker-thread hop
(``verifyd.drain``), so a client request decomposes through
``farm.request`` into its ``farm.batch`` in one Perfetto timeline.

Shutdown (``aclose``) stops admission (``shutting_down`` sheds), drains
admitted work, then closes the scheduler and farm — zero stranded
client futures: anything undrained resolves with
:class:`VerifydClosed`.
"""

from __future__ import annotations

import asyncio
import time

from ..core.signing import EdVerifier
from ..runtime.scheduler import (
    QuotaExceeded,
    SchedulerClosed,
    TenantScheduler,
)
from ..utils import metrics, tracing
from ..verify import farm as farm_mod
from ..verify.farm import Lane, VerificationFarm
from . import batchtune, protocol

# token-bucket cost per item kind: rough relative backend cost, so one
# client's POST recomputes cannot crowd out another's signatures at the
# same nominal item rate
KIND_WEIGHTS = {"sig": 1.0, "vrf": 1.0, "membership": 1.0, "pow": 1.0,
                "post": 8.0}

DEFAULT_RATE = 5000.0       # items/s replenishment per client
DEFAULT_BURST = 10000.0     # bucket depth
DEFAULT_MAX_PENDING = 1 << 15


class VerifydClosed(RuntimeError):
    """The service shut down while (or before) the request was pending."""


class Shed(Exception):
    """Typed admission rejection (protocol.SHED_* reasons).

    Carries everything a well-behaved client needs to react: the
    ``reason``, a human ``detail``, ``retry_after_s`` when the
    condition is known to clear (token refill), and — for the
    lifecycle sheds a WAIT cannot clear (``registry_full``,
    ``shutting_down``) — an optional ``replica_hint``: the Retry-After
    analog in SPACE instead of time, naming a fleet peer worth trying
    instead of backing off against a full or draining replica.  The
    server surfaces it as a structured response body, the client
    library raises it — a shed is an ANSWER, never a dropped
    connection.
    """

    def __init__(self, reason: str, detail: str = "",
                 retry_after_s: float | None = None,
                 replica_hint: str | None = None):
        super().__init__(f"{reason}: {detail}" if detail else reason)
        self.reason = reason
        self.detail = detail
        self.retry_after_s = retry_after_s
        self.replica_hint = replica_hint

    def to_doc(self) -> dict:
        doc = {"status": "SHED", "reason": self.reason,
               "detail": self.detail,
               "retry_after_s": self.retry_after_s}
        if self.replica_hint is not None:
            doc["replica_hint"] = self.replica_hint
        return doc


class _TokenBucket:
    __slots__ = ("rate", "burst", "tokens", "updated")

    def __init__(self, rate: float, burst: float, now: float):
        self.rate = max(float(rate), 1e-9)
        self.burst = max(float(burst), 1.0)
        self.tokens = self.burst
        self.updated = now

    def take(self, cost: float, now: float) -> float:
        """0.0 when ``cost`` tokens were taken; else the seconds until
        enough tokens will have refilled (the retry-after hint)."""
        self.tokens = min(self.burst,
                          self.tokens + (now - self.updated) * self.rate)
        self.updated = now
        if self.tokens >= cost:
            self.tokens -= cost
            return 0.0
        return (cost - self.tokens) / self.rate


class _Client:
    __slots__ = ("id", "weight", "bucket", "pending", "admitted",
                 "shed", "registered_at")

    def __init__(self, cid: str, weight: float, bucket: _TokenBucket,
                 now: float):
        self.id = cid
        self.weight = weight
        self.bucket = bucket
        self.pending = 0        # admitted items not yet resolved
        self.admitted = 0       # items admitted, lifetime
        self.shed = 0           # requests shed, lifetime
        self.registered_at = now


class VerifydService:
    """The verification service behind the network front-end
    (module docstring; server.py owns the sockets).

    Lifecycle: construct -> ``await start()`` (binds the loop, registers
    the health watchdog, races+persists the batch model off-loop) ->
    ``register_client`` / ``verify`` -> ``await aclose()`` in a
    ``finally``.  ``time_source`` injects the admission clock (token
    buckets, deadlines, latency SLIs) for deterministic tests and the
    sim scenario.
    """

    def __init__(self, *, farm: VerificationFarm | None = None,
                 scheduler: TenantScheduler | None = None,
                 tuner: batchtune.BatchTuner | None = None,
                 max_clients: int = 64,
                 default_rate: float = DEFAULT_RATE,
                 default_burst: float = DEFAULT_BURST,
                 max_pending_items: int = DEFAULT_MAX_PENDING,
                 workers: int = 4,
                 default_max_queued: int = 64,
                 default_max_inflight: int = 4,
                 max_batch: int = 256,
                 post_params=None, post_seed: bytes | None = None,
                 genesis_id: bytes | None = None,
                 stall_deadline_s: float = 30.0,
                 drain_timeout_s: float = 60.0,
                 shard: str = "",
                 replica_hint: str | None = None,
                 time_source=time.monotonic):
        self._now = time_source
        # fleet shard name (verifyd/fleet.py): namespaces this
        # replica's tenant ids, per-client metric series, watchdog and
        # remediation hook, so N replicas can share one process — and
        # one registry, and one device scheduler — without colliding
        self.shard = str(shard)
        self._component = f"verifyd.{self.shard}" if self.shard \
            else "verifyd"
        # a fleet peer worth trying when THIS replica is full or
        # draining; rides in registry_full/shutting_down shed docs
        self.replica_hint = replica_hint
        self.max_clients = max(int(max_clients), 1)
        self.max_pending_items = max(int(max_pending_items), 1)
        self._default_rate = float(default_rate)
        self._default_burst = float(default_burst)
        self._drain_timeout_s = float(drain_timeout_s)
        self.tuner = tuner if tuner is not None else batchtune.BatchTuner(
            max_batch=max_batch)
        self._own_farm = farm is None
        # genesis_id is a CONSENSUS parameter like the POST params: the
        # node signs ``genesis_id || domain || msg``, so a replica that
        # verifies with a different prefix fails every honest signature
        if genesis_id is not None and farm is not None:
            raise ValueError("genesis_id only configures the service's "
                             "own farm; set ed_verifier on the injected "
                             "farm instead")
        self.farm = farm if farm is not None else VerificationFarm(
            ed_verifier=(None if genesis_id is None
                         else EdVerifier(prefix=bytes(genesis_id))),
            post_params=post_params, post_seed=post_seed,
            max_batch=max_batch, stall_deadline_s=stall_deadline_s,
            tuner=self.tuner)
        if tuner is None and self.tuner._backend is None:
            # the tuner races the farm's REAL backends (batchtune.py);
            # wired after construction because each needs the other
            self.tuner._backend = self.farm._run_backend
        self._own_scheduler = scheduler is None
        self.scheduler = scheduler if scheduler is not None else \
            TenantScheduler(workers=workers,
                            default_max_queued=default_max_queued,
                            default_max_inflight=default_max_inflight,
                            time_source=time_source)
        if self.shard:
            # shard-namespaced tenant ids (runtime/scheduler.py
            # ShardScheduler): fleet replicas sharing one device
            # runtime must not collide on client identity
            self.scheduler = self.scheduler.namespaced(self.shard)
        # client table + pending counters are LOOP-ONLY by contract:
        # admission runs on the event loop, scheduler quanta only touch
        # the farm (no lock needed; the sim scenario and tests drive one
        # loop)
        self.clients: dict[str, _Client] = {}
        self._pending_items = 0
        self._closed = False
        self._loop: asyncio.AbstractEventLoop | None = None
        self._rate_ewma = 0.0   # resolved items/s (deadline admission)
        self.stats = {
            "requests": 0, "admitted_items": 0, "resolved_items": 0,
            "shed": {}, "pending_peak": 0, "clients_peak": 0,
        }
        from ..obs import health as health_mod

        # liveness contract: while admitted items are pending, the
        # resolved counter must advance within the deadline — a wedged
        # farm backend or dead scheduler worker shows on /readyz
        self._watchdog = health_mod.Watchdog(
            self._component,
            progress=lambda: self.stats["resolved_items"],
            active=lambda: self._pending_items > 0,
            deadline_s=stall_deadline_s)

    # -- lifecycle ------------------------------------------------------

    async def start(self) -> None:
        """Bind the loop, register the health probe, and race+persist
        the batch-sizing model (off-loop; a warm host loads it)."""
        self._loop = asyncio.get_running_loop()
        from ..obs import health as health_mod
        from ..obs import remediate as remediate_mod

        health_mod.HEALTH.register(self._component, self._watchdog.check)
        # recovery hook beside the watchdog (obs/remediate.py): a
        # wedged-drain verdict resets the farm's lanes — stuck client
        # requests fail typed and re-submit instead of pinning the
        # service until an operator restart
        remediate_mod.ACTIONS.register(self._component,
                                       "reset_farm_lanes",
                                       self.farm.reset_lanes)
        await asyncio.to_thread(self.tuner.ensure_raced)

    async def aclose(self) -> None:
        """Graceful drain: stop admission, let admitted work finish,
        then close the scheduler and farm.  Idempotent; never strands a
        client future (undrained work resolves VerifydClosed)."""
        if self._closed:
            return
        self._closed = True
        try:
            # admitted jobs drain through scheduler workers + the farm
            # (both need this loop alive, hence to_thread)
            await asyncio.to_thread(self.scheduler.drain,
                                    self._drain_timeout_s)
            if self._own_scheduler:
                await asyncio.to_thread(self.scheduler.close)
            if self._own_farm:
                await self.farm.aclose()
        finally:
            from ..obs import health as health_mod
            from ..obs import remediate as remediate_mod

            health_mod.HEALTH.unregister(self._component,
                                         self._watchdog.check)
            remediate_mod.ACTIONS.unregister(
                self._component, "reset_farm_lanes",
                self.farm.reset_lanes)
            if self.shard:
                # this shard's service-level gauge series go with it
                metrics.verifyd_clients.remove(shard=self.shard)
                metrics.verifyd_pending.remove(shard=self.shard)

    # -- clients --------------------------------------------------------

    def _mcid(self, cid: str) -> str:
        """The client's metric-label identity: shard-namespaced so a
        client re-routed between fleet replicas in one process never
        shares (or clobbers) series across shards — and the OLD shard's
        unregister_client drops exactly its own series."""
        return f"{self.shard}/{cid}" if self.shard else cid

    def _gauge_clients(self) -> None:
        if self.shard:
            metrics.verifyd_clients.set(len(self.clients),
                                        shard=self.shard)
        else:
            metrics.verifyd_clients.set(len(self.clients))

    def _gauge_pending(self) -> None:
        if self.shard:
            metrics.verifyd_pending.set(self._pending_items,
                                        shard=self.shard)
        else:
            metrics.verifyd_pending.set(self._pending_items)

    def register_client(self, cid: str, *, weight: float | None = None,
                        rate: float | None = None,
                        burst: float | None = None,
                        max_queued: int | None = None,
                        max_inflight: int | None = None) -> dict:
        """Register (or re-configure) a client identity; pair with
        :meth:`unregister_client` when it disconnects (spacecheck SC004
        enforces the pairing on package code).  Raises a typed
        ``registry_full`` Shed at the ``max_clients`` bound — the knob
        that keeps per-client metric cardinality finite."""
        if self._closed:
            raise VerifydClosed("verifyd closed")
        cid = str(cid)
        c = self.clients.get(cid)
        now = self._now()
        if c is None:
            if len(self.clients) >= self.max_clients:
                metrics.verifyd_shed.inc(client=self._mcid("-"),
                                         reason=protocol.SHED_REGISTRY_FULL)
                raise Shed(protocol.SHED_REGISTRY_FULL,
                           f"{len(self.clients)} clients registered "
                           f">= max_clients {self.max_clients}",
                           replica_hint=self.replica_hint)
            self.scheduler.register_tenant(
                cid, weight=weight if weight is not None else 1.0,
                max_queued=max_queued, max_inflight=max_inflight)
            c = self.clients[cid] = _Client(
                cid, weight if weight is not None else 1.0,
                _TokenBucket(rate if rate is not None
                             else self._default_rate,
                             burst if burst is not None
                             else self._default_burst, now), now)
            self._gauge_clients()
            self.stats["clients_peak"] = max(self.stats["clients_peak"],
                                             len(self.clients))
        else:
            # re-registration is RECONFIG: every unspecified knob keeps
            # its value (a rate-only update must not silently reset the
            # client's fair-share weight)
            if weight is not None:
                c.weight = weight
            if rate is not None:
                c.bucket.rate = max(float(rate), 1e-9)
            if burst is not None:
                c.bucket.burst = max(float(burst), 1.0)
            self.scheduler.register_tenant(
                cid, weight=weight, max_queued=max_queued,
                max_inflight=max_inflight)
        return {"client": cid, "weight": c.weight,
                "rate": c.bucket.rate, "burst": c.bucket.burst,
                "clients": len(self.clients),
                "max_clients": self.max_clients}

    def unregister_client(self, cid: str) -> bool:
        """Drop a client: its queued scheduler jobs fail, and EVERY
        per-client metric series disappears from the scrape (the PR-10
        series-removal pattern — a gone identity must not pin registry
        entries; regression-tested with a client-id churn loop)."""
        c = self.clients.pop(str(cid), None)
        if c is None:
            return False
        self.scheduler.unregister_tenant(c.id)
        self._gauge_clients()
        mcid = self._mcid(c.id)
        metrics.verifyd_client_pending.remove(client=mcid)
        for inst in (metrics.verifyd_requests, metrics.verifyd_items,
                     metrics.verifyd_shed):
            inst.remove_matching(client=mcid)
        return True

    # -- admission ------------------------------------------------------

    def _shed(self, c: _Client | None, cid: str, reason: str,
              detail: str = "",
              retry_after_s: float | None = None) -> None:
        if c is not None:
            c.shed += 1
        self.stats["shed"][reason] = self.stats["shed"].get(reason, 0) + 1
        mcid = self._mcid(cid if c is not None else "-")
        metrics.verifyd_shed.inc(client=mcid, reason=reason)
        metrics.verifyd_requests.inc(client=mcid, outcome="shed")
        hint = self.replica_hint if reason in (
            protocol.SHED_SHUTTING_DOWN,
            protocol.SHED_REGISTRY_FULL) else None
        raise Shed(reason, detail, retry_after_s, replica_hint=hint)

    def estimated_wait_s(self) -> float:
        """Predicted queue wait for a newly admitted item: the pending
        backlog over the resolved-rate EWMA (0.0 while idle or before
        any resolution — admission never blocks on an unknown)."""
        if self._pending_items <= 0 or self._rate_ewma <= 0:
            return 0.0
        return self._pending_items / self._rate_ewma

    async def verify(self, client_id: str, reqs: list,
                     lane: Lane = Lane.GOSSIP,
                     deadline_s: float | None = None,
                     trace_parent: str | None = None) -> list[bool]:
        """Admit one request (a list of farm request objects) and await
        its verdicts.  Raises :class:`Shed` (typed) on rejection and
        :class:`VerifydClosed` when the service shuts down mid-flight.
        ``trace_parent`` is an opaque caller-side span link token
        (``tracing.link_token()``); merge_captures() resolves it into a
        cross-process parent edge on the ``verifyd.request`` span.
        """
        cid = str(client_id)
        self.stats["requests"] += 1
        if self._closed:
            self._shed(self.clients.get(cid), cid,
                       protocol.SHED_SHUTTING_DOWN, "service is draining")
        c = self.clients.get(cid)
        if c is None:
            self._shed(None, cid, protocol.SHED_UNREGISTERED,
                       f"client {cid!r} is not registered")
        if not reqs:
            metrics.verifyd_requests.inc(client=self._mcid(cid),
                                         outcome="ok")
            return []
        lane = Lane(lane)
        n = len(reqs)
        now = self._now()
        cost = sum(KIND_WEIGHTS.get(r.kind, 1.0) for r in reqs)
        retry = c.bucket.take(cost, now)
        if retry > 0:
            self._shed(c, cid, protocol.SHED_RATE,
                       f"rate limit: {cost:.0f} weighted items over "
                       f"budget", retry_after_s=retry)
        share = self.max_pending_items / max(len(self.clients), 1)
        if (self._pending_items + n > self.max_pending_items // 2
                and c.pending + n > share):
            # heaviest first, work-conserving: below the high-water
            # mark any client may use idle capacity, but once the
            # queue is half full a client above its fair share sheds —
            # so a flood from one identity caps at its share while
            # light clients keep being admitted up to the global bound
            self._shed(c, cid, protocol.SHED_OVERLOAD,
                       f"client holds {c.pending} of "
                       f"{self._pending_items} pending "
                       f"(fair share {share:.0f})",
                       retry_after_s=self.estimated_wait_s())
        if self._pending_items + n > self.max_pending_items:
            self._shed(c, cid, protocol.SHED_QUEUE_FULL,
                       f"{self._pending_items} items pending >= bound "
                       f"{self.max_pending_items}",
                       retry_after_s=self.estimated_wait_s())
        if deadline_s is not None:
            est = self.estimated_wait_s()
            if est > deadline_s:
                # shedding NOW beats verifying late: the caller can
                # retry elsewhere instead of burning device time on a
                # verdict it will discard
                self._shed(c, cid, protocol.SHED_DEADLINE,
                           f"predicted wait {est:.3f}s exceeds "
                           f"deadline {deadline_s:.3f}s",
                           retry_after_s=est)
        attrs = ({"client": cid, "lane": lane.name.lower(), "n": n}
                 if tracing.is_enabled() else None)
        if attrs is not None and trace_parent:
            attrs["link"] = trace_parent
        sp = tracing.span("verifyd.request", attrs)
        with sp:
            parent = sp.id if tracing.is_enabled() else None
            loop = asyncio.get_running_loop()
            self._loop = loop

            def quantum():
                # scheduler worker thread: release this request's items
                # into the farm (on the loop) and wait for verdicts —
                # the wall cost charges the client's fair-share vtime
                return asyncio.run_coroutine_threadsafe(
                    self._drain_into_farm(reqs, lane, parent),
                    loop).result()  # spacecheck: ok=SC002 sync method runs on a scheduler worker thread, not the loop

            try:
                handle = self.scheduler.submit_call(
                    cid, quantum, kind="verifyd", deadline_s=deadline_s)
            except QuotaExceeded as exc:
                self._shed(c, cid, protocol.SHED_QUOTA, str(exc),
                           retry_after_s=self.estimated_wait_s())
            except KeyError:
                self._shed(c, cid, protocol.SHED_UNREGISTERED,
                           f"client {cid!r} lost its tenant")
            except SchedulerClosed:
                raise VerifydClosed("scheduler closed") from None
            self._pending_items += n
            c.pending += n
            c.admitted += n
            self.stats["admitted_items"] += n
            self.stats["pending_peak"] = max(self.stats["pending_peak"],
                                             self._pending_items)
            self._gauge_pending()
            metrics.verifyd_client_pending.set(c.pending,
                                               client=self._mcid(cid))
            t0 = self._now()
            settled = False

            def settle() -> None:
                # pending-item accounting releases when the WORK is
                # done, not when the awaiter goes away — a cancelled
                # await (client disconnect) leaves the quantum running
                # and its items still occupying the farm, and freeing
                # their admission slots early would let a
                # disconnect-churn loop bypass the overload shed
                nonlocal settled
                if settled:
                    return
                settled = True
                dt = self._now() - t0
                self._pending_items -= n
                self.stats["resolved_items"] += n
                if dt > 0:
                    rate = n / dt
                    self._rate_ewma = rate if self._rate_ewma <= 0 else (
                        0.2 * rate + 0.8 * self._rate_ewma)
                self._gauge_pending()
                live = self.clients.get(cid)
                if live is c:
                    c.pending -= n
                    metrics.verifyd_client_pending.set(
                        c.pending, client=self._mcid(cid))

            try:
                verdicts = await asyncio.wrap_future(handle.future)
            except (SchedulerClosed, farm_mod.FarmClosed) as exc:
                settle()
                raise VerifydClosed(str(exc)) from None
            except asyncio.CancelledError:
                handle.cancel()  # stops it if still queued

                def on_done(_f) -> None:
                    try:  # worker thread -> loop (state is loop-only)
                        loop.call_soon_threadsafe(settle)
                    except RuntimeError:  # loop gone at teardown
                        pass

                handle.future.add_done_callback(on_done)
                raise
            except BaseException:
                settle()
                raise
            settle()
            metrics.verifyd_request_seconds.observe(
                max(self._now() - t0, 0.0), lane=lane.name.lower())
            metrics.verifyd_requests.inc(client=self._mcid(cid),
                                         outcome="ok")
            kinds: dict[str, int] = {}
            for r in reqs:
                kinds[r.kind] = kinds.get(r.kind, 0) + 1
            for kind, count in kinds.items():
                metrics.verifyd_items.inc(count, client=self._mcid(cid),
                                          kind=kind)
            return verdicts

    async def _drain_into_farm(self, reqs: list, lane: Lane,
                               parent) -> list[bool]:
        # run_coroutine_threadsafe copies the WORKER thread's context,
        # so the request span must be re-established explicitly — the
        # farm.request spans below then parent into it, and their
        # farm.batch linkage closes the client->batch causal chain
        async with tracing.span("verifyd.drain",
                                {"n": len(reqs),
                                 "lane": lane.name.lower()}
                                if tracing.is_enabled() else None,
                                parent=parent):
            return list(await asyncio.gather(
                *(self.farm.submit(r, lane) for r in reqs)))

    # -- introspection --------------------------------------------------

    def stats_doc(self) -> dict:
        return {
            "shard": self.shard,
            "clients": len(self.clients),
            "max_clients": self.max_clients,
            "pending_items": self._pending_items,
            "max_pending_items": self.max_pending_items,
            "estimated_wait_s": round(self.estimated_wait_s(), 6),
            "resolved_items_per_sec": round(self._rate_ewma, 1),
            "requests": self.stats["requests"],
            "admitted_items": self.stats["admitted_items"],
            "resolved_items": self.stats["resolved_items"],
            "pending_peak": self.stats["pending_peak"],
            "shed": dict(self.stats["shed"]),
            "farm": {k: v for k, v in self.farm.stats.items()
                     if isinstance(v, (int, float))},
            "tuner": {
                "stats": dict(self.tuner.stats),
                "targets": {k: self.tuner.target_batch(k)
                            for k in sorted(KIND_WEIGHTS)},
            },
            "closed": self._closed,
        }
