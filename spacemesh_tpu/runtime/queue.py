"""Priority-lane admission primitives for async device-job queues.

Extracted from verify/farm.py, where the per-lane backpressure waiter
logic grew a review-fix bug (a waiter cancelled after ``_release_lane``
resolved it silently lost the freed slot — PR 2 review fixes) exactly
because every queue re-implemented it.  The farm now consumes these;
new admission surfaces (the multi-tenant scheduler's async facade, the
planned verification-as-a-service front-end) get the fixed semantics
for free instead of a fresh copy to re-break.

Two pieces:

* :class:`LaneGroup` — the per-lane global accounting one admission
  domain shares across request kinds: counts, bounds, backpressure
  waiters (with the cancellation slot-handoff), in-flight dedup map,
  and fail-all on close.  Bound to one event loop; rebinding drops
  state (the embedder-runs-asyncio.run()-twice contract).
* :class:`KindLanes` — one request kind's per-lane FIFO deques with
  highest-priority-first draining, earliest-deadline lookup, and
  promote-on-dedup removal.
"""

from __future__ import annotations

import asyncio
from collections import deque
from typing import Callable, Optional

from ..utils import sanitize


class QueueClosed(RuntimeError):
    """The admission queue was shut down while the request was pending."""


class LaneGroup:
    """Shared per-lane admission accounting for one queue domain.

    ``lanes``    the IntEnum lane type (drained in ascending order).
    ``bounds``   per-lane queued-request caps; a full lane blocks its
                 own submitters in :meth:`acquire`.
    ``make_exc`` exception factory for closed-queue failures (the farm
                 raises its own FarmClosed subtype).
    ``on_depth`` ``(lane, depth)`` hook for the owner's queue gauges.
    """

    def __init__(self, lanes, bounds: dict,
                 make_exc: Callable[[], Exception] = QueueClosed,
                 on_depth: Optional[Callable] = None):
        self.lanes = lanes
        self.bounds = dict(bounds)
        self._make_exc = make_exc
        self._on_depth = on_depth
        self.closed = False
        self._loop: asyncio.AbstractEventLoop | None = None
        # lane counts/waiters/dedup are loop-affine BY CONTRACT (no
        # lock anywhere in this class); the owner-write declaration is
        # the runtime check that no worker thread ever mutates them
        self._shared = sanitize.SharedField("runtime.queue.lanegroup",
                                            mode="owner-write")
        self._count: dict = {lane: 0 for lane in lanes}
        self._waiters: dict = {lane: deque() for lane in lanes}
        self.dedup: dict = {}

    # -- lifecycle -----------------------------------------------------

    def bind(self, loop: asyncio.AbstractEventLoop) -> bool:
        """Bind to ``loop``; returns True when state was (re)created —
        pending entries from a dead loop are unrecoverable and dropped,
        and the owner must drop its per-kind deques too."""
        if self._loop is loop:
            return False
        self._loop = loop
        self._count = {lane: 0 for lane in self.lanes}
        self._waiters = {lane: deque() for lane in self.lanes}
        self.dedup = {}
        # a rebind is a sanctioned ownership handoff: the new loop may
        # run on a different thread, which must not trip the owner-write
        # check against the dead loop's thread id
        self._shared.reset()
        return True

    def fail_waiters(self) -> None:
        """Fail every backpressure waiter with the closed exception (the
        bound loop must still be alive)."""
        for waiters in self._waiters.values():
            while waiters:
                w = waiters.popleft()
                if not w.done():
                    w.set_exception(self._make_exc())

    # -- accounting ----------------------------------------------------

    def count(self, lane) -> int:
        return self._count[lane]

    def total(self) -> int:
        return sum(self._count.values())

    def add(self, lane) -> int:
        """Unconditional occupancy increment (post-acquire, or a dedup
        promote that already holds a slot elsewhere)."""
        self._shared.touch()
        self._count[lane] += 1
        depth = self._count[lane]
        if self._on_depth is not None:
            self._on_depth(lane, depth)
        return depth

    def release(self, lane) -> None:
        """Free one slot and hand it to the next live waiter."""
        self._shared.touch()
        self._count[lane] -= 1
        if self._on_depth is not None:
            self._on_depth(lane, self._count[lane])
        self.wake_next(lane)

    def wake_next(self, lane) -> None:
        """Grant a freed lane slot to the next live backpressure waiter
        (woken submitters re-check the bound in acquire's while loop)."""
        waiters = self._waiters[lane]
        while waiters and self._count[lane] < self.bounds[lane]:
            w = waiters.popleft()
            if not w.done():
                w.set_result(None)
                return

    async def acquire(self, lane) -> None:
        """Wait until ``lane`` has room (its bound blocks only its own
        submitters).  Cancellation is slot-safe: a waiter cancelled
        after :meth:`release` resolved it hands the freed slot to the
        next waiter instead of silently losing it — the review-fix
        semantics this module exists to keep in ONE place."""
        while self._count[lane] >= self.bounds[lane]:
            waiter = self._loop.create_future()
            self._waiters[lane].append(waiter)
            try:
                await waiter
            except asyncio.CancelledError:
                try:
                    self._waiters[lane].remove(waiter)
                except ValueError:
                    # already popped by release(): it granted us a slot
                    # we will never use — hand the wakeup to the next
                    # waiter, or the freed slot is silently lost and
                    # survivors can park forever on a drained lane
                    if waiter.done() and not waiter.cancelled():
                        self.wake_next(lane)
                raise
            if self.closed:
                raise self._make_exc()


class KindLanes:
    """One request kind's per-lane FIFO deques over a :class:`LaneGroup`.

    Entries are opaque; they only need ``lane`` and ``deadline``
    attributes (the farm's pending-request records).  Draining order is
    ascending lane value — highest priority first.
    """

    def __init__(self, group: LaneGroup):
        self.group = group
        self.lanes: dict = {lane: deque() for lane in group.lanes}

    def append(self, entry) -> int:
        """Queue ``entry`` on its lane; returns the lane depth (the
        caller already holds an acquired slot)."""
        self.lanes[entry.lane].append(entry)
        return self.group.add(entry.lane)

    def remove(self, entry) -> bool:
        """Remove a still-queued entry (dedup promote); False once it
        was already taken into a batch.  Releases its lane slot."""
        try:
            self.lanes[entry.lane].remove(entry)
        except ValueError:
            return False
        self.group.release(entry.lane)
        return True

    def count(self) -> int:
        return sum(len(q) for q in self.lanes.values())

    def earliest_deadline(self) -> float:
        return min(q[0].deadline for q in self.lanes.values() if q)

    def take(self, limit: int) -> list:
        """Drain up to ``limit`` entries, highest-priority lanes first.
        Lane slots are NOT released here — the owner releases them as it
        accounts queue-wait per entry (farm._on_taken)."""
        batch: list = []
        for lane in self.group.lanes:
            q = self.lanes[lane]
            while q and len(batch) < limit:
                batch.append(q.popleft())
        return batch

    def drain_all(self) -> list:
        """Empty every lane (close path); slots are not released."""
        out: list = []
        for q in self.lanes.values():
            while q:
                out.append(q.popleft())
        return out
