"""Fair-share multi-tenant scheduling over the shared device runtime.

BASELINE.json's last config — "16 smeshers x 4 SU sharded across v5e-8"
— needs many identities served by ONE device set.  Per-job ownership
(one Initializer/Prover owning every device for the duration) leaves
the device idle in every host-side gap: session setup/teardown, ragged
tail batches, metadata saves, disk stalls.  The scheduler closes those
gaps by admitting work from every tenant into the same
submit -> batch -> dispatch -> retire engine (runtime/engine.py):

* **Per-tenant queues + fair share.**  Tenants register with a weight;
  quanta (a prove window, a verify batch, a k2pow search, a packed init
  dispatch's lane share) charge the tenant's virtual time by wall cost
  / weight.  The next quantum always goes to the runnable tenant with
  the LEAST virtual time — a flooding tenant cannot starve a light one
  (stride scheduling).
* **Deadline admission.**  A job submitted with ``deadline_s`` is
  lifted ahead of fair-share order once its deadline is within the
  admission slack (EDF among overdue jobs) — the farm's BLOCK-lane
  urgency generalized to whole jobs.
* **Quotas.**  Per-tenant ``max_queued`` (admission bound; submit
  raises :class:`QuotaExceeded`) and ``max_inflight`` (concurrent
  quanta cap) keep one identity from monopolizing the worker pool.
* **Cross-tenant init packing.**  Init jobs do not dispatch per tenant:
  a packer thread composes lanes from MANY tenants' jobs (fair-share
  order) into one fused per-lane-commitment label program
  (ops/scrypt.py supports (8, B) commitment words), keeps ``inflight``
  packs on the device via the engine, splits the fetched bytes back to
  each tenant's store and folds each tenant's VRF minimum on host
  (runtime/workloads.py fold_min_host — bit-identical to the device
  scan).  16 tiny sessions become a handful of full-bucket programs.
* **Gang-scheduled prove windows.**  One prove window (a whole disk
  pass: every nonce-group step chain of the window) runs as ONE
  quantum on one worker, gated by a ``gang_windows`` semaphore — its
  donated carry states live on device for the duration, so two prove
  windows never interleave their device state beyond the configured
  gang width.
* **Tenant labels everywhere.**  Every span and metric the runtime
  emits for scheduled work carries the tenant id
  (``runtime_tenant_*``, ``runtime.quantum``/``runtime.segment``
  spans), so a multi-tenant trace decomposes per identity.

The scheduler is thread-based and loop-free: embedders without asyncio
(bench, CLI tools, the grpc worker's executor) drive it directly.
"""

from __future__ import annotations

import concurrent.futures
import itertools
import threading
import time
import zlib
from collections import deque
from pathlib import Path

from ..utils import metrics, sanitize, tracing
from . import engine, workloads

_DEFAULT_PACK_LANES = 4096
_DEADLINE_SLACK_S = 0.05   # jobs due within this window jump fair share


class SchedulerClosed(RuntimeError):
    """The scheduler was closed while (or before) the job was pending."""


class QuotaExceeded(RuntimeError):
    """The tenant's max_queued admission bound rejected the submit."""


class JobHandle:
    """One submitted job: a concurrent future plus identity/job labels.

    Handles must be consumed: await :meth:`result` (or :meth:`wait`) on
    every path, or :meth:`cancel` in a ``finally`` — the spacecheck
    SC004 pairing rule enforces exactly this shape on package code.
    """

    def __init__(self, scheduler: "TenantScheduler", job_id: str,
                 tenant: str, kind: str):
        self.scheduler = scheduler
        self.id = job_id
        self.tenant = tenant
        self.kind = kind
        self.future: concurrent.futures.Future = concurrent.futures.Future()

    def done(self) -> bool:
        return self.future.done()

    def result(self, timeout: float | None = None):
        return self.future.result(timeout)

    def wait(self, timeout: float | None = None) -> bool:
        concurrent.futures.wait([self.future], timeout=timeout)
        return self.future.done()

    def cancel(self) -> bool:
        """Cancel a queued job (or stop an init job packing further
        lanes).  Running non-init quanta finish their current quantum;
        a cancelled prove job stops at its next window boundary."""
        return self.scheduler._cancel(self)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<JobHandle {self.id} {self.kind}@{self.tenant}>"


class _Tenant:
    __slots__ = ("id", "weight", "max_inflight", "max_queued", "vtime",
                 "running", "jobs", "init_jobs", "queued_jobs")

    def __init__(self, tid: str, weight: float, max_inflight: int,
                 max_queued: int):
        self.id = tid
        self.weight = max(float(weight), 1e-6)
        self.max_inflight = max(int(max_inflight), 1)
        self.max_queued = max(int(max_queued), 1)
        self.vtime = 0.0
        self.running = 0          # worker quanta currently executing
        self.jobs: deque = deque()       # queued worker jobs (FIFO)
        self.init_jobs: deque = deque()  # init jobs with lanes left to pack
        self.queued_jobs = 0      # admission count (all kinds, live jobs)

    def charge(self, seconds: float) -> None:
        self.vtime += seconds / self.weight


class _Job:
    """A worker-pool job: runs as one or more quanta."""

    __slots__ = ("handle", "tenant", "kind", "fn", "deadline", "cancelled",
                 "gang", "abort")

    def __init__(self, handle: JobHandle, tenant: _Tenant, kind: str, fn,
                 deadline: float | None, gang: bool = False, abort=None):
        self.handle = handle
        self.tenant = tenant
        self.kind = kind
        # fn() -> ("done", result) | ("continue", None); multi-quantum
        # jobs (prove) return "continue" between windows
        self.fn = fn
        self.deadline = deadline
        self.cancelled = False
        self.gang = gang
        # abort() releases mid-job resources (an open prove session)
        # when the job resolves without completing; never called while
        # a quantum is executing
        self.abort = abort


class _InitJob:
    """A packed init job: lanes are composed by the packer, not a worker."""

    __slots__ = ("handle", "tenant", "store", "meta", "writer", "cw",
                 "total", "next_index", "outstanding", "written",
                 "min_carry", "cancelled", "error", "progress",
                 "finalized", "crc")

    def __init__(self, handle: JobHandle, tenant: _Tenant, store, meta,
                 writer, cw, progress=None):
        self.handle = handle
        self.tenant = tenant
        self.store = store
        self.meta = meta
        self.writer = writer
        self.cw = cw                       # (8,) u32 commitment words
        self.total = meta.total_labels
        self.next_index = meta.labels_written   # next lane to pack
        self.outstanding = 0               # lanes dispatched, not retired
        self.written = meta.labels_written
        # running CRC32 of the inline-written bytes (segments retire in
        # ascending-start order per job: next_index is monotone and the
        # engine retires packs FIFO) — finalize appends it to the
        # metadata's checkpoint ledger so the next reopen's recovery
        # does not roll a verified cursor back to a stale interval
        self.crc = 0
        self.min_carry = None              # (u128 value, index) | None
        if meta.vrf_nonce is not None and meta.vrf_nonce_value is not None:
            v = bytes.fromhex(meta.vrf_nonce_value)
            self.min_carry = (int.from_bytes(v, "little"), meta.vrf_nonce)
        self.cancelled = False
        self.error: Exception | None = None
        self.progress = progress
        self.finalized = False

    @property
    def packable(self) -> int:
        return 0 if self.cancelled or self.error else \
            self.total - self.next_index


class TenantScheduler:
    """Many identities, one device runtime (module docstring).

    ``workers``       worker threads for prove/verify/pow/call quanta.
    ``pack_lanes``    target lanes per packed init dispatch (bucketed).
    ``inflight``      packed init dispatches in flight (engine window).
    ``gang_windows``  prove windows allowed on device concurrently.
    ``writer_threads`` background writer threads per init job (0 =
                      synchronous writes in retire).
    ``time_source``   injectable clock for deadline tests.

    Lifecycle: construct -> (``start`` unless ``autostart``) -> submit —
    always ``unregister_tenant`` / ``close`` in a ``finally`` (SC004).
    """

    def __init__(self, *, workers: int = 2,
                 pack_lanes: int = _DEFAULT_PACK_LANES,
                 inflight: int = 3, gang_windows: int = 1,
                 writer_threads: int = 0,
                 pack_linger_s: float = 0.002,
                 default_weight: float = 1.0,
                 default_max_inflight: int = 4,
                 default_max_queued: int = 256,
                 autostart: bool = True,
                 time_source=time.monotonic):
        from ..ops import scrypt
        from ..utils import accel

        # compiled pack shapes persist across processes like every other
        # entry point's (utils/accel.py) — a cold 16-tenant start must
        # not pay one serialized compile per pack bucket
        accel.enable_persistent_cache()
        self.pack_lanes = max(scrypt.shape_bucket(int(pack_lanes)), 1)
        self.inflight = max(int(inflight), 1)
        self.writer_threads = int(writer_threads)
        self.pack_linger_s = max(float(pack_linger_s), 0.0)
        self._defaults = (default_weight, default_max_inflight,
                          default_max_queued)
        self._now = time_source
        self._lock = sanitize.lock("runtime.scheduler")
        self._work = sanitize.condition(  # workers wait here
            "runtime.scheduler.work", self._lock)
        self._pack_work = sanitize.condition(  # packer waits
            "runtime.scheduler.pack_work", self._lock)
        self._idle = sanitize.condition(  # drain() waits
            "runtime.scheduler.idle", self._lock)
        # the tenant tables are DECLARED SHARED to the lockset
        # sanitizer: submitters, workers, the packer and close() all
        # meet here, always under _lock
        self._shared = sanitize.SharedField("runtime.scheduler.tenants")
        self._tenants: dict[str, _Tenant] = {}
        self._jobs: dict[str, object] = {}  # live job id -> job
        self._ids = itertools.count(1)
        self._closed = False
        self._live_quanta = 0
        self._lane_cost_ema = 1e-4  # seconds per packed init lane
        self._gang = threading.Semaphore(max(int(gang_windows), 1))
        self._workers = [
            threading.Thread(target=self._worker_loop, daemon=True,
                             name=f"runtime-worker-{i}")
            for i in range(max(int(workers), 1))]
        self._packer = threading.Thread(target=self._packer_loop,
                                        daemon=True, name="runtime-packer")
        self._started = False
        if autostart:
            self.start()

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        for t in self._workers:
            t.start()
        self._packer.start()

    def close(self) -> None:
        """Stop the pool; queued jobs fail with SchedulerClosed.  Safe
        to call twice.  Running quanta finish (they hold device state
        mid-flight) and their jobs then resolve as closed."""
        with self._lock:
            self._shared.touch()
            if self._closed:
                return
            self._closed = True
            failed: list = []
            for t in self._tenants.values():
                failed.extend(t.jobs)
                t.jobs.clear()
                t.init_jobs.clear()
            self._work.notify_all()
            self._pack_work.notify_all()
        for job in failed:
            self._resolve(job, error=SchedulerClosed("scheduler closed"))
        if self._started:
            for t in self._workers:
                t.join(timeout=30)
            self._packer.join(timeout=30)
        # no thread touches jobs past this point: finalize whatever the
        # packer abandoned mid-flight (writers drained+closed, futures
        # failed) so close() never strands a handle unresolved
        with self._lock:
            self._shared.touch(write=False)
            leftovers = list(self._jobs.values())
        closed_exc = SchedulerClosed("scheduler closed")
        for job in leftovers:
            if isinstance(job, _InitJob):
                job.error = job.error or closed_exc
                self._finalize_init(job)
            else:
                self._resolve(job, error=closed_exc)

    def __enter__(self) -> "TenantScheduler":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def drain(self, timeout: float | None = None) -> bool:
        """Block until every submitted job resolved; False on timeout."""
        deadline = None if timeout is None else self._now() + timeout
        with self._idle:
            self._shared.touch(write=False)
            while self._jobs:
                left = None if deadline is None else deadline - self._now()
                if left is not None and left <= 0:
                    return False
                self._idle.wait(left if left is not None else 1.0)
        return True

    # -- tenants -------------------------------------------------------

    def register_tenant(self, tid: str, *, weight: float | None = None,
                        max_inflight: int | None = None,
                        max_queued: int | None = None) -> str:
        """Register (or re-weight) a tenant; pair with
        :meth:`unregister_tenant` when the identity goes away."""
        dw, di, dq = self._defaults
        with self._lock:
            self._shared.touch()
            t = self._tenants.get(tid)
            if t is None:
                t = self._tenants[tid] = _Tenant(
                    tid, weight if weight is not None else dw,
                    max_inflight if max_inflight is not None else di,
                    max_queued if max_queued is not None else dq)
                # a new tenant starts at the LEADING edge of virtual
                # time, not 0 — or it would owe the whole backlog of
                # every long-running tenant and stall them on arrival
                live = [x.vtime for x in self._tenants.values() if x is not t]
                t.vtime = min(live) if live else 0.0
            else:
                if weight is not None:
                    t.weight = max(float(weight), 1e-6)
                if max_inflight is not None:
                    t.max_inflight = max(int(max_inflight), 1)
                if max_queued is not None:
                    t.max_queued = max(int(max_queued), 1)
        return tid

    def unregister_tenant(self, tid: str) -> None:
        """Drop a tenant; its queued jobs fail with SchedulerClosed and
        its per-tenant gauge series disappear from the scrape (a gone
        identity must not pin a stale series — the PR 7 lesson)."""
        exc = SchedulerClosed(f"tenant {tid} unregistered")
        with self._lock:
            self._shared.touch()
            t = self._tenants.pop(tid, None)
            if t is None:
                return
            failed = list(t.jobs)
            failed_inits = []
            for ij in t.init_jobs:
                if ij.outstanding == 0:
                    failed_inits.append(ij)
                else:
                    # lanes still in flight: mark the job so the
                    # packer's retire finalizes (and resolves) it when
                    # they land — clearing it silently would strand the
                    # handle forever
                    ij.error = ij.error or exc
            t.jobs.clear()
            t.init_jobs.clear()
        metrics.runtime_tenant_queued.remove(tenant=tid)
        # counters/histograms carrying the tenant beside other labels
        # drop ALL of that tenant's series too (a churn of short-lived
        # identities — verifyd clients — must not grow the registry
        # without bound; the queued-gauge removal alone left these)
        for inst in (metrics.runtime_tenant_jobs,
                     metrics.runtime_tenant_labels,
                     metrics.runtime_quantum_seconds):
            inst.remove_matching(tenant=tid)
        for job in failed:
            self._resolve(job, error=exc)
        for job in failed_inits:
            # through finalize, not a bare resolve: the job's writer
            # threads and store fds must close with it
            job.error = job.error or exc
            self._finalize_init(job)

    def tenants(self) -> list[str]:
        with self._lock:
            self._shared.touch(write=False)
            return sorted(self._tenants)

    def namespaced(self, shard: str) -> "ShardScheduler":
        """A tenant-id-namespacing view for one fleet shard: every
        tenant registered through it lives as ``<shard>/<tid>``, so N
        verifyd replicas can share ONE device runtime without their
        client identities (fair-share vtime, quotas, per-tenant metric
        series) colliding (verifyd/fleet.py)."""
        return ShardScheduler(self, shard)

    # -- submission ----------------------------------------------------

    # guarded by: self._lock — every submit_* caller enters with the scheduler lock held
    def _admit(self, tid: str, kind: str) -> tuple[_Tenant, JobHandle]:
        self._shared.touch()
        if self._closed:
            raise SchedulerClosed("scheduler closed")
        t = self._tenants.get(tid)
        if t is None:
            raise KeyError(f"tenant {tid!r} is not registered")
        if t.queued_jobs >= t.max_queued:
            metrics.runtime_tenant_jobs.inc(tenant=tid, kind=kind,
                                            state="rejected")
            raise QuotaExceeded(
                f"tenant {tid}: {t.queued_jobs} jobs queued >= "
                f"max_queued {t.max_queued}")
        handle = JobHandle(self, f"{kind}-{next(self._ids)}", tid, kind)
        t.queued_jobs += 1
        metrics.runtime_tenant_queued.set(t.queued_jobs, tenant=tid)
        return t, handle

    def submit_call(self, tid: str, fn, *, kind: str = "call",
                    deadline_s: float | None = None) -> JobHandle:
        """Generic single-quantum job: ``fn()`` runs on a worker; its
        return value resolves the handle."""
        with self._lock:
            t, handle = self._admit(tid, kind)
            job = _Job(handle, t, kind,
                       lambda: ("done", fn()),
                       None if deadline_s is None
                       else self._now() + deadline_s)
            self._jobs[handle.id] = job
            t.jobs.append(job)
            self._work.notify()
        return handle

    def submit_pow(self, tid: str, challenge: bytes, node_id: bytes,
                   difficulty: bytes, *, deadline_s: float | None = None,
                   **search_opts) -> JobHandle:
        """k2pow nonce search as a scheduled quantum (ops/pow.py)."""
        from ..ops import pow as k2pow

        return self.submit_call(
            tid, lambda: k2pow.search(challenge, node_id, difficulty,
                                      tenant=tid, **search_opts),
            kind="k2pow", deadline_s=deadline_s)

    def submit_verify(self, tid: str, items: list, params=None, *,
                      seed: bytes | None = None,
                      deadline_s: float | None = None) -> JobHandle:
        """One batched POST verification (post/verifier.verify_many)
        as a scheduled quantum; resolves to the per-item bool list."""
        from ..post import verifier as post_verifier

        return self.submit_call(
            tid, lambda: post_verifier.verify_many(items, params, seed=seed),
            kind="verify", deadline_s=deadline_s)

    def submit_prove(self, tid: str, data_dir, challenge: bytes,
                     params=None, *, deadline_s: float | None = None,
                     **prover_opts) -> JobHandle:
        """A full prove as a multi-quantum job: the k2pow gate is one
        quantum, then each nonce window is one GANG quantum (one disk
        pass, never interleaved with another tenant's window beyond the
        configured gang width).  Resolves to the Proof."""
        from ..post.prover import Prover

        state: dict = {}

        def quantum():
            if "session" not in state:
                prover = Prover(data_dir, params, **prover_opts)
                state["session"] = prover.session(challenge, tenant=tid)
                return "continue", None
            session = state["session"]
            try:
                proof = session.step()
            except Exception:
                session.close()
                raise
            if proof is None:
                return "continue", None
            session.close()
            return "done", proof

        def abort():
            session = state.pop("session", None)
            if session is not None:
                session.close()

        with self._lock:
            t, handle = self._admit(tid, "prove")
            job = _Job(handle, t, "prove", quantum,
                       None if deadline_s is None
                       else self._now() + deadline_s, gang=True,
                       abort=abort)
            self._jobs[handle.id] = job
            t.jobs.append(job)
            self._work.notify()
        return handle

    def submit_init(self, tid: str, data_dir, *, node_id: bytes,
                    commitment: bytes, num_units: int, labels_per_unit: int,
                    scrypt_n: int = 8192,
                    max_file_size: int = 64 * 1024 * 1024,
                    progress=None) -> JobHandle:
        """Create-or-resume one identity's POST init as a PACKED job:
        its lanes dispatch interleaved with every other tenant's through
        the shared engine.  Resolves to the final PostMetadata."""
        from ..ops import scrypt
        from ..post.data import LabelStore
        from ..post.initializer import open_or_create_meta

        meta = open_or_create_meta(
            Path(data_dir), node_id=node_id, commitment=commitment,
            num_units=num_units, labels_per_unit=labels_per_unit,
            scrypt_n=scrypt_n, max_file_size=max_file_size)
        store = LabelStore(data_dir, meta)
        cw = scrypt.commitment_to_words(commitment)
        try:
            with self._lock:
                t, handle = self._admit(tid, "init")
                writer = (store.start_writer(self.writer_threads,
                                             queue_depth=8)
                          if self.writer_threads > 0 else None)
                job = _InitJob(handle, t, store, meta, writer, cw,
                               progress=progress)
                self._jobs[handle.id] = job
                if job.packable > 0:
                    t.init_jobs.append(job)
                    self._pack_work.notify()
                else:
                    # nothing to do (already complete): resolve now
                    self._jobs.pop(handle.id, None)
                    t.queued_jobs -= 1
                    handle.future.set_result(meta)
                    metrics.runtime_tenant_jobs.inc(tenant=tid, kind="init",
                                                    state="done")
        except Exception:
            store.close()
            raise
        return handle

    # -- cancellation / resolution -------------------------------------

    def _cancel(self, handle: JobHandle) -> bool:
        with self._lock:
            self._shared.touch()
            job = self._jobs.get(handle.id)
            if job is None:
                return False
            if isinstance(job, _InitJob):
                job.cancelled = True
                try:
                    job.tenant.init_jobs.remove(job)
                except ValueError:
                    pass
                if job.outstanding > 0:
                    return True  # packer finalizes after in-flight retires
            else:
                job.cancelled = True
                try:
                    job.tenant.jobs.remove(job)
                except ValueError:
                    return True  # running: stops at its next quantum edge
        if isinstance(job, _InitJob):
            # through finalize: writer threads and store fds close too
            self._finalize_init(job)
        else:
            self._resolve(job, cancelled=True)
        return True

    def _resolve(self, job, result=None, error: Exception | None = None,
                 cancelled: bool = False) -> None:
        handle = job.handle
        with self._lock:
            self._shared.touch()
            if self._jobs.pop(handle.id, None) is None:
                return  # already resolved
            t = self._tenants.get(handle.tenant)
            if t is not None:
                t.queued_jobs -= 1
                metrics.runtime_tenant_queued.set(t.queued_jobs,
                                                  tenant=t.id)
            self._idle.notify_all()
        state = ("cancelled" if cancelled
                 else "failed" if error is not None else "done")
        metrics.runtime_tenant_jobs.inc(tenant=handle.tenant,
                                        kind=handle.kind, state=state)
        if state != "done" and isinstance(job, _Job) \
                and job.abort is not None:
            try:
                job.abort()
            except Exception:  # noqa: BLE001 — cleanup must not mask the outcome
                pass
        if cancelled:
            handle.future.cancel()
        elif error is not None:
            handle.future.set_exception(error)
        else:
            handle.future.set_result(result)

    # -- worker pool (prove/verify/pow/call quanta) ---------------------

    # guarded by: self._lock — _worker_loop picks with the scheduler lock held
    def _pick_job(self) -> _Job | None:
        """Under the lock: the next quantum by deadline-then-fair-share."""
        self._shared.touch()
        now = self._now()
        best_t = None
        overdue_job = None
        overdue_deadline = None
        for t in self._tenants.values():
            if not t.jobs or t.running >= t.max_inflight:
                continue
            for job in t.jobs:
                if job.deadline is not None \
                        and job.deadline <= now + _DEADLINE_SLACK_S \
                        and (overdue_deadline is None
                             or job.deadline < overdue_deadline):
                    overdue_job, overdue_deadline = job, job.deadline
            if best_t is None or t.vtime < best_t.vtime:
                best_t = t
        if best_t is None:
            return None
        fair_pick = best_t.jobs[0]
        if overdue_job is not None:
            if overdue_job is not fair_pick:
                metrics.runtime_deadline_boosts.inc()
            overdue_job.tenant.jobs.remove(overdue_job)
            return overdue_job
        return best_t.jobs.popleft()

    def _worker_loop(self) -> None:
        while True:
            with self._lock:
                self._shared.touch(write=False)
                job = None
                while not self._closed:
                    job = self._pick_job()
                    if job is not None:
                        break
                    self._work.wait()
                if job is None:  # closed
                    return
                job.tenant.running += 1
                self._live_quanta += 1
            self._run_quantum(job)

    def _run_quantum(self, job: _Job) -> None:
        t0 = time.perf_counter()
        outcome, result, error = "continue", None, None
        # gang gating applies to every quantum of a gang job (the pow
        # gate is cheap; per-window discrimination is not worth a
        # second state channel)
        if job.gang:
            self._gang.acquire()
        try:
            with tracing.span("runtime.quantum",
                              {"tenant": job.tenant.id, "kind": job.kind,
                               "job": job.handle.id}
                              if tracing.is_enabled() else None):
                try:
                    outcome, result = job.fn()
                except Exception as exc:  # noqa: BLE001 — job fails, pool survives
                    outcome, error = "error", exc
        finally:
            if job.gang:
                self._gang.release()
            dt = time.perf_counter() - t0
            metrics.runtime_quantum_seconds.inc(dt, kind=job.kind,
                                                tenant=job.tenant.id)
            with self._lock:
                self._shared.touch()
                job.tenant.charge(dt)
                job.tenant.running -= 1
                self._live_quanta -= 1
                requeue = (outcome == "continue" and error is None
                           and not job.cancelled and not self._closed)
                if requeue:
                    # multi-quantum job continues ahead of the tenant's
                    # own later jobs (per-job FIFO), fair share decides
                    # across tenants
                    job.tenant.jobs.appendleft(job)
                self._work.notify()
            if error is not None:
                self._resolve(job, error=error)
            elif job.cancelled:
                self._resolve(job, cancelled=True)
            elif outcome == "done":
                self._resolve(job, result=result)
            elif not requeue:
                # dropped at close mid-job: the handle must not strand
                self._resolve(job, error=SchedulerClosed(
                    "scheduler closed"))

    # -- the init packer ------------------------------------------------

    def _compose_pack(self, block: bool):
        """Cut one pack of init lanes in fair-share order.

        ``block`` — wait for work (the engine window is empty); False
        returns None immediately when no tenant has packable lanes (the
        packer then yields IDLE so in-flight packs keep retiring).
        Returns (segments, scrypt_n), or None on close/no-work.

        Pack-fill policy: a burst of submits races the packer, and a
        half-empty first pack both wastes lanes and mints a smaller
        shape bucket.  So a partial pack LINGERS up to
        ``pack_linger_s`` for more lanes to arrive, and with work
        already in flight (``block`` False) a pack under half full is
        deferred outright — the engine retires results meanwhile and
        the lanes coalesce into the next full pack."""
        with self._lock:
            self._shared.touch()
            while True:
                if self._closed:
                    return None
                ready = [t for t in self._tenants.values() if t.init_jobs]
                if ready:
                    avail = sum(j.packable for t in ready
                                for j in t.init_jobs)
                    if avail >= self.pack_lanes:
                        break
                    if not block:
                        if avail >= self.pack_lanes // 2:
                            break
                        return None
                    deadline = time.monotonic() + self.pack_linger_s
                    while avail < self.pack_lanes and not self._closed:
                        left = deadline - time.monotonic()
                        if left <= 0 or not self._pack_work.wait(left):
                            break
                        ready = [t for t in self._tenants.values()
                                 if t.init_jobs]
                        avail = sum(j.packable for t in ready
                                    for j in t.init_jobs)
                    ready = [t for t in self._tenants.values()
                             if t.init_jobs]
                    if ready:
                        break
                    continue
                if not block:
                    return None
                self._pack_work.wait()
            segments: list[workloads.PackSegment] = []
            lanes = 0
            n = None
            for t in sorted(ready, key=lambda t: t.vtime):
                while t.init_jobs and lanes < self.pack_lanes:
                    job = t.init_jobs[0]
                    take = min(job.packable, self.pack_lanes - lanes)
                    if take == 0:
                        # cancelled/errored (packable 0) or the pack is
                        # full for this tenant's head job: never emit a
                        # zero-count segment
                        if job.packable == 0:
                            t.init_jobs.popleft()
                            continue
                        break
                    if n is None:
                        n = job.meta.scrypt_n
                    elif job.meta.scrypt_n != n:
                        break  # one static N per fused program
                    segments.append(workloads.PackSegment(
                        job, job.next_index, take, lanes))
                    job.next_index += take
                    job.outstanding += take
                    lanes += take
                    # provisional fair-share charge at the EMA lane cost
                    # (the true wall cost lands in the EMA at retire)
                    t.charge(take * self._lane_cost_ema)
                    if job.packable == 0:
                        t.init_jobs.popleft()
                if lanes >= self.pack_lanes:
                    break
            return segments, n

    def _dispatch_pack(self, pack):
        import jax.numpy as jnp
        import numpy as np

        from ..ops import autotune, scrypt

        segments, n = pack
        lanes = sum(s.count for s in segments)
        cw = np.empty((8, lanes), dtype=np.uint32)
        idx = np.empty(lanes, dtype=np.uint64)
        for s in segments:
            cw[:, s.lane0:s.lane0 + s.count] = s.job.cw[:, None]
            idx[s.lane0:s.lane0 + s.count] = np.arange(
                s.start, s.start + s.count, dtype=np.uint64)
        metrics.runtime_pack_occupancy.observe(lanes)
        metrics.runtime_pack_tenants.observe(
            len({s.job.tenant.id for s in segments}))
        # the tuned mesh routing every mesh-aware entry point shares
        # (SPACEMESH_MESH forces; CPU consults the raced winner). Packs
        # dispatch at their shape bucket either way — one executable per
        # (n, bucket) — so the bucket is what the mesh must divide.
        bucket = scrypt.shape_bucket(lanes)
        devs, d = autotune.resolve_auto_mesh(n, bucket)
        if devs is not None and len(devs) > 1 and bucket % len(devs) == 0:
            from ..parallel import mesh as pmesh

            # mesh callers pre-bucket on host (ops/scrypt.py _tunable
            # skips padding for sharded inputs): repeat the last lane —
            # a real commitment/index, so padding lanes recompute a real
            # label and stay branch-free; _retire_pack slices only the
            # segment-addressed lanes
            if bucket != lanes:
                cw = np.concatenate(
                    [cw, np.repeat(cw[:, -1:], bucket - lanes, axis=1)],
                    axis=1)
                idx = np.concatenate(
                    [idx, np.repeat(idx[-1:], bucket - lanes)])
            lo, hi = scrypt.split_indices(idx)
            words = pmesh.scrypt_labels_sharded(
                pmesh.data_mesh(devs), cw, lo, hi, n=n, impl=d.impl)
        else:
            lo, hi = scrypt.split_indices(idx)
            # scrypt_labels_jit pads ragged packs to their shape bucket
            # (per-lane cw padded too) — one executable per (n, bucket)
            words = scrypt.scrypt_labels_jit(
                jnp.asarray(cw), jnp.asarray(lo), jnp.asarray(hi), n=n)
        return words, segments, time.perf_counter()

    def _retire_pack(self, ticket) -> None:
        import numpy as np

        from ..ops import scrypt

        words, segments, t_dispatch = ticket
        arr = np.asarray(words)  # the only device sync of the pack
        lanes = sum(s.count for s in segments)
        dt = time.perf_counter() - t_dispatch
        # EMA of the measured per-lane cost feeds the provisional
        # fair-share charge in _compose_pack — which reads it under the
        # scheduler lock, so the read-modify-write must hold it too or
        # a concurrent compose can consume (and charge tenants by) a
        # half-updated cost (found by SC007, ISSUE 12)
        with self._lock:
            self._shared.touch()
            self._lane_cost_ema += 0.25 * (dt / max(lanes, 1)
                                           - self._lane_cost_ema)
        # ONE byte conversion for the whole pack, sliced per segment —
        # 16 tiny per-tenant byteswaps would hand back the per-call
        # overhead the pack just amortized
        pack_bytes = scrypt.labels_to_bytes(arr)
        finalize: list[_InitJob] = []
        for s in segments:
            job: _InitJob = s.job
            with tracing.span("runtime.segment",
                              {"tenant": job.tenant.id, "start": s.start,
                               "count": s.count}
                              if tracing.is_enabled() else None):
                try:
                    if job.error is None and not job.cancelled:
                        data = pack_bytes[s.lane0 * scrypt.LABEL_BYTES:
                                          (s.lane0 + s.count)
                                          * scrypt.LABEL_BYTES]
                        if job.writer is not None:
                            job.writer.submit(s.start, data)
                        else:
                            job.store.write_labels(s.start, data)
                            job.crc = zlib.crc32(data, job.crc)
                        job.min_carry = workloads.fold_min_host(
                            job.min_carry, data, s.start)
                        job.written = max(job.written, s.start + s.count)
                        metrics.runtime_tenant_labels.inc(
                            s.count, tenant=job.tenant.id)
                        if job.progress is not None:
                            job.progress(job.written, job.total)
                except Exception as exc:  # noqa: BLE001 — fail THIS job, not the pack
                    job.error = exc
            with self._lock:
                self._shared.touch()
                job.outstanding -= s.count
                if job.error is not None or job.cancelled:
                    # packable is 0 now: drop the queued remainder so
                    # the compose loop stops seeing this tenant as
                    # ready work
                    try:
                        job.tenant.init_jobs.remove(job)
                    except ValueError:
                        pass
                done = (job.outstanding == 0
                        and (job.next_index >= job.total or job.cancelled
                             or job.error is not None))
            if done and job not in finalize:
                finalize.append(job)
        for job in finalize:
            self._finalize_init(job)

    def _finalize_init(self, job: _InitJob) -> None:
        # idempotent: unregister/close/retire can race to finalize the
        # same job; only the first pass drains/closes and resolves
        with self._lock:
            self._shared.touch()
            if job.finalized:
                return
            job.finalized = True
        error = job.error
        try:
            if job.writer is not None:
                # drain + checkpoint fsync the dirty label files before
                # advancing the durable cursor (post/data.py fsync
                # discipline) — the cursor persisted below means
                # FSYNCED, not "handed to the page cache" — and hand
                # back the interval CRC for the ledger
                job.writer.drain()
                durable, crc = job.writer.checkpoint()
                job.writer.close(drain=False)
            else:
                job.store.sync()  # same contract on the inline path
                durable, crc = job.written, job.crc
            if error is None and not job.cancelled:
                meta = job.meta
                meta.labels_written = durable
                # the checkpoint ledger must cover the cursor it backs:
                # a cursor ahead of a stale ledger would be rolled BACK
                # (and its durable labels truncated) by the next
                # reopen's recovery (post/data.py recover_store)
                prev_end = meta.intervals[-1][0] if meta.intervals else 0
                if durable > prev_end:
                    meta.intervals.append([durable, crc])
                nonce, value = workloads.min_carry_to_meta(job.min_carry)
                if nonce is not None:
                    meta.vrf_nonce = nonce
                    meta.vrf_nonce_value = value
                meta.save(job.store.dir)
        except Exception as exc:  # noqa: BLE001 — surface via the handle
            error = error or exc
        finally:
            job.store.close()
        if job.cancelled and error is None:
            self._resolve(job, cancelled=True)
        elif error is not None:
            self._resolve(job, error=error)
        else:
            self._resolve(job, result=job.meta)

    def _packer_loop(self) -> None:
        """The shared-device init stream: one engine pipeline whose
        items are cross-tenant packs, kept ``inflight`` deep for the
        whole life of the scheduler — tenant boundaries never drain the
        device the way per-job ownership does."""
        pipe = engine.Pipeline(kind="init_pack", tenant="*",
                               inflight=self.inflight, span="runtime.pack",
                               attrs=lambda p: {
                                   "lanes": sum(s.count for s in p[0]),
                                   "tenants": len({s.job.tenant.id
                                                   for s in p[0]})},
                               stop=lambda: self._closed)  # spacecheck: ok=SC007 monotonic close flag; a stale read only delays stop by one batch

        def packs():
            while True:
                if self._closed:
                    return
                # block for work only when the window is empty: with
                # packs in flight, an empty queue yields IDLE so the
                # engine retires results instead of deadlocking a full
                # window behind a quiet submit queue
                pack = self._compose_pack(block=pipe.pending_count == 0)
                if pack is None or not pack[0]:
                    if self._closed:
                        return
                    if pipe.pending_count:
                        yield engine.IDLE
                    continue
                yield pack

        try:
            pipe.run(packs(), self._dispatch_pack, self._retire_pack)
        except Exception as exc:  # noqa: BLE001 — fail in-flight init jobs, not the thread
            with self._lock:
                self._shared.touch(write=False)
                jobs = [j for j in self._jobs.values()
                        if isinstance(j, _InitJob)]
            for j in jobs:
                j.error = j.error or exc
                if j.outstanding == 0:
                    self._finalize_init(j)


class ShardScheduler:
    """One shard's view of a shared :class:`TenantScheduler`.

    Prefixes every tenant id with ``<shard>/`` on the way in and strips
    it on the way out, so per-shard client registries (verifyd fleet
    replicas) scale past one registry's identity space while sharing
    the device runtime.  ``close``/``drain``/``start`` pass through to
    the underlying scheduler — the OWNER decides lifetime; a view held
    by a non-owning service simply never calls close (the same
    ownership rule VerifydService already applies to an injected
    scheduler).
    """

    def __init__(self, inner: TenantScheduler, shard: str):
        self.inner = inner
        self.shard = str(shard)
        self._prefix = f"{self.shard}/"

    def _tid(self, tid: str) -> str:
        return self._prefix + str(tid)

    def register_tenant(self, tid: str, **kwargs) -> str:
        self.inner.register_tenant(self._tid(tid), **kwargs)
        return str(tid)

    def unregister_tenant(self, tid: str) -> None:
        self.inner.unregister_tenant(self._tid(tid))

    def submit_call(self, tid: str, fn, **kwargs) -> JobHandle:
        return self.inner.submit_call(self._tid(tid), fn, **kwargs)

    def tenants(self) -> list[str]:
        return [t[len(self._prefix):] for t in self.inner.tenants()
                if t.startswith(self._prefix)]

    def start(self) -> None:
        self.inner.start()

    def drain(self, timeout: float | None = None) -> bool:
        return self.inner.drain(timeout)

    def close(self) -> None:
        self.inner.close()
