"""One device-job runtime, many tenants (docs/DEVICE_RUNTIME.md).

The four device pipelines — POST init (post/initializer.py), POST prove
(post/prover.py), the verification farm (verify/farm.py) and the k2pow
nonce search (ops/pow.py) — used to each carry a private copy of the
same machinery: bounded in-flight dispatch, donated carry state,
pad-and-trim ragged tails, autotune consultation, device-failure
fallback, per-stage spans and metrics.  ROADMAP items #1/#2 (and the
review-fix history in ADVICE.md) argue that class of subtle code should
exist ONCE.  This package is that once:

* :mod:`engine`    — the submit -> batch -> dispatch -> retire executor
  (:class:`engine.Pipeline`): one bounded window of device work in
  flight, early exit, stop, fallback-on-device-failure, per-stage
  spans/metrics with a ``tenant`` label.
* :mod:`queue`     — the async admission primitives the farm's priority
  lanes are built from (:class:`queue.LaneGroup`,
  :class:`queue.KindLanes`): per-lane bounds, backpressure waiters with
  cancellation handoff, in-flight dedup.
* :mod:`workloads` — the registry of device workload kinds (fused init
  labels, packed multi-tenant init, prove scan step, verify batch,
  k2pow) with their warm-shape recipes (tools/warmcache.py compiles
  exactly these).
* :mod:`scheduler` — the multi-tenant layer
  (:class:`scheduler.TenantScheduler`): per-tenant job queues drained
  by fair-share (stride) + deadline admission onto one shared device,
  cross-tenant lane packing for init, gang-scheduled prove windows,
  per-tenant quotas, and a ``tenant`` label flowing through metrics and
  span tracing.
"""

from .engine import Pipeline, PipelineStats, JobStopped  # noqa: F401
from .scheduler import (  # noqa: F401
    JobHandle, SchedulerClosed, TenantScheduler,
)
