"""The device-job engine: one submit -> batch -> dispatch -> retire loop.

Every device pipeline in this repo has the same steady-state shape: a
host thread enqueues up to K batches of device work (dispatch), then
pops the oldest and blocks on its results (retire), so device compute,
PCIe copies and host-side work overlap.  post/initializer.py,
post/prover.py and ops/pow.py each hand-rolled that deque — and the
prover's reader-error path and the farm's lane waiter each grew
review-fix bugs in their private copies (ADVICE.md; ROADMAP item #2).

:class:`Pipeline` is the one copy.  Workload-specific behavior stays in
two callbacks:

``dispatch(item) -> ticket``
    Enqueue device work for one item and return immediately (the ticket
    is whatever the retire side needs — device arrays, counts, byte
    offsets).  A raised exception is fed to the ``fallback`` hook when
    one is configured (device-failure fallback, e.g. k2pow's host
    re-hash) before it is allowed to kill the job.

``retire(ticket) -> result | None``
    Block on the oldest in-flight ticket and consume its results.  A
    non-None return is a sound EARLY EXIT: the pipeline stops pulling
    items, abandons the remaining in-flight tickets (the prover's
    winning-nonce rule) and returns that value.

The engine owns the subtle parts: the bounded window, drain-vs-discard
on stop, early-exit semantics, per-stage wall-time accounting, the
``runtime_*`` metrics and the per-stage spans — all labeled with the
submitting ``tenant`` so a multi-tenant trace decomposes per identity
(docs/DEVICE_RUNTIME.md).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Any, Callable, Iterable, Optional

from ..utils import metrics, sanitize, tracing

# per-kind AGGREGATE in-flight depth: concurrent pipelines of one kind
# (two gang prove windows, parallel k2pow searches) each contribute a
# delta instead of clobbering the gauge — the finishing pipeline removes
# only its own share, never zeroes a peer's. Declared shared to the
# lockset sanitizer: every pipeline thread passes through here.
_inflight_lock = sanitize.lock("runtime.engine.inflight")
_inflight_shared = sanitize.SharedField("runtime.engine.inflight_by_kind")
_inflight_by_kind: dict[str, int] = {}


def _inflight_adjust(kind: str, delta: int) -> int:
    with _inflight_lock:
        _inflight_shared.touch()
        n = _inflight_by_kind.get(kind, 0) + delta
        _inflight_by_kind[kind] = n
        return n


class JobStopped(RuntimeError):
    """The job's stop predicate flipped while work was still queued."""


# Sentinel a CONTINUOUS item stream (the multi-tenant packer) yields
# when it has no new work right now: the engine retires the oldest
# in-flight ticket (if any) instead of dispatching, so results keep
# draining while the stream decides whether to block for more work.
# Finite streams (init/prove/pow) never need it — exhausting the
# iterator drains the window.
IDLE = object()


@dataclasses.dataclass
class PipelineStats:
    """Per-run stage accounting (the engine's copy; pipelines fold it
    into their own richer stats objects)."""

    batches: int = 0
    dispatch_s: float = 0.0   # host time enqueueing device work
    retire_s: float = 0.0     # blocked consuming results
    fallbacks: int = 0        # dispatch exceptions absorbed by fallback
    early_exited: bool = False
    stopped: bool = False

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class Pipeline:
    """Bounded in-flight dispatch->retire executor for one device job.

    ``kind``      workload kind label (metrics/spans): "init", "prove",
                  "pow", "verify", ...
    ``tenant``    identity label carried on every span/metric; "-" for
                  single-tenant embedders.
    ``inflight``  device batches in flight before the oldest retires.
    ``stop``      checked before each dispatch; True discards the
                  remaining in-flight work (the initializer's stop
                  contract: stop latency is one retire, not a drain).
    ``fallback``  ``(item, exc) -> ticket`` — a dispatch exception goes
                  here once per item (device-failure fallback); absent,
                  the exception propagates.
    ``breaker``   an ``obs.remediate.CircuitBreaker`` wrapped around the
                  device-dispatch attempt.  Without one, a permanently
                  dead backend re-pays the failing dispatch on EVERY
                  batch (the pre-remediation behavior: no memory
                  between batches); with one, failures trip it open and
                  dispatch goes straight to ``fallback`` — the item
                  sees a typed :class:`~..obs.remediate.BreakerOpen`
                  instead of the long-dead device error — until a
                  half-open probe finds the device back.
    ``span``      span name prefix; None disables the engine's spans
                  (callers that still own their own, e.g. during
                  migration tests).  Dispatch spans are named
                  ``f"{span}.dispatch"`` so existing timeline tooling
                  (trace-smoke CI, profiler --timeline) keeps matching.
    ``attrs``     ``item -> dict`` extra dispatch-span attributes.
    ``on_inflight`` depth hook (pipeline-specific gauges).
    """

    def __init__(self, *, kind: str, tenant: str = "-", inflight: int = 3,
                 stop: Optional[Callable[[], bool]] = None,
                 fallback: Optional[Callable[[Any, Exception], Any]] = None,
                 breaker=None,
                 span: str | None = None,
                 attrs: Optional[Callable[[Any], dict]] = None,
                 on_inflight: Optional[Callable[[int], None]] = None):
        self.kind = kind
        self.tenant = tenant
        self.inflight = max(int(inflight), 1)
        self._stop = stop
        self._fallback = fallback
        self._breaker = breaker
        self._span = span
        self._attrs = attrs
        self._on_inflight = on_inflight
        self.stats = PipelineStats()
        self._pending: deque = deque()
        self._last_depth = 0

    @property
    def pending_count(self) -> int:
        """Tickets in flight right now (continuous streams consult this
        to decide between blocking for work and yielding IDLE)."""
        return len(self._pending)

    # -- internals -----------------------------------------------------

    def _set_inflight(self, n: int) -> None:
        total = _inflight_adjust(self.kind, n - self._last_depth)
        self._last_depth = n
        metrics.runtime_inflight.set(total, kind=self.kind)
        if self._on_inflight is not None:
            self._on_inflight(n)

    def _dispatch_one(self, dispatch, item):
        t0 = time.perf_counter()
        attrs = None
        if self._span is not None and tracing.is_enabled():
            attrs = {"kind": self.kind, "tenant": self.tenant}
            if self._attrs is not None:
                attrs.update(self._attrs(item))
        sp = (tracing.span(f"{self._span}.dispatch", attrs)
              if self._span is not None else tracing._NOP)
        br = self._breaker
        with sp:
            if br is not None and not br.allow():
                # open breaker: the device path is known-dead, go
                # straight to the fallback WITHOUT re-paying the
                # failing dispatch attempt (sustained-failure memory
                # between batches)
                from ..obs.remediate import BreakerOpen

                if self._fallback is None:
                    raise BreakerOpen(br.component, br.retry_in())
                ticket = self._fallback(
                    item, BreakerOpen(br.component, br.retry_in()))
                self.stats.fallbacks += 1
                metrics.runtime_fallbacks.inc(kind=self.kind)
            else:
                try:
                    ticket = dispatch(item)
                except Exception as exc:  # noqa: BLE001 — routed to fallback
                    if br is not None:
                        br.record_failure()
                    if self._fallback is None:
                        raise
                    ticket = self._fallback(item, exc)
                    self.stats.fallbacks += 1
                    metrics.runtime_fallbacks.inc(kind=self.kind)
                else:
                    if br is not None:
                        br.record_success()
        self.stats.dispatch_s += time.perf_counter() - t0
        self.stats.batches += 1
        metrics.runtime_dispatched.inc(kind=self.kind, tenant=self.tenant)
        return ticket

    def _retire_one(self, retire, ticket):
        t0 = time.perf_counter()
        try:
            return retire(ticket)
        finally:
            self.stats.retire_s += time.perf_counter() - t0
            metrics.runtime_retired.inc(kind=self.kind, tenant=self.tenant)

    # -- the loop ------------------------------------------------------

    def run(self, items: Iterable[Any], dispatch, retire):
        """Drive ``items`` through the bounded window.

        Returns the first non-None retire result (early exit), or None
        when every item retired (or the stop predicate ended the run —
        ``stats.stopped`` distinguishes).  Stage seconds and counters
        accumulate in ``self.stats`` and the ``runtime_*`` metrics.
        """
        stats = self.stats
        pending = self._pending
        result = None
        try:
            for item in items:
                if self._stop is not None and self._stop():
                    stats.stopped = True
                    # stop contract: discard in-flight device work, the
                    # caller persists whatever already retired
                    pending.clear()
                    return None
                if item is IDLE:
                    if pending:
                        result = self._retire_one(retire, pending.popleft())
                        self._set_inflight(len(pending))
                        if result is not None:
                            stats.early_exited = True
                            pending.clear()
                            return result
                    continue
                pending.append(self._dispatch_one(dispatch, item))
                self._set_inflight(len(pending))
                if len(pending) >= self.inflight:
                    result = self._retire_one(retire, pending.popleft())
                    self._set_inflight(len(pending))
                    if result is not None:
                        stats.early_exited = True
                        pending.clear()  # abandon: the result is final
                        return result
            while pending:
                if self._stop is not None and self._stop():
                    stats.stopped = True
                    pending.clear()
                    return None
                result = self._retire_one(retire, pending.popleft())
                self._set_inflight(len(pending))
                if result is not None:
                    stats.early_exited = True
                    pending.clear()
                    return result
            return None
        finally:
            self._set_inflight(0)
            for stage, secs in (("dispatch", stats.dispatch_s),
                                ("retire", stats.retire_s)):
                metrics.runtime_stage_seconds.inc(secs, kind=self.kind,
                                                  stage=stage)
