"""Registry of device workload kinds + their warm-shape recipes.

The runtime engine treats a workload as two callbacks; this module is
where the repo's actual device workloads are cataloged so tools can
enumerate them without importing every pipeline:

* ``init``        — fused single-identity label batches chained to the
                    on-device VRF min-scan (post/initializer.py).
* ``init_pack``   — the multi-tenant packed variant: ONE fused label
                    program over many identities' lanes (per-lane
                    commitment words), VRF minimum folded per tenant on
                    host (runtime/scheduler.py).
* ``prove_scan``  — the streaming prover's scan step (post/prover.py).
* ``verify``      — the batched POST verifier's recompute shapes
                    (per-lane commitments + proving hash).
* ``k2pow``       — the SHA-256 nonce-search batch (ops/pow.py).
* ``k2pow_verify`` — the per-item-prefix k2pow witness verification
                    batch (ops/pow.py verify_many; the verifyd service
                    and the farm's "pow" kind dispatch it).

Each kind carries a ``warm(n, batch)`` recipe compiling exactly the
executables that kind runs at one (N, bucketed batch) shape —
tools/warmcache.py iterates :func:`registered` so a cold 16-tenant
start does not pay one serialized compile per workload kind
(docs/DEVICE_RUNTIME.md).

Also home to the host-side helpers the packed init path shares with its
tests: :func:`fold_min_host` (the per-tenant VRF running minimum over
fetched label bytes — bit-identical to the device scan's first-
occurrence LE-u128 argmin) and :class:`PackSegment`.
"""

from __future__ import annotations

import dataclasses
from typing import Callable


@dataclasses.dataclass(frozen=True)
class WorkloadKind:
    """One registered device workload kind."""

    name: str
    description: str
    # warm(n, batch) -> {program name: compile seconds}; compiles (or
    # cache-deserializes) every executable the kind runs at that shape
    warm: Callable[[int, int], dict]


_REGISTRY: dict[str, WorkloadKind] = {}


def register(kind: WorkloadKind) -> WorkloadKind:
    if kind.name in _REGISTRY:
        raise ValueError(f"workload kind {kind.name!r} already registered")
    _REGISTRY[kind.name] = kind
    return kind


def registered() -> list[WorkloadKind]:
    """All registered kinds, stable order (warmcache iterates this)."""
    return [_REGISTRY[k] for k in sorted(_REGISTRY)]


def get(name: str) -> WorkloadKind:
    return _REGISTRY[name]


# --- warm recipes -------------------------------------------------------
#
# Imports live inside the recipes: the registry must import without jax
# (spacecheck and CLI --list paths run before deps install).


def _timed(doc: dict, name: str, fn) -> None:
    import time

    import jax

    t0 = time.perf_counter()
    jax.block_until_ready(fn())
    doc[name] = round(time.perf_counter() - t0, 2)


def _warm_init(n: int, batch: int) -> dict:
    import hashlib

    import jax.numpy as jnp
    import numpy as np

    from ..ops import scrypt

    cw = scrypt.commitment_to_words(hashlib.sha256(b"warm-runtime").digest())
    idx = np.arange(batch, dtype=np.uint64)
    lo, hi = scrypt.split_indices(idx)
    jcw, jlo, jhi = jnp.asarray(cw), jnp.asarray(lo), jnp.asarray(hi)
    doc: dict = {}
    _timed(doc, "labels_fused",
           lambda: scrypt.scrypt_labels_jit(jcw, jlo, jhi, n=n))
    _timed(doc, "labels_min_fused",
           lambda: scrypt.scrypt_labels_with_min(
               jcw, jlo, jhi, jnp.asarray(scrypt.vrf_carry_init()), n=n)[0])
    return doc


def _warm_init_pack(n: int, batch: int) -> dict:
    import hashlib

    import jax.numpy as jnp
    import numpy as np

    from ..ops import scrypt

    # per-lane commitment words: the packed program's distinguishing
    # shape (a (8, B) cw is a different executable than a shared (8,))
    cw = np.stack([
        scrypt.commitment_to_words(hashlib.sha256(b"warm-%d" % i).digest())
        for i in range(2)], axis=1)
    cw = np.repeat(cw, (batch + 1) // 2, axis=1)[:, :batch]
    idx = np.arange(batch, dtype=np.uint64)
    lo, hi = scrypt.split_indices(idx)
    doc: dict = {}
    _timed(doc, "labels_fused_perlane",
           lambda: scrypt.scrypt_labels_jit(
               jnp.asarray(cw), jnp.asarray(lo), jnp.asarray(hi), n=n))
    # when the tuned routing shards packs at this bucket, the sharded
    # twin is a DIFFERENT executable (GSPMD-partitioned) — warm it too,
    # or the first real pack dispatch pays the compile
    from ..ops import autotune

    devs, d = autotune.resolve_auto_mesh(n, batch)
    if devs is not None and len(devs) > 1 and batch % len(devs) == 0:
        from ..parallel import mesh as pmesh

        mesh = pmesh.data_mesh(devs)
        _timed(doc, f"labels_fused_perlane_mesh{len(devs)}",
               lambda: pmesh.scrypt_labels_sharded(
                   mesh, cw, lo, hi, n=n, impl=d.impl))
        doc["pack_devices"] = len(devs)
    return doc


def _warm_prove_scan(n: int, batch: int) -> dict:
    import jax.numpy as jnp
    import numpy as np

    from ..ops import proving, scrypt

    b = scrypt.shape_bucket(-(-batch // proving.HIT_SEGMENT)
                            * proving.HIT_SEGMENT)
    ng, cap = 16, 37  # prover defaults (nonce_group, k2)
    cw = jnp.asarray(proving.challenge_words(bytes(32)))
    idx = np.arange(b, dtype=np.uint64)
    lo, hi = scrypt.split_indices(idx)
    lw = jnp.zeros((4, b), jnp.uint32)
    counts, carry = proving.init_hit_state(ng, cap)
    doc: dict = {"batch": b}
    _timed(doc, "prove_scan_step",
           lambda: proving.prove_scan_step_jit(
               cw, jnp.uint32(0), jnp.asarray(lo), jnp.asarray(hi), lw,
               jnp.uint32(1 << 30), counts, carry, jnp.uint32(b),
               jnp.uint32(0), jnp.uint32(0), n_nonces=ng, max_hits=cap))
    return doc


def _warm_verify(n: int, batch: int) -> dict:
    import jax.numpy as jnp
    import numpy as np

    from ..ops import proving

    # the verifier's second pass: proving-hash values over the
    # recomputed labels (its first pass shares init_pack's per-lane
    # label executable)
    doc = _warm_init_pack(n, batch)
    cw = jnp.asarray(proving.challenge_words(bytes(32)))
    idx = np.arange(batch, dtype=np.uint64)
    lo_h = (idx & 0xFFFFFFFF).astype(np.uint32)
    hi_h = (idx >> 32).astype(np.uint32)
    lo, hi = jnp.asarray(lo_h), jnp.asarray(hi_h)
    lw = jnp.zeros((4, batch), jnp.uint32)
    _timed(doc, "proving_hash",
           lambda: proving.proving_hash_jit(cw, jnp.uint32(7), lo, hi, lw))
    if doc.get("pack_devices", 1) > 1:
        # the verify farm's sharded batch: per-lane challenges/nonces,
        # GSPMD-partitioned proving hash (post/verifier.py mesh path)
        from ..ops import autotune
        from ..parallel import mesh as pmesh

        devs, _ = autotune.resolve_auto_mesh(n, batch)
        lay = pmesh.topology.get().layouts_for_devices(devs)
        chal_b = np.broadcast_to(
            np.asarray(proving.challenge_words(bytes(32)))[:, None],
            (8, batch)).copy()
        _timed(doc, f"proving_hash_mesh{len(devs)}",
               lambda: proving.proving_hash_jit(
                   lay.put_lane(chal_b),
                   lay.put_batch(np.full(batch, 7, np.uint32)),
                   lay.put_batch(lo_h), lay.put_batch(hi_h),
                   pmesh.words_to_le(
                       lay.put_lane(np.zeros((4, batch), np.uint32)))))
    return doc


def _warm_k2pow_verify(n: int, batch: int) -> dict:
    import hashlib

    import jax.numpy as jnp
    import numpy as np

    from ..ops import pow as k2pow
    from ..ops import scrypt

    # the verify path pads ragged chunks to their power-of-two bucket
    # (ops/pow.py verify_many), so warm exactly that shape
    b = max(scrypt.shape_bucket(batch), 1)
    block1 = np.stack([np.frombuffer(
        hashlib.sha256(b"warm-powv-%d" % i).digest() * 2,
        dtype=">u4").astype(np.uint32) for i in range(b)], axis=1)
    targets = np.broadcast_to(
        np.full((8, 1), 0xFFFFFFFF, dtype=np.uint32), (8, b)).copy()
    nonces = np.arange(b, dtype=np.uint64)
    lo = jnp.asarray((nonces & 0xFFFFFFFF).astype(np.uint32))
    hi = jnp.asarray((nonces >> 32).astype(np.uint32))
    doc: dict = {"batch": b}
    _timed(doc, "pow_verify_batch",
           lambda: k2pow.pow_verify_batch_jit(
               jnp.asarray(block1), lo, hi, jnp.asarray(targets)))
    return doc


def _warm_k2pow(n: int, batch: int) -> dict:
    import jax.numpy as jnp
    import numpy as np

    from ..ops import pow as k2pow

    st = jnp.asarray(k2pow.prefix_state(bytes(32), bytes(32)))
    tgt = jnp.asarray(np.full(8, 0xFFFFFFFF, dtype=np.uint32))
    nonces = np.arange(batch, dtype=np.uint64)
    lo = jnp.asarray((nonces & 0xFFFFFFFF).astype(np.uint32))
    hi = jnp.asarray((nonces >> 32).astype(np.uint32))
    doc: dict = {}
    _timed(doc, "pow_batch",
           lambda: k2pow.below_target_jit(
               k2pow.pow_hash_batch_jit(st, lo, hi), tgt))
    return doc


INIT = register(WorkloadKind(
    "init", "fused label batch + on-device VRF min-scan", _warm_init))
INIT_PACK = register(WorkloadKind(
    "init_pack", "multi-tenant packed label batch (per-lane commitments)",
    _warm_init_pack))
PROVE_SCAN = register(WorkloadKind(
    "prove_scan", "streaming prove scan step (compact+merge on device)",
    _warm_prove_scan))
VERIFY = register(WorkloadKind(
    "verify", "batched POST verify recompute (per-lane labels + hash)",
    _warm_verify))
K2POW = register(WorkloadKind(
    "k2pow", "SHA-256 k2pow nonce-search batch", _warm_k2pow))
K2POW_VERIFY = register(WorkloadKind(
    "k2pow_verify",
    "per-item-prefix k2pow witness verification batch (verifyd)",
    _warm_k2pow_verify))


# --- packed-init host helpers ------------------------------------------


@dataclasses.dataclass
class PackSegment:
    """One tenant's contiguous lane range inside a packed dispatch."""

    job: object          # scheduler _InitJob
    start: int           # global label index of the segment's first lane
    count: int           # valid lanes (pre-bucket-pad)
    lane0: int           # first lane inside the packed batch


def fold_min_host(carry, label_bytes: bytes, start_index: int):
    """Fold one segment's labels into a per-tenant VRF running minimum.

    ``carry`` is ``None`` or ``(value_u128, index)``.  Bit-identical to
    the device scan (ops/scrypt.py _stage_minscan): the label's 16
    bytes read as a little-endian u128, ties keep the EARLIER index
    (np.lexsort first-occurrence semantics — the original host path the
    device carry replaced, reused here because a packed batch spans
    many tenants and the fused single-carry argmin cannot).
    """
    import numpy as np

    if not label_bytes:
        return carry
    halves = np.frombuffer(label_bytes, dtype="<u8").reshape(-1, 2)
    lo, hi = halves[:, 0], halves[:, 1]
    # primary key hi, then lo, then index: lexsort's first element is
    # the minimum with the smallest index
    best = int(np.lexsort((np.arange(lo.shape[0]), lo, hi))[0])
    value = (int(hi[best]) << 64) | int(lo[best])
    index = start_index + best
    if carry is None or value < carry[0] \
            or (value == carry[0] and index < carry[1]):
        return (value, index)
    return carry


def min_carry_to_meta(carry) -> tuple[int | None, str | None]:
    """(vrf_nonce, vrf_nonce_value hex) for PostMetadata — the exact
    byte layout post/initializer.py persists (lo u64 || hi u64, LE)."""
    if carry is None:
        return None, None
    value, index = carry
    lo = value & 0xFFFFFFFFFFFFFFFF
    hi = value >> 64
    return index, (lo.to_bytes(8, "little") + hi.to_bytes(8, "little")).hex()
