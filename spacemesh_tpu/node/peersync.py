"""Peersync: clock-drift detection against peers.

Mirrors the reference timesync/peersync (sync.go, round.go): sample
peers' wall clocks over a request/response round, estimate the local
offset as ``server_time - (t_send + rtt/2)``, take the median across
peers, and raise when it exceeds the tolerance — a node whose clock
drifts silently misses every hare round and proposal slot, so loud
failure beats quiet divergence (the reference errors the node out).
"""

from __future__ import annotations

import asyncio
import statistics
import struct
import time
from typing import Callable, Optional

from ..utils.logging import get as get_logger

PROTOCOL = "ts/1"

log = get_logger("peersync")


class PeerSync:
    def __init__(self, server, fetch, *, wall=time.time,
                 max_drift: float = 10.0, interval: float = 60.0,
                 min_peers: int = 3,
                 on_drift: Optional[Callable[[float], None]] = None):
        """``min_peers`` is a QUORUM: one skewed/malicious peer must not
        dictate the 'median' (reference peersync requires 3 responses)."""
        self.server = server
        self.fetch = fetch
        self.wall = wall
        self.max_drift = max_drift
        self.interval = interval
        self.min_peers = min_peers
        self.on_drift = on_drift
        # last measured median offset (None before the first quorum) —
        # the clock-drift health probe (obs/health.py via node/app.py)
        # reads this instead of re-sampling the network per scrape
        self.last_offset: float | None = None
        self._stop = False
        server.register(PROTOCOL, self._serve)

    async def _serve(self, peer: bytes, data: bytes) -> bytes:
        return struct.pack("<d", self.wall())

    async def sample(self, peer: bytes) -> float | None:
        """One peer's estimated clock offset relative to ours (seconds;
        positive = the peer's clock is ahead)."""
        t0 = self.wall()
        try:
            resp = await self.server.request(peer, PROTOCOL, b"", timeout=5.0)
        except Exception:  # noqa: BLE001 — unreachable peer: no sample
            return None
        t1 = self.wall()
        if len(resp) != 8:
            return None
        (server_time,) = struct.unpack("<d", resp)
        return server_time - (t0 + (t1 - t0) / 2)

    async def check(self) -> float | None:
        """Median offset across peers, or None without enough samples."""
        peers = self.fetch.peers() if self.fetch else self.server.peers()
        samples = [s for s in await asyncio.gather(
            *(self.sample(p) for p in peers[:8])) if s is not None]
        if len(samples) < self.min_peers:
            return None
        return statistics.median(samples)

    async def run(self) -> None:
        while not self._stop:
            offset = await self.check()
            if offset is not None:
                self.last_offset = offset
            if offset is not None and abs(offset) > self.max_drift:
                log.error("clock drift %.2fs exceeds tolerance %.2fs — "
                          "fix the system clock", offset, self.max_drift)
                if self.on_drift:
                    self.on_drift(offset)
            await asyncio.sleep(self.interval)

    def stop(self) -> None:
        self._stop = True
