"""Layer clock: wall time -> LayerID ticker with awaitable layers.

Mirrors the reference's NodeClock (reference timesync/clock.go:25-44:
genesis time + layer duration drive a ticker; consumers AwaitLayer(n)).
asyncio-native; tests inject a fake time source and step it manually
(the reference injects clockwork fake clocks — SURVEY.md §4.3).
"""

from __future__ import annotations

import asyncio
import time as _time
from typing import Callable

from ..core.types import LayerID


class LayerClock:
    def __init__(self, genesis_time: float, layer_duration: float,
                 time_source: Callable[[], float] = _time.time,
                 poll_interval: float = 0.05):
        if layer_duration <= 0:
            raise ValueError("layer_duration must be positive")
        self.genesis_time = genesis_time
        self.layer_duration = layer_duration
        self._now = time_source
        self._poll = poll_interval
        # current wake generation: notify_time_changed() fires it so
        # every await_layer re-checks the (jumped) time source NOW
        self._jump: asyncio.Event | None = None

    def current_layer(self) -> LayerID:
        dt = self._now() - self.genesis_time
        if dt < 0:
            return LayerID(0)
        return LayerID(int(dt // self.layer_duration))

    def time_of(self, layer: int) -> float:
        return self.genesis_time + layer * self.layer_duration

    def genesis_reached(self) -> bool:
        return self._now() >= self.genesis_time

    def notify_time_changed(self) -> None:
        """Wake every await_layer waiter immediately: an injected time
        source jumped (chaos timeskew, a test stepping FakeTime) and
        waiters must observe the new time now, not at their next poll."""
        ev, self._jump = self._jump, None
        if ev is not None:
            ev.set()

    async def await_layer(self, layer: int) -> LayerID:
        """Sleep until ``layer`` begins (returns immediately if begun)."""
        while True:
            cur = self.current_layer()
            if self.genesis_reached() and cur >= layer:
                return cur
            delay = max(self.time_of(layer) - self._now(), 0.0)
            if self._jump is None:
                self._jump = asyncio.Event()
            ev = self._jump
            # fake clocks jump: poll with a bounded sleep so manual time
            # steps are observed promptly in tests, real time sleeps
            # long; notify_time_changed() short-circuits the poll
            try:
                await asyncio.wait_for(
                    ev.wait(), min(delay, self._poll) if delay else 0.01)
            except asyncio.TimeoutError:
                pass

    async def ticks(self):
        """Async iterator of layer starts, from the next layer onward."""
        nxt = self.current_layer() + 1 if self.genesis_reached() else 0
        while True:
            cur = await self.await_layer(nxt)
            for lyr in range(nxt, cur + 1):
                yield LayerID(lyr)
            nxt = cur + 1


class FakeTime:
    """Manually stepped time source for tests."""

    def __init__(self, start: float = 0.0):
        self.t = start

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt
