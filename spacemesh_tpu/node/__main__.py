"""Node CLI (reference cmd/node + node.go:142 GetCommand).

  python -m spacemesh_tpu.node --preset standalone [--data-dir D]
      [--config FILE.json] [--until-layer N] [--genesis-now]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import time


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="spacemesh_tpu.node")
    p.add_argument("--preset", default="standalone",
                   choices=["mainnet", "fastnet", "standalone"])
    p.add_argument("--config", help="JSON config file merged over the preset")
    p.add_argument("--data-dir")
    p.add_argument("--until-layer", type=int,
                   help="stop after this layer (default: run forever)")
    p.add_argument("--genesis-now", action="store_true",
                   help="set genesis time to now + one layer")
    p.add_argument("--api", action="store_true",
                   help="serve the JSON API on api.private_listener")
    p.add_argument("--grpc", action="store_true",
                   help="serve the gRPC API (spacemesh.v1 + v2alpha1) on "
                        "api.public_listener")
    p.add_argument("--listen", help="p2p listen addr (host:port; enables "
                   "the TCP transport)")
    p.add_argument("--bootnode", action="append", default=[],
                   help="bootstrap peer host:port (repeatable)")
    p.add_argument("--profile", metavar="OUT.pstats",
                   help="profile the node and dump cProfile stats on exit "
                        "(the reference's pprof analogue, node.go:2121)")
    a = p.parse_args(argv)

    from .app import App
    from .config import load
    from . import events as events_mod
    from ..utils import logging as slog

    # SPACEMESH_LOG_JSON=1 flips this to trace-correlated JSON lines
    # (utils/logging.py JsonFormatter; docs/OBSERVABILITY.md)
    slog.configure()

    overrides = {}
    if a.data_dir:
        overrides["data_dir"] = a.data_dir
    if a.listen:
        overrides["p2p"] = {"listen": a.listen, "bootnodes": a.bootnode}
    cfg = load(a.preset, file=a.config, overrides=overrides)
    app = App(cfg)

    async def go():
        sub = app.events.subscribe(events_mod.LayerUpdate,
                                   events_mod.AtxPublished,
                                   events_mod.PostEvent)

        async def report():
            while True:
                ev = await sub.next()
                print(json.dumps({"event": type(ev).__name__,
                                  **{k: (v.hex() if isinstance(v, bytes) else v)
                                     for k, v in ev.__dict__.items()}}),
                      flush=True)

        reporter = asyncio.ensure_future(report())
        api_started = False
        net_started = False
        try:
            if a.api:
                port = await app.start_api()
                api_started = True
                print(json.dumps({"event": "ApiStarted", "port": port}),
                      flush=True)
            if a.grpc:
                port = await app.start_public_grpc_api()
                print(json.dumps({"event": "GrpcStarted", "port": port}),
                      flush=True)
            if a.listen or cfg.p2p.bootnodes:
                addr = await app.start_network()
                net_started = True
                print(json.dumps({"event": "P2PStarted", "host": addr[0],
                                  "port": addr[1]}), flush=True)
            app.start_ops()
            await app.prepare()
            if a.genesis_now:
                # rebase the CLOCK only, after the slow prepare (POST init,
                # jit warmup) — the network id stays the configured one
                from . import clock as clock_mod

                app.clock = clock_mod.LayerClock(
                    # spacecheck: ok=SC001 real node boot: genesis anchors to actual wall time
                    time.time() + cfg.layer_duration, cfg.layer_duration)
            await app.run(until_layer=a.until_layer)
        finally:
            reporter.cancel()
            if net_started:
                await app.stop_network()
            if api_started:
                await app.api.stop()  # stop accepting before the DB closes
            await app.stop_grpc_api()  # may have started via worker_grpc
            app.close()

    profiler = None
    if a.profile:
        import cProfile

        profiler = cProfile.Profile()
        profiler.enable()
    try:
        asyncio.run(go())
    except KeyboardInterrupt:
        print("interrupted", file=sys.stderr)
        return 130
    finally:
        if profiler is not None:
            profiler.disable()
            profiler.dump_stats(a.profile)
            print(json.dumps({"event": "ProfileWritten",
                              "path": a.profile}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
