"""App: the composition root wiring every service (reference
node/node.go:583 initServices — the ONLY place cross-component wiring
happens — and :2091 startSynchronous for the lifecycle; --standalone runs
an in-proc poet + post worker, node.go:1293 launchStandalone).

Layer cadence (one asyncio task):
  layer tick
    ├─ epoch start?  -> beacon.run_epoch, atx builder for the next epoch
    ├─ miner.build(layer)          (proposal gossip)
    ├─ hare.run_layer(layer)       (rounds; output -> block -> certify)
    └─ mesh.process_layer(layer)   (tortoise tally + state application)
"""

from __future__ import annotations

import asyncio
import os
import time
from pathlib import Path

from ..consensus import activation, beacon as beacon_mod, blocks, eligibility
from ..consensus import malfeasance as malfeasance_mod
from ..consensus import hare as hare_mod
from ..consensus import mesh as mesh_mod
from ..consensus import miner as miner_mod
from ..consensus import poet as poet_mod
from ..consensus import tortoise as tortoise_mod
from ..core.hashing import sum256
from ..core.signing import Domain, EdSigner, EdVerifier
from ..core.types import Address
from ..p2p.pubsub import PubSub
from ..post import initializer as post_init
from ..post.prover import ProofParams
from ..post.service import PostClient, PostService
from ..storage import db as dbmod
from ..storage.cache import AtxCache
from ..txs import ConservativeState
from ..utils import tracing
from ..vm import VM
from ..vm import sdk as vm_sdk
from . import clock as clock_mod
from . import events as events_mod
from .config import Config


class App:
    def __init__(self, cfg: Config, *, signer: EdSigner | None = None,
                 pubsub: PubSub | None = None,
                 time_source=None):
        self.cfg = cfg
        # mutable skew over real time (chaos timeskew scenarios,
        # reference systest/chaos/timeskew.go:12); explicit time_source
        # injection (virtual-clock tests, the sim scenario engine)
        # bypasses it
        self.time_offset = 0.0
        self._time_injected = time_source is not None
        if time_source is None:
            time_source = lambda: time.time() + self.time_offset  # noqa: E731
        self.time_source = time_source
        self.data = Path(cfg.data_dir)
        self.data.mkdir(parents=True, exist_ok=True)
        prefix = cfg.genesis.genesis_id
        self.signers = self._load_or_create_identities(
            prefix, cfg.smeshing.num_identities, primary=signer)
        self.signer = self.signers[0]
        self.verifier = EdVerifier(prefix=prefix)
        self.events = events_mod.EventBus()
        self.clock = clock_mod.LayerClock(cfg.genesis.time, cfg.layer_duration,
                                          time_source=time_source)
        self.pubsub = pubsub or PubSub(node_name=self.signer.node_id)
        self.state = dbmod.open_state(self.data / "state.db",
                                      read_pool=cfg.db_read_pool)
        self.local = dbmod.open_local(self.data / "local.db")
        self.cache = AtxCache()
        self.golden_atx = sum256(b"golden", prefix)
        self._wire()
        self._tasks: list[asyncio.Task] = []
        self._hare_tasks: dict[int, asyncio.Task] = {}  # layer -> session
        self.stopped = asyncio.Event()
        self._recover_state()

    def _load_or_create_identities(self, prefix: bytes, n: int,
                                   primary: EdSigner | None = None
                                   ) -> list[EdSigner]:
        """Persisted node identities (reference node/node_identities.go:
        ed25519 keys live in the data dir and survive restarts; one node
        may host many smeshers). local.key is the primary; extras are
        local_01.key, local_02.key, ..."""
        key_dir = self.data / "identities"
        key_dir.mkdir(parents=True, exist_ok=True)
        signers: list[EdSigner] = []
        for i in range(max(n, 1)):
            if i == 0 and primary is not None:
                signers.append(primary)
                continue
            name = "local.key" if i == 0 else f"local_{i:02d}.key"
            key_file = key_dir / name
            if key_file.exists():
                signers.append(EdSigner(
                    seed=bytes.fromhex(key_file.read_text().strip()),
                    prefix=prefix))
            else:
                s = EdSigner(prefix=prefix)
                key_file.write_text(s.private_bytes().hex())
                key_file.chmod(0o600)
                signers.append(s)
        return signers

    def _wire(self) -> None:
        cfg = self.cfg
        self.oracle = eligibility.Oracle(
            self.cache, cfg.layers_per_epoch,
            slots_per_layer=cfg.slots_per_layer,
            min_weight_table=[tuple(x) for x in cfg.min_active_set_weight])
        from ..consensus.activeset import ActiveSetGenerator

        self.activeset_gen = ActiveSetGenerator(
            self.state, self.local, self.cache,
            layers_per_epoch=cfg.layers_per_epoch,
            layer_duration=cfg.layer_duration,
            genesis_time=lambda: self.clock.genesis_time,
            network_delay=cfg.activeset.network_delay,
            good_atx_percent=cfg.activeset.good_atx_percent)
        self.vm = VM(self.state, self.verifier)
        self.cstate = ConservativeState(self.state, self.vm)
        self.tortoise = tortoise_mod.Tortoise(
            self.cache, cfg.layers_per_epoch, hdist=cfg.tortoise.hdist,
            zdist=cfg.tortoise.zdist, window=cfg.tortoise.window_size,
            tracer=self._tortoise_tracer())
        self.proposal_store = mesh_mod.ProposalStore()
        self.executor = mesh_mod.Executor(self.state, self.vm, self.cstate)
        self.mesh = mesh_mod.Mesh(
            db=self.state, tortoise=self.tortoise, executor=self.executor,
            proposals=self.proposal_store, cache=self.cache)
        self.beacon = beacon_mod.ProtocolDriver(
            db=self.state, oracle=self.oracle, pubsub=self.pubsub,
            genesis_id=cfg.genesis.genesis_id, verifier=self.verifier,
            proposal_duration=cfg.beacon.proposal_duration,
            first_voting_round_duration=cfg.beacon.first_voting_round_duration,
            voting_round_duration=cfg.beacon.voting_round_duration,
            rounds_number=cfg.beacon.rounds_number,
            grace_period=cfg.beacon.grace_period,
            kappa=cfg.beacon.kappa, theta=cfg.beacon.theta,
            wall=self.time_source,
            on_fallback_used=lambda epoch, reason: self.events.emit(
                events_mod.BeaconFallback(epoch=epoch, reason=reason)))
        self.post_params = ProofParams(
            k1=cfg.post.k1, k2=cfg.post.k2, k3=cfg.post.k3,
            pow_difficulty=cfg.post.pow_difficulty_bytes)
        # ONE verification farm per node: every hot verification path
        # (ATX/ballot/certificate/malfeasance ingest, sync backfill)
        # submits to it and the scheduler coalesces device-wide batches
        # (verify/farm.py, docs/VERIFY_FARM.md)
        from ..verify.farm import VerificationFarm

        self.verify_farm = VerificationFarm(
            ed_verifier=self.verifier, post_params=self.post_params)
        # node-wide health & SLO engine (obs/health.py): windowed SLIs
        # over the metrics registry, stall watchdogs the pipelines and
        # the farm register on obs.health.HEALTH, flight bundles spooled
        # under the data dir; served as /healthz + /readyz (api/http.py)
        from ..obs.health import HealthEngine

        # with an injected time source the engine's windows/burn math
        # follow it too (deterministic SLO evaluation on a virtual
        # clock); production keeps the monotonic default
        self.health_engine = HealthEngine(
            bus=self.events, spool_dir=self.data / "flight",
            **({"time_source": self.time_source}
               if self._time_injected else {}))
        # the layer that ACTS on health verdicts (obs/remediate.py,
        # docs/SELF_HEALING.md): SloBreach/ComponentHealth events map
        # through the recovery policy onto the hooks components
        # registered beside their watchdogs; its snapshot rides into
        # every flight bundle
        from ..obs.remediate import RemediationEngine

        self.remediation = RemediationEngine(
            bus=self.events,
            **({"time_source": self.time_source}
               if self._time_injected else {}))
        self.health_engine.remediation = self.remediation
        # ROADMAP #3's failover residual: SPACEMESH_VERIFYD_URL routes
        # this node's verification through a remote verifyd service,
        # with breaker-guarded transparent fallback to the local farm
        # (verifyd/failover.py). SPACEMESH_VERIFYD_URLS (comma-
        # separated) generalizes that to a FLEET: consistent-hash
        # placement across the listed replicas, remote→remote failover
        # down the ring, local farm last (verifyd/fleet.py). Both
        # unset = exactly the local farm.
        self.failover_verifier = None
        self.fleet_verifier = None
        verify_router = self.verify_farm
        # the deadline bounds a BLACK-HOLED service (drop-everything
        # partition): without it each remote attempt would ride
        # aiohttp's default multi-minute timeout while BLOCK-lane
        # handlers wait, which is exactly the availability the
        # failover exists to protect.
        verifyd_deadline_s = float(os.environ.get(
            "SPACEMESH_VERIFYD_DEADLINE_S", "5.0"))
        verifyd_urls = os.environ.get("SPACEMESH_VERIFYD_URLS")
        verifyd_url = os.environ.get("SPACEMESH_VERIFYD_URL")
        if verifyd_urls:
            from ..verifyd.fleet import fleet_from_urls

            self.fleet_verifier = fleet_from_urls(
                [u.strip() for u in verifyd_urls.split(",")
                 if u.strip()],
                farm=self.verify_farm,
                client_id=self.signer.node_id.hex()[:16],
                deadline_s=verifyd_deadline_s, bus=self.events,
                **({"time_source": self.time_source}
                   if self._time_injected else {}))
            verify_router = self.fleet_verifier
        elif verifyd_url:
            from ..verifyd.client import VerifydClient
            from ..verifyd.failover import FailoverVerifier

            # retry=None: the breaker owns retry policy here — the
            # client's own shed-retry sleeps would stack a second
            # backoff layer in front of it and delay failover.
            self.failover_verifier = FailoverVerifier(
                remote=VerifydClient(verifyd_url,
                                     self.signer.node_id.hex()[:16],
                                     retry=None),
                farm=self.verify_farm, own_remote=True, bus=self.events,
                deadline_s=verifyd_deadline_s,
                **({"time_source": self.time_source}
                   if self._time_injected else {}))
            verify_router = self.failover_verifier
        self.verify_router = verify_router
        self.atx_handler = activation.Handler(
            db=self.state, cache=self.cache, verifier=self.verifier,
            golden_atx=self.golden_atx, post_params=self.post_params,
            labels_per_unit=cfg.post.labels_per_unit,
            scrypt_n=cfg.post.scrypt_n, pubsub=self.pubsub,
            on_atx=self._on_atx, now=self.time_source,
            farm=self.verify_router)
        from ..consensus import activation_v2

        self.atx_handler_v2 = activation_v2.HandlerV2(
            db=self.state, cache=self.cache, verifier=self.verifier,
            golden_atx=self.golden_atx, post_params=self.post_params,
            labels_per_unit=cfg.post.labels_per_unit,
            scrypt_n=cfg.post.scrypt_n, pubsub=self.pubsub,
            now=self.time_source, farm=self.verify_router)
        self.generator = blocks.Generator(
            mesh=self.mesh, proposals=self.proposal_store, cache=self.cache,
            layers_per_epoch=cfg.layers_per_epoch)
        self.certifier = blocks.Certifier(
            db=self.state, signer=self.signer, verifier=self.verifier,
            pubsub=self.pubsub, oracle=self.oracle,
            committee_size=cfg.hare.committee_size,
            threshold=cfg.hare.committee_size // 2 + 1,
            layers_per_epoch=cfg.layers_per_epoch,
            beacon_getter=self.beacon.get, farm=self.verify_router)

        self.certifier.on_certificate = self._adopt_full_certificate
        self.miners = [miner_mod.ProposalBuilder(
            signer=s, db=self.state, cache=self.cache,
            oracle=self.oracle, tortoise=self.tortoise, cstate=self.cstate,
            pubsub=self.pubsub, layers_per_epoch=cfg.layers_per_epoch,
            beacon_getter=self.beacon.get,
            activeset_gen=self.activeset_gen) for s in self.signers]
        self.miner = self.miners[0]
        def post_checker(atx, index_pos: int) -> bool:
            """True when the ATX's POST index at ``index_pos`` fails its
            recompute (InvalidPostIndex validation)."""
            import dataclasses as _dc

            from ..post import verifier as pv
            from ..post.prover import Proof as _Proof
            from ..storage import misc as _misc

            poet = _misc.poet_proof(self.state,
                                    atx.nipost.post_metadata.challenge)
            if poet is None:
                return False
            challenge = activation.nipost_challenge(atx.prev_atx,
                                                    atx.publish_epoch)
            params = _dc.replace(self.post_params, k2=1, k3=1)
            item = pv.VerifyItem(
                proof=_Proof(
                    nonce=atx.nipost.post.nonce,
                    indices=[atx.nipost.post.indices[index_pos]],
                    pow_nonce=atx.nipost.post.pow_nonce, k2=1),
                challenge=activation.post_challenge(poet.root, challenge),
                node_id=atx.node_id,
                commitment=activation.commitment_of(atx.node_id,
                                                    self.golden_atx),
                scrypt_n=cfg.post.scrypt_n,
                total_labels=atx.num_units * cfg.post.labels_per_unit)
            return not pv.verify(item, params)

        self.malfeasance = malfeasance_mod.Handler(
            db=self.state, cache=self.cache, verifier=self.verifier,
            pubsub=self.pubsub, tortoise=self.tortoise,
            post_checker=post_checker, farm=self.verify_router,
            on_malicious=lambda nid: self.events.emit(
                events_mod.Malfeasance(node_id=nid)))

        def on_double_ballot(node_id, b1, b2):
            proof = malfeasance_mod.proof_from_ballots(b1, b2)
            # track the task: the loop keeps only weak refs, and a dropped
            # publish would silently swallow the malfeasance proof
            task = asyncio.ensure_future(self.malfeasance.publish(proof))
            self._tasks.append(task)
            task.add_done_callback(
                lambda t: self._tasks.remove(t) if t in self._tasks else None)

        self.proposal_handler = miner_mod.ProposalHandler(
            db=self.state, cache=self.cache, oracle=self.oracle,
            tortoise=self.tortoise, store=self.proposal_store,
            verifier=self.verifier, pubsub=self.pubsub,
            layers_per_epoch=cfg.layers_per_epoch,
            beacon_getter=self.beacon.get,
            on_malfeasance=on_double_ballot, farm=self.verify_router)
        self.hare = hare_mod.Hare(
            signers=self.signers, verifier=self.verifier, oracle=self.oracle,
            pubsub=self.pubsub, committee_size=cfg.hare.committee_size,
            round_duration=cfg.hare.round_duration,
            iteration_limit=cfg.hare.iteration_limit,
            preround_delay=cfg.hare.preround_delay,
            layers_per_epoch=cfg.layers_per_epoch,
            beacon_of=self.beacon.get, atx_for=self._atx_of,
            proposals_for=self.proposal_store.ids_in_layer,
            on_output=self._on_hare_output, compact=cfg.hare.compact,
            committee_upgrade=cfg.hare.committee_upgrade,
            compact_enable_layer=cfg.hare.compact_enable_layer,
            wall=self.time_source)
        if cfg.poet_servers:
            # external poet daemons (reference activation/poet.go client;
            # multi-poet best-by-ticks, nipost.go getBestProof)
            from ..consensus.poet_remote import MultiPoet, RemotePoetClient

            clients = []
            for spec in cfg.poet_servers:
                host, _, port = spec.rpartition(":")
                clients.append(RemotePoetClient((host, int(port))))
            self.poet = clients[0] if len(clients) == 1 else MultiPoet(clients)
        else:
            self.poet = poet_mod.PoetService(
                poet_id=sum256(b"poet", cfg.genesis.genesis_id), ticks=64)
        self.post_service = PostService()
        self.atx_builders: list[activation.Builder] = []
        self.post_supervisor = None
        from ..p2p.pubsub import TOPIC_POET, TOPIC_TX

        self.pubsub.register(TOPIC_TX, self._on_tx)
        self.pubsub.register(TOPIC_POET, self._on_poet)
        self.server = None
        self.fetch = None
        self.syncer = None

    def _recover_state(self) -> None:
        """Warm the in-RAM caches from storage after a restart (reference
        atxsdata warmup node.go:1963 setupDBs + tortoise.Recover
        tortoise/recover.go:20): the ATX cache, then the tortoise rebuilt
        through Tortoise.recover."""
        from ..storage import atxs as atxstore
        from ..storage import misc as miscstore
        from ..storage.cache import AtxInfo

        ticks_by_id: dict[bytes, int] = {}
        for row in atxstore.all_rows(self.state):
            v = atxstore._view(row)
            if v is None:
                continue
            prev_height = ticks_by_id.get(v.prev_atx, 0)
            height = row["tick_height"]
            ticks_by_id[row["id"]] = height
            self.cache.add(v.target_epoch(), row["id"], AtxInfo(
                node_id=v.node_id,
                weight=v.num_units * max(height - prev_height, 0),
                base_height=prev_height, height=height,
                num_units=v.num_units, vrf_nonce=v.vrf_nonce,
                vrf_public_key=v.vrf_public_key))
        for node_id in miscstore.all_malicious(self.state):
            self.cache.set_malicious(node_id)

        self.tortoise = tortoise_mod.Tortoise.recover(
            self.state, self.cache, self.oracle,
            layers_per_epoch=self.cfg.layers_per_epoch,
            hdist=self.cfg.tortoise.hdist, zdist=self.cfg.tortoise.zdist,
            window=self.cfg.tortoise.window_size,
            tracer=self._tortoise_tracer())
        self._rewire_tortoise()

    def _tortoise_tracer(self):
        """One shared tracer per App: __init__ builds a tortoise in _wire
        and immediately replaces it in _recover_state — both must share
        the file handle (and replay treats the LAST init event as the
        live one, so the discarded instance's init line is harmless)."""
        if not self.cfg.tortoise.trace:
            return None
        if getattr(self, "_tracer_fn", None) is None:
            # App-lifetime handle, closed in close() (spacecheck SC004:
            # an open() that outlives its function must have an owner)
            fh = self._tracer_fh = open(
                self.data / "tortoise_trace.jsonl", "a")

            def write(line: str) -> None:
                fh.write(line + "\n")
                fh.flush()

            self._tracer_fn = write
        return self._tracer_fn

    def _rewire_tortoise(self) -> None:
        """Point every service that captured the tortoise at the recovered
        instance (recovery replaces the object built in _wire)."""
        self.mesh.tortoise = self.tortoise
        for m in self.miners:
            m.tortoise = self.tortoise
        self.proposal_handler.tortoise = self.tortoise
        self.malfeasance.tortoise = self.tortoise

    # --- networking (request/response + fetch + sync) -------------------

    def connect_network(self, net) -> None:
        """Join a transport (LoopbackNet in tests; QUIC later): exposes the
        local databases to peers and gains fetch/sync (reference
        node.go:1166-1211 wires fetch validators the same way)."""
        import struct as _struct

        from ..consensus.poet import PoetBlob
        from ..core.types import ActivationTx, Ballot, Block
        from ..p2p import fetch as fetch_mod
        from ..p2p.server import Server
        from ..p2p.sync import Syncer
        from ..storage import atxs as atxstore
        from ..storage import ballots as ballotstore
        from ..storage import blocks as blockstore
        from ..storage import layers as layerstore
        from ..storage import misc as miscstore

        self.server = Server(self.signer.node_id)
        net.join(self.server)
        self.fetch = fetch_mod.Fetch(self.server)

        # blob readers (serve our stores to peers)
        def _r(getter, encode=lambda v: v.to_bytes()):
            return lambda h: (lambda v: encode(v) if v is not None else None)(
                getter(self.state, h))

        # get_blob, not get: v2 (merged) envelope rows must be servable too
        self.fetch.set_reader(fetch_mod.HINT_ATX,
                              lambda h: atxstore.get_blob(self.state, h))
        self.fetch.set_reader(fetch_mod.HINT_BALLOT, _r(ballotstore.get))
        self.fetch.set_reader(fetch_mod.HINT_BLOCK, _r(blockstore.get))

        from ..storage import transactions as txstore_mod

        def read_tx(h: bytes):
            tx = txstore_mod.get_tx(self.state, h)
            return tx.raw if tx is not None else None

        self.fetch.set_reader(fetch_mod.HINT_TX, read_tx)

        def read_malfeasance(node_id: bytes):
            proof = miscstore.malfeasance_proof(self.state, node_id)
            return proof.to_bytes() if proof is not None else None

        self.fetch.set_reader(fetch_mod.HINT_MALFEASANCE, read_malfeasance)

        def read_active_set(set_id: bytes):
            ids = miscstore.active_set(self.state, set_id)
            return b"".join(ids) if ids is not None else None

        self.fetch.set_reader(fetch_mod.HINT_ACTIVESET, read_active_set)

        def read_poet(ref: bytes):
            proof = miscstore.poet_proof(self.state, ref)
            if proof is None:
                return None
            row = self.state.one("SELECT data FROM active_sets WHERE id=?",
                                 (b"poetcnt!" + ref[:24],))
            count = int.from_bytes(row["data"], "little") if row else 0
            return PoetBlob(proof=proof, member_count=count).to_bytes()

        self.fetch.set_reader(fetch_mod.HINT_POET, read_poet)

        # validators (ingest fetched blobs through the SAME gossip paths).
        # Every validator first checks the blob's content hash equals the
        # requested id — else one malicious peer could satisfy a fetch with
        # a different (valid-looking) object and the real one is never
        # retried from honest peers.
        from ..verify.farm import Lane

        async def v_atx(h: bytes, blob: bytes) -> bool:
            from ..core.types import ActivationTxV2

            try:
                atx = ActivationTx.from_bytes(blob)
            except Exception:  # noqa: BLE001
                atx = None
            if atx is not None and atx.id == h:
                # backfill rides the farm's SYNC lane: floods coalesce
                # into device-wide batches without starving live gossip
                return await self.atx_handler.process_async(
                    atx, lane=Lane.SYNC)
            try:  # v2: the id must be one of the envelope's identity ids
                atx2 = ActivationTxV2.from_bytes(blob)
            except Exception:  # noqa: BLE001
                return False
            if h not in {atx2.identity_atx_id(sp.node_id)
                         for sp in atx2.subposts}:
                return False
            return await self.atx_handler_v2.process_async(
                atx2, lane=Lane.SYNC)

        async def v_ballot(h: bytes, blob: bytes) -> bool:
            try:
                ballot = Ballot.from_bytes(blob)
            except Exception:  # noqa: BLE001
                return False
            if ballot.id != h:
                return False
            return await self.proposal_handler.ingest_ballot(
                ballot, lane=Lane.SYNC)

        async def v_block(h: bytes, blob: bytes) -> bool:
            try:
                block = Block.from_bytes(blob)
            except Exception:  # noqa: BLE001
                return False
            if block.id != h:
                return False
            # data availability: the executor needs the block's txs at
            # apply time — backfill best-effort now (round-1 gap: the TX
            # hint existed but nothing ever fetched it). The BLOB itself
            # is exactly what was requested, so the serving peer earns a
            # success either way; apply-time deferral (process_synced_
            # layer) guards against executing with txs still missing.
            missing = [t for t in block.tx_ids
                       if not txstore_mod.has_tx(self.state, t)]
            if missing:
                await self.fetch.get_hashes(fetch_mod.HINT_TX, missing)
            self.mesh.add_block(block)
            return True

        async def v_tx(h: bytes, blob: bytes) -> bool:
            from ..core.types import Transaction

            tx = Transaction(raw=blob)
            if tx.id != h:
                return False
            if self.vm.parse(tx) is None:
                return False
            # store for block application; historical txs may no longer be
            # mempool-admissible (nonce consumed), so storage is enough
            txstore_mod.add_tx(self.state, tx)
            self.cstate.add(tx)
            return True

        async def v_malfeasance(node_id: bytes, blob: bytes) -> bool:
            from ..core.types import MalfeasanceProof

            try:
                proof = MalfeasanceProof.from_bytes(blob)
            except Exception:  # noqa: BLE001
                return False
            # a married member's malice is proven by the OFFENDER's proof
            # (the whole equivocation set shares one proof) — accept when
            # processing it actually condemns the requested identity
            if not await self.malfeasance.process_async(proof,
                                                        lane=Lane.SYNC):
                return False
            return (proof.node_id == node_id
                    or miscstore.is_malicious(self.state, node_id))

        async def v_active_set(set_id: bytes, blob: bytes) -> bool:
            if len(blob) % 32:
                return False
            ids = [blob[i:i + 32] for i in range(0, len(blob), 32)]
            from ..consensus.miner import active_set_root

            if active_set_root(ids) != set_id:  # content-addressed
                return False
            # members we don't know yet are fetched like the reference's
            # handleSet (proposals/handler.go:225) — the declared set's
            # weight is only computable once every member resolves
            missing = [a for a in ids
                       if atxstore.get(self.state, a) is None]
            if missing:
                got = await self.fetch.get_hashes(fetch_mod.HINT_ATX,
                                                  missing)
                if not all(got.get(a) for a in missing):
                    # partial member fetch must REJECT the set blob:
                    # storing it would make fetch_active_set treat the
                    # root as resolved and never re-fetch, wedging ref-
                    # ballot validation until epoch ATX sync happens to
                    # deliver the stragglers (ADVICE r5). Returning
                    # False leaves the root unresolved so the next
                    # ballot retries the whole fetch+validate.
                    return False
            # epoch unknown at fetch time: -1 keeps the row out of the
            # pruner's epoch-horizon deletes (it prunes epoch>=0 only)
            miscstore.add_active_set(self.state, set_id, -1, ids)
            return True

        async def v_poet(h: bytes, blob: bytes) -> bool:
            from ..consensus.poet import PoetBlob

            try:
                if PoetBlob.from_bytes(blob).proof.id != h:
                    return False
            except Exception:  # noqa: BLE001
                return False
            return await self._on_poet(b"sync", blob)

        self.fetch.set_validator(fetch_mod.HINT_ATX, v_atx)
        self.fetch.set_validator(fetch_mod.HINT_BALLOT, v_ballot)
        self.fetch.set_validator(fetch_mod.HINT_BLOCK, v_block)
        self.fetch.set_validator(fetch_mod.HINT_POET, v_poet)
        self.fetch.set_validator(fetch_mod.HINT_TX, v_tx)
        self.fetch.set_validator(fetch_mod.HINT_MALFEASANCE, v_malfeasance)
        self.fetch.set_validator(fetch_mod.HINT_ACTIVESET, v_active_set)

        async def fetch_active_set(root: bytes) -> bool:
            got = await self.fetch.get_hashes(fetch_mod.HINT_ACTIVESET,
                                              [root])
            return bool(got.get(root))

        async def fetch_ballot(ballot_id: bytes) -> bool:
            got = await self.fetch.get_hashes(fetch_mod.HINT_BALLOT,
                                              [ballot_id])
            return bool(got.get(ballot_id))

        # ballots declare active sets by root; eligibility validation
        # resolves the declared set (fetching it if unseen) so nodes
        # with divergent ATX views agree on slot counts, and secondary
        # ballots fetch a missing ref ballot instead of letting gossip
        # order decide validity (ADVICE r4 + code-review r5)
        self.proposal_handler.fetch_active_set = fetch_active_set
        self.proposal_handler.fetch_ballot = fetch_ballot

        # index endpoints
        async def serve_epoch(peer: bytes, data: bytes) -> bytes:
            epoch = _struct.unpack("<I", data)[0]
            return b"".join(atxstore.ids_in_epoch(self.state, epoch))

        async def serve_layer(peer: bytes, data: bytes) -> bytes:
            layer = _struct.unpack("<I", data)[0]
            cert = miscstore.certified_block(self.state, layer)
            applied = layerstore.applied_block(self.state, layer)
            return fetch_mod.LayerData(
                ballots=ballotstore.ids_in_layer(self.state, layer),
                blocks=blockstore.ids_in_layer(self.state, layer),
                certified=cert or applied or bytes(32)).to_bytes()

        async def serve_poet_refs(peer: bytes, data: bytes) -> bytes:
            epoch = _struct.unpack("<I", data)[0]
            rows = self.state.all(
                "SELECT ref FROM poet_proofs WHERE round_id=?", (str(epoch),))
            return b"".join(r["ref"] for r in rows)

        async def serve_beacon(peer: bytes, data: bytes) -> bytes:
            epoch = _struct.unpack("<I", data)[0]
            if epoch <= 1:
                return self.beacon.get_now(epoch)  # protocol-defined bootstrap
            stored = miscstore.get_beacon(self.state, epoch)
            return stored or b""  # never serve a fabricated fallback

        async def serve_certificate(peer: bytes, data: bytes) -> bytes:
            layer = _struct.unpack("<I", data)[0]
            cert = miscstore.certificate(self.state, layer)
            return cert.to_bytes() if cert is not None else b""

        async def serve_malicious_ids(peer: bytes, data: bytes) -> bytes:
            return b"".join(miscstore.all_malicious(self.state))

        async def serve_layer_hash(peer: bytes, data: bytes) -> bytes:
            layer = _struct.unpack("<I", data)[0]
            if layer == 0xFFFFFFFF:
                # tip probe: (u32 layer, hash) of our highest aggregated
                # layer — fork finders anchor at the COMMON frontier
                tip = layerstore.last_applied(self.state)
                h = layerstore.aggregated_hash(self.state, tip)
                if tip < 0 or h is None:
                    return b""
                return _struct.pack("<I", tip) + h
            return layerstore.aggregated_hash(self.state, layer) or b""

        if self.cfg.hare.compact:
            # hare4 full exchange rides the req/resp server
            from ..consensus.hare import P_FULL_EXCHANGE

            self.hare.server = self.server
            self.server.register(P_FULL_EXCHANGE, self.hare._serve_full)

        self.server.register(fetch_mod.P_EPOCH, serve_epoch)
        self.server.register(fetch_mod.P_LAYER, serve_layer)
        self.server.register("pt/1", serve_poet_refs)
        self.server.register("bk/1", serve_beacon)
        self.server.register("ct/1", serve_certificate)
        self.server.register("ml/1", serve_malicious_ids)
        self.server.register("lh/1", serve_layer_hash)

        # sync2 rangesync: fingerprint-bisection set reconciliation over
        # per-epoch ATX ids and malfeasance ids (p2p/rangesync.py;
        # reference sync2/rangesync — there a standalone subsystem, here
        # one stateless responder on the same req/resp server)
        from ..p2p import rangesync as rangesync_mod

        # short-TTL cache: one reconciliation issues O(diff*log n)
        # request frames — rebuilding the set (DB scan + Fenwick) per
        # frame would make server work O(n) per frame (code-review r3);
        # a few seconds of staleness only means a second pass picks up
        # the newest ids
        rs_cache: dict[str, tuple[float, object]] = {}

        def set_for(name: str):
            now = self.time_source()  # TTL follows the node clock
            hit = rs_cache.get(name)
            if hit is not None and hit[0] > now:
                return hit[1]
            if name.startswith("atx/"):
                try:
                    epoch = int(name[4:])
                except ValueError:
                    return None
                oset = rangesync_mod.OrderedSet(
                    atxstore.ids_in_epoch(self.state, epoch))
            elif name == "malfeasance":
                oset = rangesync_mod.OrderedSet(
                    miscstore.all_malicious(self.state))
            else:
                return None
            if len(rs_cache) > 64:
                rs_cache.clear()
            rs_cache[name] = (now + 5.0, oset)
            return oset

        self.server.register(rangesync_mod.P_RANGESYNC,
                             rangesync_mod.RangeSyncResponder(set_for).handle)

        async def adopt_certificate(layer: int, block_id: bytes) -> bool:
            """Fetch + VERIFY the full certificate before trusting a
            peer-reported hare output (a majority of layer-data answers
            plus a threshold of validated certifier signatures)."""
            from ..core.types import Certificate
            from ..p2p.server import RequestError as _RE

            if miscstore.certified_block(self.state, layer) == block_id:
                return True
            for peer in self.fetch.peers()[:3]:
                try:
                    blob = await self.server.request(
                        peer, "ct/1", _struct.pack("<I", layer))
                except (_RE, asyncio.TimeoutError):
                    self.fetch.report_failure(peer)
                    continue
                if not blob:
                    continue
                try:
                    cert = Certificate.from_bytes(blob)
                except Exception:  # noqa: BLE001
                    self.fetch.report_failure(peer, 3)
                    continue
                if cert.block_id != block_id:
                    continue
                if await self.certifier.validate_certificate(layer, cert):
                    with self.state.tx():
                        miscstore.add_certificate(self.state, layer, cert)
                    self._adopt_full_certificate(layer, block_id)
                    return True
                self.fetch.report_failure(peer, 3)
            return False

        async def process_synced_layer(layer: int, data) -> None:
            async with tracing.span("sync.apply_layer", {"layer": layer}
                                    if tracing.is_enabled() else None):
                await _process_synced_layer(layer, data)

        async def _process_synced_layer(layer: int, data) -> None:
            from ..storage import blocks as bs

            # candidates vote-ordered; certificate VALIDATION picks the
            # real one when peers disagree (a forged cert cannot verify)
            candidates = []
            if data is not None:
                candidates = list(getattr(data, "cert_candidates", []))
                if data.certified != bytes(32) and \
                        data.certified not in candidates:
                    candidates.insert(0, data.certified)
            async def txs_ready(block) -> bool:
                # never execute a block whose txs are still missing —
                # a divergent state root is silent; defer the layer
                # so the next sync pass retries the txs
                missing = [t for t in block.tx_ids
                           if not txstore_mod.has_tx(self.state, t)]
                if missing:
                    got = await self.fetch.get_hashes(
                        fetch_mod.HINT_TX, missing)
                    return all(got.values())
                return True

            for cand in candidates:
                if await adopt_certificate(layer, cand):
                    block = bs.get(self.state, cand)
                    if block is None:
                        continue
                    if not await txs_ready(block):
                        return
                    self.mesh.process_hare_output(block, layer)
                    return
            # no validatable certificate: fall back to TORTOISE validity
            # (reference syncer/state_syncer.go processLayers applies
            # tortoise opinions when certificates are absent) — a block
            # the network applied without certifying, e.g. hare output
            # minted at a partition-merge instant, still propagates via
            # the votes of later ballots
            self.mesh.process_layer(int(self.clock.current_layer()))
            for vb in self.mesh.tortoise.valid_blocks(layer):
                block = bs.get(self.state, vb)
                if block is not None:
                    if not await txs_ready(block):
                        return
                    self.mesh.process_hare_output(block, layer)
                    return
            self.mesh.process_hare_output(None, layer)

        async def derive_beacon(epoch: int, ballot_ids: list[bytes]) -> None:
            """Beacon from ballots (reference: ballots carry the beacon in
            EpochData and the network's weight majority defines it): fetch
            raw ballot blobs WITHOUT ingestion, verify signatures and ATX
            binding, and adopt the ATX-weight-majority beacon. A lying
            peer cannot forge this — it has no weighty identities."""
            from ..core.signing import Domain as _Domain
            from ..core.types import Ballot as _Ballot

            if epoch <= 1 or miscstore.get_beacon(self.state, epoch) \
                    is not None:
                return
            votes: dict[bytes, int] = {}
            seen_nodes: set[bytes] = set()
            req = fetch_mod.HashRequest(
                hint=fetch_mod.HINT_BALLOT,
                hashes=list(dict.fromkeys(ballot_ids))[:256])
            for peer in self.fetch.peers()[:3]:
                try:
                    resp = fetch_mod.HashResponse.from_bytes(
                        await self.server.request(peer, fetch_mod.P_HASH,
                                                  req.to_bytes()))
                except Exception:  # noqa: BLE001
                    continue
                for blob in resp.blobs:
                    if not blob:
                        continue
                    try:
                        b = _Ballot.from_bytes(blob)
                    except Exception:  # noqa: BLE001
                        continue
                    if (b.epoch_data is None
                            or b.layer // self.cfg.layers_per_epoch != epoch
                            or b.node_id in seen_nodes):
                        continue
                    from ..verify.farm import SigRequest as _SigReq

                    if not await self.verify_router.submit(
                            _SigReq(int(_Domain.BALLOT), b.node_id,
                                    b.signed_bytes(), b.signature),
                            lane=Lane.SYNC):
                        continue
                    info = self.cache.get(epoch, b.atx_id)
                    if info is None or info.node_id != b.node_id:
                        continue
                    seen_nodes.add(b.node_id)
                    beacon = b.epoch_data.beacon
                    votes[beacon] = votes.get(beacon, 0) + info.weight
            if votes:
                best = max(votes.items(), key=lambda kv: kv[1])[0]
                self.beacon.on_fallback(epoch, best)

        def resume_point() -> int:
            # a crash can leave processed ahead of applied; resync from the
            # lower of the two so the state gap backfills
            return min(layerstore.processed(self.state),
                       layerstore.last_applied(self.state))

        self.syncer = Syncer(
            fetch=self.fetch, current_layer=lambda: int(self.clock.current_layer()),
            processed_layer=resume_point,
            process_layer=process_synced_layer,
            layers_per_epoch=self.cfg.layers_per_epoch,
            store_beacon=self.beacon.on_fallback,
            layer_hash=lambda lyr: layerstore.aggregated_hash(self.state, lyr),
            on_fork=self._on_fork, derive_beacon=derive_beacon,
            # client side of the rs/1 responder above: fingerprint
            # reconciliation backfills ATX ids the bulk epoch pull
            # missed; fetched blobs ingest through v_atx on the farm's
            # SYNC lane
            rangesync_sets=set_for)

    async def start_network(self) -> tuple[str, int]:
        """Open the real transport (TCP by default; QUIC-lite when
        cfg.p2p.transport == "quic" — reference p2p/host.go:166
        EnableQUICTransport) on cfg.p2p.listen, bootstrap-dial
        cfg.p2p.bootnodes, and run the syncer in the background.
        Returns the bound (host, port)."""
        if self.cfg.p2p.transport == "quic":
            from ..p2p.quic import QuicHost as Host
        else:
            from ..p2p.transport import Host

        cfg = self.cfg.p2p
        self.host = Host(
            signer=self.signer,
            genesis_id=self.cfg.genesis.genesis_id,
            listen=cfg.listen or "127.0.0.1:0",
            bootstrap=cfg.bootnodes,
            min_peers=cfg.min_peers, max_peers=cfg.max_peers,
            # ban windows / dial pacing / gossip heartbeats follow the
            # node clock, so sim/chaos timeskew reaches the transport
            time_source=self.time_source)
        addr = await self.host.start()
        self.host.join_pubsub(self.pubsub)
        self.connect_network(self.host)
        self._tasks.append(asyncio.ensure_future(self.syncer.run()))
        from .peersync import PeerSync
        from . import events as _ev

        # wall rides the node's time source: under a virtual clock the
        # drift rounds measure SIM offsets (and a scripted timeskew
        # really registers); in production this is wall time + chaos
        # offset, exactly what peers observe of us
        self.peersync = PeerSync(
            self.server, self.fetch, wall=self.time_source,
            on_drift=lambda off: self.events.emit(
                _ev.ClockDrift(offset=off)))
        self._tasks.append(asyncio.ensure_future(self.peersync.run()))
        self._register_network_probes()
        return addr

    def _register_network_probes(self) -> None:
        """Sync + clock-drift liveness on the global health registry
        (obs/health.py): while catching up, the processed frontier (or
        the sync state itself) must advance; the clock probe reports the
        peersync median offset against its tolerance."""
        from ..obs import health as health_mod
        from ..storage import layers as layerstore

        sync_wd = health_mod.Watchdog(
            "sync",
            progress=lambda: (self.syncer.state.value,
                              layerstore.processed(self.state)),
            deadline_s=120.0,
            active=lambda: (self.syncer is not None
                            and not self.syncer.is_synced()
                            and self.clock.genesis_reached()))

        def clock_probe(now: float):
            ps = getattr(self, "peersync", None)
            offset = ps.last_offset if ps is not None else None
            if offset is None:
                return True, "no quorum yet"
            tolerance = ps.max_drift
            if abs(offset) > tolerance:
                return False, (f"clock drift {offset:.2f}s exceeds "
                               f"tolerance {tolerance:.2f}s")
            return True, f"offset={offset:.3f}s"

        # keep the probe objects: unregister must be equality-checked so
        # tearing down THIS node never evicts another in-process node's
        # live probes from the shared registry (multi-App test clusters)
        self._sync_probe = sync_wd.check
        self._clock_probe = clock_probe
        health_mod.HEALTH.register("sync", self._sync_probe)
        health_mod.HEALTH.register("clock", self._clock_probe)
        # recovery hook beside the sync watchdog (obs/remediate.py): a
        # stalled-sync verdict kicks one immediate synchronize pass —
        # the restart a stuck syncer usually needs — instead of waiting
        # out its background cadence
        from ..obs import remediate as remediate_mod

        self._sync_restart = self._kick_sync
        remediate_mod.ACTIONS.register("sync", "restart_component",
                                       self._sync_restart)

    def _kick_sync(self) -> None:
        if self.syncer is None:
            return
        task = asyncio.ensure_future(self.syncer.synchronize())
        self._tasks.append(task)
        task.add_done_callback(
            lambda t: self._tasks.remove(t) if t in self._tasks else None)

    async def stop_network(self) -> None:
        # the failover/fleet verifiers' owned remote clients hold
        # aiohttp sessions and server-side registrations — both need a
        # live loop to release (the sync App.close() can only drop the
        # breaker registrations), so the async teardown path owns them
        if self.failover_verifier is not None:
            await self.failover_verifier.aclose()
        if self.fleet_verifier is not None:
            await self.fleet_verifier.aclose()
        if getattr(self, "host", None) is not None:
            from ..obs import health as health_mod
            from ..obs import remediate as remediate_mod

            if getattr(self, "_sync_probe", None) is not None:
                health_mod.HEALTH.unregister("sync", self._sync_probe)
            if getattr(self, "_clock_probe", None) is not None:
                health_mod.HEALTH.unregister("clock", self._clock_probe)
            if getattr(self, "_sync_restart", None) is not None:
                remediate_mod.ACTIONS.unregister(
                    "sync", "restart_component", self._sync_restart)
                self._sync_restart = None
            if self.syncer is not None:
                self.syncer.stop()
            if getattr(self, "peersync", None) is not None:
                self.peersync.stop()
                self.peersync = None
            await self.host.stop()
            self.host = None

    def _adopt_full_certificate(self, layer: int, block_id: bytes) -> None:
        """A threshold certificate is the committee's decision for the
        layer; a node whose own hare missed it (clock skew, late join)
        must ADOPT it or diverge permanently when the tortoise margin
        never crosses on a small committee (round-5 chaos flake). Fires
        on gossip-assembled AND sync-fetched certificates."""
        self.mesh.adopt_certified(layer, block_id)

    def _on_fork(self, divergent_layer: int) -> None:
        """Fork finder hit (reference syncer/find_fork.go): a peer's
        aggregated mesh hash diverges from ours at ``divergent_layer``
        and its chain data has been ingested. Arbitration belongs to the
        TORTOISE: tally with everything known; if the vote weight favors
        the other chain, the mesh reverts + reapplies the flipped layers
        (reference mesh.go:302 ProcessLayer reverts on opinion change).
        No blind rollback — a peer without ballot weight behind its
        chain cannot move our applied state."""
        self.mesh.process_layer(int(self.clock.current_layer()))

    # --- handlers ------------------------------------------------------

    async def _on_poet(self, peer: bytes, data: bytes) -> bool:
        from ..consensus.poet import PoetBlob

        try:
            blob = PoetBlob.from_bytes(data)
        except Exception:  # noqa: BLE001
            return False
        activation.store_poet_blob(self.state, blob)
        return True

    def _atx_of(self, epoch: int, node_id: bytes):
        """The ATX a local identity holds for ``epoch`` (cache lookup)."""
        for atx_id, info in self.cache.iter_epoch(epoch):
            if info.node_id == node_id:
                return atx_id
        return None

    def _on_atx(self, atx) -> None:
        self.events.emit(events_mod.AtxEvent(
            atx_id=atx.id, node_id=atx.node_id, epoch=atx.publish_epoch))

    async def _on_tx(self, peer: bytes, data: bytes) -> bool:
        from ..core.types import Transaction
        from ..vm.vm import TxValidity

        validity = self.cstate.add(Transaction(raw=data))
        self.events.emit(events_mod.TxEvent(
            tx_id=Transaction(raw=data).id,
            valid=validity == TxValidity.VALID))
        return validity == TxValidity.VALID

    async def _on_hare_output(self, out: hare_mod.ConsensusOutput) -> None:
        if out.coin is not None:
            self.tortoise.on_weak_coin(out.layer, out.coin)
        if not out.completed:
            # hare FAILED (iteration limit, no agreement): the layer is
            # undecided and belongs to the tortoise — recording a
            # positive "empty" decision here would poison every vote
            # within hdist (reference: no hare output; layerpatrol
            # leaves the layer to the syncer/tortoise)
            self.events.emit(events_mod.LayerUpdate(layer=out.layer,
                                                    status="hare_failed"))
            return
        async with tracing.span("mesh.hare_output", {"layer": out.layer}
                                if tracing.is_enabled() else None):
            block = self.generator.process_hare_output(out)
            self.events.emit(events_mod.LayerUpdate(layer=out.layer,
                                                    status="hare_done"))
            if block is not None:
                epoch = out.layer // self.cfg.layers_per_epoch
                for s in self.signers:
                    await self.certifier.certify_if_eligible(
                        out.layer, block.id, self._atx_of(epoch, s.node_id),
                        signer=s)

    # --- smeshing ------------------------------------------------------

    async def start_smeshing(self) -> None:
        """POST-init every identity and build one ATX Builder per signer
        (reference activation.Builder.Register, activation.go:218;
        BASELINE config 5: N smeshers in one node). With
        smeshing.external_worker, proofs come from the out-of-process
        worker via PostSupervisor + RemotePostClient."""
        cfg = self.cfg
        post_base = self.data / "post"
        for s in self.signers:
            post_dir = post_base / s.node_id.hex()[:16]
            commitment = activation.commitment_of(s.node_id, self.golden_atx)
            self.events.emit(events_mod.PostEvent(node_id=s.node_id,
                                                  kind="init_start"))
            await asyncio.to_thread(
                post_init.initialize, post_dir,
                node_id=s.node_id, commitment=commitment,
                num_units=cfg.smeshing.num_units,
                labels_per_unit=cfg.post.labels_per_unit,
                scrypt_n=cfg.post.scrypt_n,
                batch_size=cfg.smeshing.init_batch)
            self.events.emit(events_mod.PostEvent(node_id=s.node_id,
                                                  kind="init_complete"))
        clients = {}
        if cfg.smeshing.external_worker and cfg.smeshing.worker_grpc:
            # reference topology: node hosts PostService, worker dials in
            # and Registers each identity (post_service.go:91, supervisor
            # passes the node address like post_supervisor.go does)
            from ..post.supervisor import PostSupervisor

            port = await self.start_grpc_api()
            self.post_supervisor = PostSupervisor(
                post_base, params=self.post_params,
                node_address=f"127.0.0.1:{port}")
            await asyncio.to_thread(self.post_supervisor.start)
            svc = self.grpc_api.post_service
            await svc.wait_registered([s.node_id for s in self.signers],
                                      timeout=120.0)
            for s in self.signers:
                clients[s.node_id] = svc.client(s.node_id)
        elif cfg.smeshing.external_worker:
            from ..post.supervisor import PostSupervisor
            from ..post.remote import RemotePostClient

            self.post_supervisor = PostSupervisor(
                post_base, params=self.post_params)
            addr = await asyncio.to_thread(self.post_supervisor.start)
            for s in self.signers:
                clients[s.node_id] = RemotePostClient(addr, s.node_id)
        else:
            for s in self.signers:
                clients[s.node_id] = PostClient(
                    post_base / s.node_id.hex()[:16], self.post_params)
        self.atx_builders = []
        for s in self.signers:
            client = clients[s.node_id]
            self.post_service.register(s.node_id, client)
            coinbase = (Address.decode(cfg.smeshing.coinbase).raw
                        if cfg.smeshing.coinbase
                        else vm_sdk.wallet_address(s.public_key).raw)
            self.atx_builders.append(activation.Builder(
                signer=s, db=self.state, pubsub=self.pubsub,
                poet=self.poet, post_client=client,
                golden_atx=self.golden_atx, coinbase=coinbase,
                handler=self.atx_handler,
                num_units=cfg.smeshing.num_units))
        if cfg.poet_certifier:
            await self._certify_identities(cfg.poet_certifier)

    async def _certify_identities(self, addr_spec: str) -> None:
        """Obtain one poet certificate per identity from the configured
        certifier (reference activation/certifier.go:246 Certify): prove
        the POST once over a canonical per-identity challenge, submit,
        store the cert on the builder for every poet registration."""
        from ..consensus.certifier import CertifierClient

        host, _, port = addr_spec.rpartition(":")
        certifier = CertifierClient((host or "127.0.0.1", int(port)),
                                    time_source=self.time_source)
        for b in self.atx_builders:
            node_id = b.signer.node_id
            challenge = sum256(b"poet-cert-challenge", node_id)
            proof, _meta = await asyncio.to_thread(b.post_client.proof,
                                                   challenge)
            info = await asyncio.to_thread(b.post_client.info)
            b.poet_cert = await asyncio.to_thread(
                certifier.certificate, proof=proof, challenge=challenge,
                node_id=node_id, commitment=info.commitment,
                num_units=info.num_units,
                labels_per_unit=info.labels_per_unit)
            self.events.emit(events_mod.PostEvent(
                node_id=node_id, kind="certified"))

    @property
    def atx_builder(self):
        return self.atx_builders[0] if self.atx_builders else None

    async def publish_atx(self, publish_epoch: int) -> None:
        if not self.atx_builders:
            return
        from ..storage import atxs as atxstore

        # restart safety: publishing a SECOND (different) ATX for an epoch
        # already covered would be self-equivocation -> malfeasance
        builders = [b for b in self.atx_builders
                    if atxstore.by_node_in_epoch(
                        self.state, b.signer.node_id, publish_epoch) is None]
        if not builders:
            return
        # phase 0 for EVERY identity before the round runs, then one
        # builder drives the in-proc poet round (standalone) while the
        # rest await its result
        for b in builders:
            await b.register_challenge(publish_epoch)
        results = await asyncio.gather(
            builders[0].finish(publish_epoch,
                               execute_round=self.cfg.standalone),
            *(b.finish(publish_epoch) for b in builders[1:]))
        for atx in results:
            self.events.emit(events_mod.AtxPublished(
                atx_id=atx.id, node_id=atx.node_id, epoch=publish_epoch))

    # --- lifecycle -----------------------------------------------------

    async def prepare(self) -> None:
        """Smeshing setup + first ATX (targets epoch 1). Idempotent; may be
        called before run() so slow POST init/compiles don't eat layers."""
        if self.cfg.smeshing.start and self.atx_builder is None:
            await self.start_smeshing()
            await self.publish_atx(0)

    def start_ops(self) -> None:
        """Bootstrap updater + pruner background loops (reference
        bootstrap/updater.go, prune/prune.go), driven by config."""
        from . import bootstrap as bootstrap_mod
        from ..storage import misc as miscstore
        from ..consensus.miner import active_set_root

        if self.cfg.bootstrap_source:
            def on_activeset(epoch: int, ids: list[bytes]) -> None:
                miscstore.add_active_set(self.state, active_set_root(ids),
                                         epoch, ids)
                # trusted fallback feeds the generator too
                # (miner/active_set_generator.go:78 updateFallback)
                self.activeset_gen.update_fallback(epoch, ids)

            self.bootstrap = bootstrap_mod.BootstrapUpdater(
                self.cfg.bootstrap_source,
                on_beacon=self.beacon.on_fallback,
                on_activeset=on_activeset,
                cache_dir=self.data / "bootstrap")
            self._tasks.append(asyncio.ensure_future(self.bootstrap.run()))
        if self.cfg.prune_retention_layers > 0:
            self.pruner = bootstrap_mod.Pruner(
                self.state,
                retention_layers=self.cfg.prune_retention_layers,
                current_layer=lambda: int(self.clock.current_layer()),
                layers_per_epoch=self.cfg.layers_per_epoch)
            self._tasks.append(asyncio.ensure_future(self.pruner.run()))

    async def start_api(self) -> int:
        """Start the JSON API (reference startAPIServices, node.go:1603)."""
        from ..api import ApiServer

        self.api = ApiServer(self, listen=self.cfg.api.private_listener)
        self.health_engine.ensure_running()
        self.remediation.start()
        if self.failover_verifier is not None:
            self.failover_verifier.start()
        if self.fleet_verifier is not None:
            self.fleet_verifier.start()
        return await self.api.start()

    async def start_grpc_api(self) -> int:
        """Start the PRIVATE gRPC listener (loopback post_listener): the
        full spacemesh.v1 surface incl. the PostService Register seam,
        Admin, and Smesher (reference api/grpcserver/grpc.go private +
        post listeners, config.go:31-57)."""
        from ..api.rpc import GrpcApiServer

        if getattr(self, "grpc_api", None) is None:
            self.grpc_api = GrpcApiServer(
                self, listen=self.cfg.api.post_listener,
                post_query_interval=max(self.cfg.layer_duration / 20, 0.1))
            self.grpc_port = await self.grpc_api.start()
        return self.grpc_port

    async def start_public_grpc_api(self, listen: str | None = None) -> int:
        """Start the PUBLIC gRPC listener: query surface only —
        Node/Mesh/GlobalState/Transaction + all v2alpha1 services. No
        Admin (Recover wipes state), no Smesher, no PostService seam
        (reference public-services set, api/grpcserver/config.go:31-40)."""
        from ..api.rpc import GrpcApiServer

        if getattr(self, "grpc_public_api", None) is None:
            self.grpc_public_api = GrpcApiServer(
                self, listen=listen or self.cfg.api.public_listener,
                public_only=True)
            self.grpc_public_port = await self.grpc_public_api.start()
        return self.grpc_public_port

    async def stop_grpc_api(self) -> None:
        if getattr(self, "grpc_api", None) is not None:
            await self.grpc_api.stop()
            self.grpc_api = None
        if getattr(self, "grpc_public_api", None) is not None:
            await self.grpc_public_api.stop()
            self.grpc_public_api = None

    async def run(self, until_layer: int | None = None) -> None:
        """The main layer loop (callers wanting the API call start_api()
        first, as __main__ --api does)."""
        cfg = self.cfg
        if cfg.smeshing.start and self.atx_builder is None:
            await self.prepare()
        from ..storage import layers as layerstore

        self.health_engine.ensure_running()
        self.remediation.start()
        if self.failover_verifier is not None:
            self.failover_verifier.start()
        if self.fleet_verifier is not None:
            self.fleet_verifier.start()
        seen_epochs = {0}
        async for layer in self.clock.ticks():
            if layer <= layerstore.processed(self.state):
                # already processed (restart replay / clock anomalies):
                # re-running hare would overwrite the recorded opinion with
                # an empty one and trigger a bogus revert
                continue
            epoch = cfg.epoch_of(layer)
            if epoch not in seen_epochs:
                seen_epochs.add(epoch)
                # tracked so close()/kill cancels it — an untracked epoch
                # task outliving state.close() would block forever on the
                # drained read pool
                et = asyncio.ensure_future(self._epoch_start(epoch))
                self._tasks.append(et)
                et.add_done_callback(
                    lambda t: self._tasks.remove(t) if t in self._tasks
                    else None)
            # hare sessions run CONCURRENTLY with the layer loop — the
            # graded protocol's 8-round iterations legitimately outlive a
            # layer (reference runs per-layer sessions the same way);
            # proposal building must finish before the preround snapshot,
            # which preround_delay covers
            ht = asyncio.ensure_future(
                self.hare.run_layer(layer, self.clock.time_of(layer)))
            self._hare_tasks[layer] = ht
            ht.add_done_callback(self._reap_hare(layer))
            async with tracing.span("layer.build", {"layer": layer}
                                    if tracing.is_enabled() else None):
                await asyncio.gather(*(m.build(layer) for m in self.miners))
            with tracing.span("mesh.process_layer", {"layer": layer}
                              if tracing.is_enabled() else None):
                self.mesh.process_layer(layer)
            # report the frontier that is ACTUALLY applied — with hare
            # running concurrently, layer L's block typically lands after
            # this tick, and the event stream must not claim otherwise
            self.events.emit(events_mod.LayerUpdate(
                layer=self.mesh.latest_applied, status="applied"))
            if until_layer is not None and layer >= until_layer:
                break
        # drain in-flight sessions so the final layers still get their
        # hare outputs (callers stopping hard cancel via stop()/close())
        if self._hare_tasks:
            await asyncio.gather(*list(self._hare_tasks.values()),
                                 return_exceptions=True)
            self.mesh.process_layer(int(self.clock.current_layer()))
        self.stopped.set()

    def _reap_hare(self, layer: int):
        def _done(task: asyncio.Task) -> None:
            self._hare_tasks.pop(layer, None)
            if not task.cancelled() and task.exception() is not None:
                import logging

                logging.getLogger("hare").error(
                    "layer %d session failed: %r", layer, task.exception())
        return _done

    async def _epoch_start(self, epoch: int) -> None:
        participants = [
            (s, s.vrf_signer(), atx) for s in self.signers
            if (atx := self._atx_of(epoch, s.node_id)) is not None]
        await self.beacon.run_epoch(epoch, self.signer,
                                    self.signer.vrf_signer(),
                                    participants[0][2] if participants
                                    else None,
                                    participants=participants)
        if self.cfg.smeshing.start:
            await self.publish_atx(epoch)  # targets epoch+1

    def close(self) -> None:
        for t in self._hare_tasks.values():
            t.cancel()
        self._hare_tasks.clear()
        # epoch-start/background futures must die WITH the stores: one
        # surviving get_beacon() against a closed Database blocks its
        # caller forever on the drained reader pool
        for t in self._tasks:
            t.cancel()
        self._tasks.clear()
        self.remediation.close()
        if self.failover_verifier is not None:
            self.failover_verifier.shutdown()
        if self.fleet_verifier is not None:
            self.fleet_verifier.shutdown()
        self.health_engine.close()
        self.verify_farm.shutdown()
        if self.post_supervisor is not None:
            self.post_supervisor.stop()
        self.state.close()
        self.local.close()
        if getattr(self, "_tracer_fh", None) is not None:
            self._tracer_fh.close()
            self._tracer_fh = None
            self._tracer_fn = None
