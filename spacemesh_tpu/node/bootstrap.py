"""Bootstrap fallback updater + prune loop (the operational shell).

Reference parity:
- bootstrap/updater.go:114-159: polls a URL for per-epoch JSON carrying a
  fallback beacon and/or activeset; verified, cached on disk, and pushed
  to subscribers (beacon fallback + miner/hare activeset). Here the
  source is a file path or http(s)/file URL (urllib); the epoch document
  shape mirrors bootstrap/schema.json:
      {"epoch": N, "beacon": "hex8", "activeset": ["hex64", ...]}
- prune/prune.go: periodic deletion of stale data outside the retention
  window (old proposals are in-RAM here, so prune covers certificates,
  active sets, and poet proofs).
"""

from __future__ import annotations

import asyncio
import json
import urllib.request
from pathlib import Path
from typing import Callable

from ..utils.logging import get as get_logger

log = get_logger("bootstrap")


class BootstrapUpdater:
    """Poll a local path or URL for epoch fallback documents."""

    def __init__(self, source: str, *,
                 on_beacon: Callable[[int, bytes], None] | None = None,
                 on_activeset: Callable[[int, list[bytes]], None] | None = None,
                 interval: float = 30.0, cache_dir: str | Path | None = None):
        self.source = source
        self.on_beacon = on_beacon
        self.on_activeset = on_activeset
        self.interval = interval
        self.cache_dir = Path(cache_dir) if cache_dir else None
        self._seen: set[int] = set()
        self._stop = False

    def _read(self) -> list[dict]:
        if "://" in self.source:
            with urllib.request.urlopen(self.source, timeout=10) as r:
                raw = r.read()
        else:
            path = Path(self.source)
            if not path.exists():
                return []
            raw = path.read_bytes()
        doc = json.loads(raw)
        return doc if isinstance(doc, list) else [doc]

    def poll_once(self) -> int:
        """Fetch + apply any new epoch documents; returns how many."""
        try:
            docs = self._read()
        except (OSError, ValueError) as e:
            log.warning("bootstrap source unavailable: %s", e)
            return 0
        applied = 0
        for doc in docs:
            try:
                epoch = int(doc["epoch"])
                if epoch in self._seen:
                    continue
                beacon = (bytes.fromhex(doc["beacon"])
                          if doc.get("beacon") else None)
                activeset = [bytes.fromhex(a)
                             for a in doc.get("activeset", [])]
                if beacon is not None and len(beacon) != 4:
                    raise ValueError("beacon must be 4 bytes")
                if any(len(a) != 32 for a in activeset):
                    raise ValueError("activeset ids must be 32 bytes")
            except (KeyError, ValueError, TypeError) as e:
                log.warning("bad bootstrap document: %s", e)
                continue
            self._seen.add(epoch)
            if self.cache_dir is not None:
                self.cache_dir.mkdir(parents=True, exist_ok=True)
                (self.cache_dir / f"epoch-{epoch}.json").write_text(
                    json.dumps(doc))
            if beacon is not None and self.on_beacon:
                self.on_beacon(epoch, beacon)
            if activeset and self.on_activeset:
                self.on_activeset(epoch, activeset)
            applied += 1
            log.info("bootstrap epoch %d applied (beacon=%s, activeset=%d)",
                     epoch, beacon.hex() if beacon else "-", len(activeset))
        return applied

    async def run(self) -> None:
        while not self._stop:
            # poll_once does blocking I/O (urllib) — keep it off the loop
            await asyncio.to_thread(self.poll_once)
            await asyncio.sleep(self.interval)

    def stop(self) -> None:
        self._stop = True


class Pruner:
    """Periodic retention cleanup (reference prune/prune.go)."""

    def __init__(self, db, *, retention_layers: int,
                 current_layer: Callable[[], int],
                 layers_per_epoch: int, interval: float = 60.0):
        self.db = db
        self.retention = retention_layers
        self.current_layer = current_layer
        self.layers_per_epoch = layers_per_epoch
        self.interval = interval
        self._stop = False

    def prune_once(self) -> dict:
        horizon = self.current_layer() - self.retention
        if horizon <= 0:
            return {"certificates": 0, "active_sets": 0, "poet_proofs": 0}
        epoch_horizon = max(horizon // self.layers_per_epoch - 1, 0)
        with self.db.tx():
            certs = self.db.exec(
                "DELETE FROM certificates WHERE layer<?",
                (horizon,)).rowcount
            sets_ = self.db.exec(
                "DELETE FROM active_sets WHERE epoch>=0 AND epoch<?",
                (epoch_horizon,)).rowcount
            poets = self.db.exec(
                "DELETE FROM poet_proofs WHERE CAST(round_id AS INT)<?"
                " AND round_id GLOB '[0-9]*'",
                (epoch_horizon,)).rowcount
        out = {"certificates": certs, "active_sets": sets_,
               "poet_proofs": poets}
        if any(out.values()):
            log.info("pruned %s below layer %d", out, horizon)
        return out

    async def run(self) -> None:
        while not self._stop:
            # maintenance failures (a transient "database is locked"
            # from a slow reader, a full disk) must not kill the
            # retention loop for the life of the node — log and retry
            # next tick (code-review r5)
            try:
                await asyncio.to_thread(self.prune_once)
                # retention deletes leave free pages; reclaim them when
                # the freelist crosses the threshold (reference
                # sql/vacuum.go — scheduled maintenance alongside
                # pruning, not per-write)
                await asyncio.to_thread(self.db.maybe_vacuum)
            except Exception:
                log.exception("prune/vacuum tick failed; will retry")
            await asyncio.sleep(self.interval)

    def stop(self) -> None:
        self._stop = True
