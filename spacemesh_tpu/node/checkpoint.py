"""Checkpoint: state snapshot + restore ("quicksync").

Mirrors the reference checkpoint package (reference checkpoint/runner.go:31
Generate writes a JSON snapshot of accounts + essential ATX chain data at a
layer; recovery.go:111 Recover wipes the database and bootstraps from the
snapshot, preserving the node's own ATX lineage :401; triggered by the
admin API or config at startup).
"""

from __future__ import annotations

import json
import time
from pathlib import Path

from ..core.types import ActivationTx
from ..storage import atxs as atxstore
from ..storage import layers as layerstore
from ..storage import misc as miscstore
from ..storage import transactions as txstore
from ..storage.db import Database
from ..utils import fsio

VERSION = 1


def generate(db: Database, layer: int | None = None) -> dict:
    """Snapshot accounts (latest state) + all ATXs + beacons at ``layer``
    (default: last applied)."""
    if layer is None:
        layer = layerstore.last_applied(db)
    accounts = []
    for row in txstore.all_current_accounts(db):
        accounts.append({
            "address": row["address"].hex(),
            "balance": row["balance"],
            "next_nonce": row["next_nonce"],
            "template": row["template"].hex() if row["template"] else None,
            "state": row["state"].hex() if row["state"] else None,
        })
    atx_rows = db.all(
        "SELECT id, tick_height, data FROM atxs"
        " ORDER BY publish_epoch, id")
    # v2 (merged) envelopes appear once per covered identity — snapshot
    # each blob once; ticks stay per-row (synthetic per-identity ids)
    seen_blobs: set[bytes] = set()
    atxs = []
    for r in atx_rows:
        if r["data"] not in seen_blobs:
            seen_blobs.add(r["data"])
            atxs.append(r["data"].hex())
    ticks = {r["id"].hex(): r["tick_height"] for r in atx_rows}
    beacons = {str(r["epoch"]): r["beacon"].hex() for r in
               db.all("SELECT epoch, beacon FROM beacons")}
    return {
        "version": VERSION,
        # spacecheck: ok=SC001 checkpoint files record REAL wall time for operators (reference parity)
        "timestamp": int(time.time()),
        "layer": layer,
        "state_hash": (layerstore.state_hash(db, layer) or b"").hex(),
        "accounts": accounts,
        "atxs": atxs,
        "atx_ticks": ticks,
        "beacons": beacons,
    }


def write(db: Database, path: str | Path, layer: int | None = None) -> dict:
    snapshot = generate(db, layer)
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    # durable write (utils/fsio): a checkpoint exists precisely for the
    # crash case — a rename that beats its payload to the platter would
    # leave a truncated snapshot for the recovery it was meant to serve
    fsio.atomic_write_text(p, json.dumps(snapshot))
    return snapshot


def recover(db: Database, snapshot: dict, *,
            preserve_node_id: bytes | None = None) -> None:
    """Wipe consensus tables and restore from the snapshot. ATXs belonging
    to ``preserve_node_id`` that are NOT in the snapshot survive (the
    reference preserves the node's own ATX lineage so it can keep smeshing
    across a checkpoint recovery)."""
    if snapshot.get("version") != VERSION:
        raise ValueError(f"unsupported checkpoint version "
                         f"{snapshot.get('version')}")
    own: list[tuple] = []
    if preserve_node_id is not None:
        own = [tuple(r) for r in db.all(
            "SELECT id, node_id, publish_epoch, num_units, tick_height,"
            " vrf_nonce, coinbase, received, data, version FROM atxs"
            " WHERE node_id=?", (preserve_node_id,))]
    with db.tx():
        for table in ("atxs", "ballots", "blocks", "layers", "certificates",
                      "beacons", "transactions", "accounts", "rewards",
                      "poet_proofs", "active_sets"):
            db.exec(f"DELETE FROM {table}")
        layer = snapshot["layer"]
        for acct in snapshot["accounts"]:
            txstore.update_account(
                db, bytes.fromhex(acct["address"]), layer, acct["balance"],
                acct["next_nonce"],
                bytes.fromhex(acct["template"]) if acct["template"] else None,
                bytes.fromhex(acct["state"]) if acct["state"] else None)
        ticks = snapshot.get("atx_ticks", {})
        for blob_hex in snapshot["atxs"]:
            blob = bytes.fromhex(blob_hex)
            atx = None
            try:  # ONLY the parse probe — storage errors must surface
                atx = ActivationTx.from_bytes(blob)
            except Exception:  # noqa: BLE001 — not a v1 blob
                pass
            if atx is not None:
                atxstore.add(db, atx,
                             tick_height=ticks.get(atx.id.hex(), 0))
                continue
            from ..core.types import ActivationTxV2

            atx2 = ActivationTxV2.from_bytes(blob)
            atxstore.add_v2(db, atx2, tick_heights={
                sp.node_id: ticks.get(
                    atx2.identity_atx_id(sp.node_id).hex(), 0)
                for sp in atx2.subposts})
        for epoch, beacon in snapshot.get("beacons", {}).items():
            # checkpoint-derived: supersedable, like the 0002 migration's
            # default for pre-existing rows (ADVICE r2)
            miscstore.set_beacon(db, int(epoch), bytes.fromhex(beacon),
                                 source=miscstore.BEACON_FALLBACK)
        for row in own:
            db.exec(
                "INSERT OR IGNORE INTO atxs (id, node_id, publish_epoch,"
                " num_units, tick_height, vrf_nonce, coinbase, received,"
                " data, version) VALUES (?,?,?,?,?,?,?,?,?,?)", row)
        state_hash = bytes.fromhex(snapshot["state_hash"]) or bytes(32)
        layerstore.set_applied(db, layer, bytes(32), state_hash)
        layerstore.set_processed(db, layer)


def recover_file(db: Database, path: str | Path,
                 preserve_node_id: bytes | None = None) -> dict:
    snapshot = json.loads(Path(path).read_text())
    recover(db, snapshot, preserve_node_id=preserve_node_id)
    return snapshot
