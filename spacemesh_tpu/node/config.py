"""Configuration tree + presets.

Mirrors the reference's config package (reference config/config.go: every
subsystem owns a Config struct embedded in the root; config/presets
register whole profiles — fastnet/testnet/standalone; genesis id =
hash(time || extra) per config/genesis.go). JSON files merge over a preset;
explicit kwargs merge over both.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path

from ..core.hashing import sum256


@dataclasses.dataclass
class GenesisConfig:
    time: float = 0.0            # unix seconds
    extra_data: str = "tpu-mainnet"

    @property
    def genesis_id(self) -> bytes:
        """20-byte network id (reference config/genesis.go GenesisID)."""
        return sum256(str(int(self.time)).encode(), self.extra_data.encode())[:20]


@dataclasses.dataclass
class PostConfig:
    """Protocol POST params; the defaults ARE the mainnet values
    (reference config/mainnet.go:184-190 — including K3=1, which
    overrides activation/post.go's library default of 37)."""

    min_num_units: int = 4
    max_num_units: int = 1 << 20
    labels_per_unit: int = 4294967296
    scrypt_n: int = 8192
    k1: int = 26
    k2: int = 37
    k3: int = 1
    pow_difficulty: str = "000dfb23b0979b4b" + "00" * 24  # hex, 32 bytes

    @property
    def pow_difficulty_bytes(self) -> bytes:
        return bytes.fromhex(self.pow_difficulty)


@dataclasses.dataclass
class SmeshingConfig:
    start: bool = False
    coinbase: str = ""           # bech32
    data_dir: str = "post-data"
    num_units: int = 4
    init_batch: int = 1 << 13
    num_identities: int = 1      # signers per node (reference
                                 # node_identities.go multi-smesher)
    external_worker: bool = False  # prove via the out-of-proc POST worker
                                   # (PostSupervisor + RemotePostClient)
    worker_grpc: bool = False      # reference topology: worker dials the
                                   # node's gRPC PostService and Registers
                                   # (api/grpcserver/post_service.go:91)


@dataclasses.dataclass
class HareConfig:
    committee_size: int = 800
    leader_count: int = 5
    round_duration: float = 25.0
    preround_delay: float = 25.0
    iteration_limit: int = 4
    compact: bool = False        # hare4-style compact proposal ids (b4)
    committee_upgrade: list | None = None   # [layer, size] — committee
                                 # switches at that layer (reference
                                 # hare4/hare.go:52 CommitteeUpgrade)
    compact_enable_layer: int | None = None  # layer-gated plain->compact
                                 # protocol switch (node.go:915-943)


@dataclasses.dataclass
class BeaconConfig:
    kappa: int = 40
    q: str = "1/3"
    rounds_number: int = 300
    grace_period: float = 10.0
    proposal_duration: float = 30.0
    first_voting_round_duration: float = 210.0
    voting_round_duration: float = 30.0
    weak_coin_round_duration: float = 30.0
    theta: float = 0.00004
    votes_limit: int = 100


@dataclasses.dataclass
class TortoiseConfig:
    hdist: int = 10              # hare result trust distance
    zdist: int = 8
    window_size: int = 1000
    delay_layers: int = 10
    trace: bool = False          # record a replayable JSON trace
                                 # (reference node.go:688 EnableTracer)


@dataclasses.dataclass
class ActiveSetConfig:
    """Active-set generation knobs (reference miner config: networkDelay,
    goodAtxPercent; mainnet uses 30 min delay)."""

    network_delay: float = 1800.0
    good_atx_percent: int = 50


@dataclasses.dataclass
class P2PConfig:
    listen: str = "0.0.0.0:7513"
    bootnodes: list[str] = dataclasses.field(default_factory=list)
    min_peers: int = 20
    max_peers: int = 100
    network_cookie: str = ""
    transport: str = "tcp"       # "tcp" | "quic" (reference
                                 # p2p/host.go:166 EnableQUICTransport)


@dataclasses.dataclass
class APIConfig:
    public_listener: str = "0.0.0.0:9092"
    private_listener: str = "127.0.0.1:9093"
    post_listener: str = "127.0.0.1:0"


@dataclasses.dataclass
class Config:
    preset: str = ""
    data_dir: str = "data"
    layer_duration: float = 300.0          # mainnet: 5 min layers
    layers_per_epoch: int = 4032           # 2 weeks
    slots_per_layer: int = 50              # proposal slots (epoch total / lpe)
    db_read_pool: int = 4                  # read-only sqlite connections
    # (WAL snapshot readers — API/sync reads don't serialize behind the
    #  writer lock; 0 disables, :memory: databases never pool)
    min_active_set_weight: list = dataclasses.field(default_factory=list)
    # ^ [(epoch, weight)] ascending — reference miner/minweight table
    #   (config/mainnet.go MinimalActiveSetWeight).
    #   CONSENSUS PARAMETER (ADVICE r4): it enters the eligibility
    #   denominator (num_eligible_slots), so every node on a network
    #   must run the same table — like genesis config, a mismatch splits
    #   validate_slot's j >= num_slots check and partitions the network.
    activeset: ActiveSetConfig = dataclasses.field(
        default_factory=ActiveSetConfig)
    genesis: GenesisConfig = dataclasses.field(default_factory=GenesisConfig)
    post: PostConfig = dataclasses.field(default_factory=PostConfig)
    smeshing: SmeshingConfig = dataclasses.field(default_factory=SmeshingConfig)
    hare: HareConfig = dataclasses.field(default_factory=HareConfig)
    beacon: BeaconConfig = dataclasses.field(default_factory=BeaconConfig)
    tortoise: TortoiseConfig = dataclasses.field(default_factory=TortoiseConfig)
    p2p: P2PConfig = dataclasses.field(default_factory=P2PConfig)
    api: APIConfig = dataclasses.field(default_factory=APIConfig)
    poet_servers: list[str] = dataclasses.field(default_factory=list)
    poet_certifier: str = ""     # host:port of a certifier daemon; when
                                 # set, identities obtain a poet cert at
                                 # smeshing start (consensus/certifier.py)
    poet_cycle_gap: float = 43200.0        # 12 h
    standalone: bool = False
    bootstrap_source: str = ""             # file path or URL of epoch
                                           # fallback docs (bootstrap/)
    prune_retention_layers: int = 0        # 0 = pruning disabled

    def epoch_of(self, layer: int) -> int:
        return layer // self.layers_per_epoch


def _merge(obj, overrides: dict):
    for key, val in overrides.items():
        if not hasattr(obj, key):
            raise ValueError(f"unknown config key: {key}")
        cur = getattr(obj, key)
        if dataclasses.is_dataclass(cur) and isinstance(val, dict):
            _merge(cur, val)
        else:
            setattr(obj, key, val)


PRESETS = {}


def preset(name):
    def deco(fn):
        PRESETS[name] = fn
        return fn
    return deco


@preset("mainnet")
def _mainnet() -> Config:
    """Mainnet shape (reference config/mainnet.go): 5-minute layers,
    two-week epochs, 64 GiB space units at scrypt N=8192, nonzero
    min-active-set-weight floor (the dust-set defense — mainnet.go:139),
    and the historical hare committee downgrade 400 -> 50
    (mainnet.go:70-75 CommitteeUpgrade)."""
    c = Config(preset="mainnet")
    c.layer_duration = 300.0               # mainnet.go:91
    c.layers_per_epoch = 4032              # mainnet.go:93
    # PostConfig defaults ARE the mainnet values (mainnet.go:184-190)
    c.hare = HareConfig(committee_size=400,
                        committee_upgrade=[105_720, 50])
    c.tortoise = TortoiseConfig(hdist=10, zdist=2, window_size=4032)
    c.min_active_set_weight = [(0, 1_000_000)]  # mainnet.go:139-141
    c.poet_cycle_gap = 43200.0             # 12 h, mainnet.go:172
    return c


@preset("testnet")
def _testnet() -> Config:
    """Public testnet shape (reference config/presets/testnet.go):
    mainnet timing with short epochs (one day), small space units, and
    a low min-weight floor."""
    c = Config(preset="testnet")
    c.genesis.extra_data = "tpu-testnet"
    c.layer_duration = 300.0               # testnet.go:79
    c.layers_per_epoch = 288               # testnet.go:81
    c.post = PostConfig(min_num_units=2, labels_per_unit=1024,
                        scrypt_n=8192, k1=26, k2=37, k3=1)
    c.tortoise = TortoiseConfig(hdist=10, zdist=2, window_size=576)
    c.min_active_set_weight = [(0, 10_000)]  # testnet.go:104
    c.poet_cycle_gap = 7200.0              # 2 h, testnet.go:126
    return c


@preset("fastnet")
def _fastnet() -> Config:
    """Small/fast everything (reference config/presets/fastnet.go:19:
    15 s layers, 4 layers/epoch, scrypt N=2, small committees)."""
    c = Config(preset="fastnet")
    c.genesis.extra_data = "tpu-fastnet"
    c.layer_duration = 15.0
    c.layers_per_epoch = 4
    c.post = PostConfig(
        min_num_units=1, labels_per_unit=1024, scrypt_n=2, k1=12, k2=4, k3=4,
        pow_difficulty="08" + "ff" * 31)
    c.hare = HareConfig(committee_size=50, round_duration=0.7,
                        preround_delay=1.0, iteration_limit=2)
    c.beacon = BeaconConfig(kappa=40, rounds_number=4, grace_period=0.5,
                            proposal_duration=0.7,
                            first_voting_round_duration=1.4,
                            voting_round_duration=0.7,
                            weak_coin_round_duration=0.7)
    c.tortoise = TortoiseConfig(hdist=4, zdist=2, window_size=100,
                                delay_layers=4)
    c.poet_cycle_gap = 30.0
    c.activeset = ActiveSetConfig(network_delay=1.5)
    return c


@preset("standalone")
def _standalone() -> Config:
    """One in-proc node: own poet, own post worker, no external network
    (reference config/presets/standalone.go + node.go:1293
    launchStandalone)."""
    c = _fastnet()
    c.preset = "standalone"
    c.genesis.extra_data = "tpu-standalone"
    c.standalone = True
    c.smeshing.start = True
    c.smeshing.num_units = 1
    c.p2p.listen = ""
    # sub-second layers: the grading window must fit inside one epoch
    c.activeset = ActiveSetConfig(network_delay=0.05)
    return c


def load(preset_name: str = "", file: str | Path | None = None,
         overrides: dict | None = None) -> Config:
    """Preset -> JSON file -> explicit overrides (later wins)."""
    cfg = PRESETS[preset_name]() if preset_name else Config()
    if file is not None:
        _merge(cfg, json.loads(Path(file).read_text()))
    if overrides:
        _merge(cfg, overrides)
    if cfg.p2p.transport not in ("tcp", "quic"):
        # a typo'd transport must fail at startup, not silently run TCP
        raise ValueError(
            f"p2p.transport must be 'tcp' or 'quic', got "
            f"{cfg.p2p.transport!r}")
    return cfg
