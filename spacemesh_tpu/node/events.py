"""In-process event bus with buffered fan-out subscriptions.

Mirrors the reference's events package (reference events/reporter.go:
global reporter, typed Emit*/Subscribe*, buffered subscriptions with an
overflow signal streamed to the API event service). asyncio-native: each
subscription is a bounded queue; on overflow the subscription is marked
lossy (consumers resync from storage, as the reference does).
"""

from __future__ import annotations

import asyncio
import dataclasses
from collections import defaultdict
from typing import Any, Type

from ..utils import metrics


@dataclasses.dataclass
class LayerUpdate:
    layer: int
    status: str          # "tick" | "hare_done" | "applied"


@dataclasses.dataclass
class AtxEvent:
    atx_id: bytes
    node_id: bytes
    epoch: int


@dataclasses.dataclass
class BeaconEvent:
    epoch: int
    beacon: bytes


@dataclasses.dataclass
class BeaconFallback:
    """Beacon protocol could not decide; a fallback value was recorded."""

    epoch: int
    reason: str


@dataclasses.dataclass
class TxEvent:
    tx_id: bytes
    valid: bool


@dataclasses.dataclass
class PostEvent:
    node_id: bytes
    kind: str            # "init_start" | "init_complete" | "post_start" | "post_complete"
    detail: str = ""


@dataclasses.dataclass
class AtxPublished:
    atx_id: bytes
    node_id: bytes
    epoch: int


@dataclasses.dataclass
class ClockDrift:
    """Local clock drift vs the peer median exceeds tolerance."""

    offset: float


@dataclasses.dataclass
class Malfeasance:
    node_id: bytes


class Subscription:
    def __init__(self, bus: "EventBus", types: tuple, size: int):
        self._bus = bus
        self.types = types
        self.queue: asyncio.Queue = asyncio.Queue(maxsize=size)
        self.overflowed = False

    def _offer(self, ev) -> None:
        try:
            self.queue.put_nowait(ev)
        except asyncio.QueueFull:
            # the boolean marks the subscription lossy for its consumer;
            # the counter makes the loss visible to OPERATORS before any
            # consumer notices a gap in its stream
            self.overflowed = True
            metrics.events_overflows.inc(type=type(ev).__name__)

    async def next(self):
        return await self.queue.get()

    def close(self) -> None:
        self._bus._drop(self)


class EventBus:
    def __init__(self) -> None:
        self._subs: dict[type, list[Subscription]] = defaultdict(list)

    def subscribe(self, *types: Type, size: int = 256) -> Subscription:
        sub = Subscription(self, types, size)
        for t in types:
            self._subs[t].append(sub)
        return sub

    def emit(self, ev: Any) -> None:
        subs = list(self._subs.get(type(ev), ()))
        for sub in subs:
            sub._offer(ev)
        if subs:
            # deepest queue across this event's subscribers: a consumer
            # falling behind trends this toward its bound before the
            # overflow counter ever fires
            metrics.events_queue_depth.set(
                max(s.queue.qsize() for s in subs))

    def _drop(self, sub: Subscription) -> None:
        for t in sub.types:
            if sub in self._subs.get(t, ()):
                self._subs[t].remove(sub)
