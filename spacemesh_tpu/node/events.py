"""In-process event bus with buffered fan-out subscriptions.

Mirrors the reference's events package (reference events/reporter.go:
global reporter, typed Emit*/Subscribe*, buffered subscriptions with an
overflow signal streamed to the API event service). asyncio-native: each
subscription is a bounded queue; on overflow the subscription is marked
lossy (consumers resync from storage, as the reference does).
"""

from __future__ import annotations

import asyncio
import dataclasses
import time
import weakref
from collections import defaultdict, deque
from typing import Any, Type

from ..utils import metrics, sanitize


@dataclasses.dataclass
class LayerUpdate:
    layer: int
    status: str          # "tick" | "hare_done" | "applied"


@dataclasses.dataclass
class AtxEvent:
    atx_id: bytes
    node_id: bytes
    epoch: int


@dataclasses.dataclass
class BeaconEvent:
    epoch: int
    beacon: bytes


@dataclasses.dataclass
class BeaconFallback:
    """Beacon protocol could not decide; a fallback value was recorded."""

    epoch: int
    reason: str


@dataclasses.dataclass
class TxEvent:
    tx_id: bytes
    valid: bool


@dataclasses.dataclass
class PostEvent:
    node_id: bytes
    kind: str            # "init_start" | "init_complete" | "post_start" | "post_complete"
    detail: str = ""


@dataclasses.dataclass
class AtxPublished:
    atx_id: bytes
    node_id: bytes
    epoch: int


@dataclasses.dataclass
class ClockDrift:
    """Local clock drift vs the peer median exceeds tolerance."""

    offset: float


@dataclasses.dataclass
class Malfeasance:
    node_id: bytes


@dataclasses.dataclass
class SloBreach:
    """A declarative SLO's burn exceeded its budget (obs/health.py)."""

    slo: str
    sli: str
    value: float
    target: float
    burn: float


@dataclasses.dataclass
class ComponentHealth:
    """A component liveness probe changed verdict (obs/health.py)."""

    component: str
    healthy: bool
    reason: str


@dataclasses.dataclass
class RemediationAction:
    """The remediation engine decided a recovery action
    (obs/remediate.py): ``outcome`` is ok/error/no_hook/rate_limited/
    escalated/quarantined — every decision is an event, including the
    refusals, so an operator can replay WHY a component was (not)
    restarted."""

    component: str
    action: str
    outcome: str
    detail: str = ""


class Subscription:
    def __init__(self, bus: "EventBus", types: tuple, size: int):
        self._bus = bus
        self.types = types
        self.queue: asyncio.Queue = asyncio.Queue(maxsize=size)
        self.overflowed = False

    def _offer(self, ev) -> None:
        try:
            self.queue.put_nowait(ev)
        except asyncio.QueueFull:
            # the boolean marks the subscription lossy for its consumer;
            # the counter makes the loss visible to OPERATORS before any
            # consumer notices a gap in its stream
            self.overflowed = True
            metrics.events_overflows.inc(type=type(ev).__name__)

    async def next(self):
        return await self.queue.get()

    def close(self) -> None:
        self._bus._drop(self)


class EventBus:
    # a bounded ring of the last emissions, for the flight recorder: a
    # diagnostic bundle wants "what just happened" without any consumer
    # having subscribed in advance
    RECENT = 256

    def __init__(self) -> None:
        self._subs: dict[type, list[Subscription]] = defaultdict(list)
        self.recent: deque = deque(maxlen=self.RECENT)
        # the PR 7 deepest_queue race class, runtime-checked: subscriber
        # lists are loop-affine for MUTATION (owner-write = the runtime
        # twin of `# spacecheck: loop-only`); other threads may only
        # snapshot-read (deepest_queue, flight dumps)
        self._shared = sanitize.SharedField("events.bus.subs",
                                            mode="owner-write")
        _BUSES.add(self)

    def subscribe(self, *types: Type, size: int = 256) -> Subscription:
        self._shared.touch()
        sub = Subscription(self, types, size)
        for t in types:
            self._subs[t].append(sub)
        return sub

    def emit(self, ev: Any) -> None:
        self._shared.touch()
        # display timestamp for flight-bundle event dumps, never used
        # in logic or digests
        self.recent.append((time.time(), type(ev).__name__, ev))  # spacecheck: ok=SC001 wall display timestamp only
        for sub in list(self._subs.get(type(ev), ())):
            sub._offer(ev)

    def deepest_queue(self) -> int:
        """Deepest subscription queue right now (scrape-time truth).
        Snapshots the dict/lists first: collectors run from flight-dump
        worker threads while the loop thread subscribes (GIL makes the
        list() copies atomic; plain iteration would race a dict
        resize)."""
        deepest = 0
        seen: set[int] = set()
        self._shared.touch(write=False)
        for subs in list(self._subs.values()):
            for sub in list(subs):
                if id(sub) in seen:
                    continue  # multi-type subscriptions appear once
                seen.add(id(sub))
                deepest = max(deepest, sub.queue.qsize())
        return deepest

    def _drop(self, sub: Subscription) -> None:
        self._shared.touch()
        for t in sub.types:
            if sub in self._subs.get(t, ()):
                self._subs[t].remove(sub)


# The queue-depth gauge is recomputed at SCRAPE time over every live
# bus: the old emit-time write never decayed as consumers drained (or
# when the deepest subscriber closed), so /metrics reported the
# high-water mark of the last emission forever.
_BUSES: "weakref.WeakSet[EventBus]" = weakref.WeakSet()


def _collect_queue_depth() -> None:
    metrics.events_queue_depth.set(
        max((bus.deepest_queue() for bus in list(_BUSES)), default=0))


metrics.REGISTRY.add_collector(_collect_queue_depth)
