"""Node composition: config, presets, clock, events, the App wiring.

The layer-9 of SURVEY.md §1 (reference node/node.go App + config/): all
cross-component wiring happens here, nowhere else.
"""
