"""JSON-over-HTTP API server.

Service -> route map (reference api/grpcserver; JSON gateway semantics):

  NodeService        GET  /v1/node/status, /v1/node/version
  MeshService        GET  /v1/mesh/genesis, /v1/mesh/layer/{n},
                          /v1/mesh/epoch/{e}/atxs
  GlobalState        GET  /v1/account/{bech32}, /v1/account/{bech32}/rewards,
                          /v1/globalstate/root
  TransactionService POST /v1/tx/submit {"raw": hex}; GET /v1/tx/{id}
  ActivationService  GET  /v1/atx/{id}
  SmesherService     GET  /v1/smesher/status
  DebugService       GET  /v1/debug/state
  AdminService       POST /v1/admin/checkpoint {"path": ...},
                     POST /v1/admin/recover {"path": ...}
  EventsService      GET  /v1/events?timeout=s  (long-poll drain)
"""

from __future__ import annotations

import asyncio
import json

from aiohttp import web

from ..core.types import Address, Transaction
from ..node import checkpoint as checkpoint_mod
from ..node import events as events_mod
from ..storage import atxs as atxstore
from ..storage import ballots as ballotstore
from ..storage import blocks as blockstore
from ..storage import layers as layerstore
from ..storage import misc as miscstore
from ..storage import transactions as txstore
from ..vm.vm import TxValidity

API_VERSION = "v0.1.0"


def _hex(b: bytes | None) -> str | None:
    return b.hex() if b is not None else None


class ApiServer:
    def __init__(self, app, listen: str = "127.0.0.1:0"):
        self.node = app
        host, _, port = listen.rpartition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port or 0)
        self.web_app = web.Application()
        self._routes()
        self.runner: web.AppRunner | None = None
        self.actual_port: int | None = None

    def _routes(self) -> None:
        r = self.web_app.router
        r.add_get("/v1/node/status", self.node_status)
        r.add_get("/v1/node/version", self.node_version)
        r.add_get("/v1/node/peers", self.node_peers)
        r.add_get("/v1/mesh/genesis", self.mesh_genesis)
        r.add_get("/v1/mesh/layer/{layer}", self.mesh_layer)
        r.add_get("/v1/mesh/epoch/{epoch}/atxs", self.epoch_atxs)
        r.add_get("/v1/account/{address}", self.account)
        r.add_get("/v1/account/{address}/rewards", self.account_rewards)
        r.add_get("/v1/globalstate/root", self.state_root)
        r.add_post("/v1/tx/submit", self.tx_submit)
        r.add_get("/v1/tx/{tx_id}", self.tx_get)
        r.add_get("/v1/atx/{atx_id}", self.atx_get)
        r.add_get("/v1/smesher/status", self.smesher_status)
        r.add_get("/v1/debug/state", self.debug_state)
        r.add_post("/v1/admin/checkpoint", self.admin_checkpoint)
        r.add_post("/v1/admin/recover", self.admin_recover)
        r.add_post("/v1/admin/chaos/block", self.admin_chaos_block)
        r.add_post("/v1/admin/chaos/clear", self.admin_chaos_clear)
        r.add_post("/v1/admin/chaos/link", self.admin_chaos_link)
        r.add_post("/v1/admin/chaos/timeskew", self.admin_chaos_timeskew)
        r.add_get("/v1/events", self.events)
        r.add_get("/metrics", self.metrics)
        # pprof-analogue debug surface (reference node/node.go:2121-2151
        # mounts net/http/pprof): stack dumps and an on-demand CPU
        # profile, the two handles operators actually pull on a wedged
        # or hot node
        r.add_get("/debug/stacks", self.debug_stacks)
        r.add_get("/debug/profile", self.debug_profile)
        # span-trace capture (utils/tracing.py): start/stop a bounded
        # ring capture and export it as Perfetto-compatible JSON. GET
        # and POST both accepted — operators drive these with curl
        for route in ("/debug/trace/start", "/debug/trace/stop"):
            handler = (self.trace_start if route.endswith("start")
                       else self.trace_stop)
            r.add_get(route, handler)
            r.add_post(route, handler)
        r.add_get("/debug/trace/export", self.trace_export)
        # health & SLO engine surface (obs/health.py, docs/OBSERVABILITY.md):
        # /healthz is liveness (the tick loop runs), /readyz is per-
        # component readiness with reasons, /debug/flight spools a
        # diagnostic bundle on demand
        r.add_get("/healthz", self.healthz)
        r.add_get("/readyz", self.readyz)
        r.add_get("/debug/flight", self.debug_flight)
        r.add_post("/debug/flight", self.debug_flight)
        # self-healing surface (obs/remediate.py, docs/SELF_HEALING.md):
        # breaker states, recovery-action history, budgets
        r.add_get("/debug/remediation", self.debug_remediation)

    # --- lifecycle ---------------------------------------------------

    async def start(self) -> int:
        self.runner = web.AppRunner(self.web_app)
        await self.runner.setup()
        site = web.TCPSite(self.runner, self.host, self.port)
        await site.start()
        self.actual_port = site._server.sockets[0].getsockname()[1]
        return self.actual_port

    async def stop(self) -> None:
        await self.stop_event_pump()
        if self.runner is not None:
            await self.runner.cleanup()

    # --- NodeService -------------------------------------------------

    async def node_status(self, req) -> web.Response:
        n = self.node
        synced = n.syncer.is_synced() if n.syncer else True
        return web.json_response({
            "status": {
                "connected_peers": len(n.server.peers()) if n.server else 0,
                "is_synced": synced,
                "synced_layer": layerstore.processed(n.state),
                "top_layer": int(n.clock.current_layer()),
                "verified_layer": n.tortoise.verified,
            }})

    async def node_version(self, req) -> web.Response:
        return web.json_response({"version": API_VERSION})

    # --- MeshService -------------------------------------------------

    async def mesh_genesis(self, req) -> web.Response:
        g = self.node.cfg.genesis
        return web.json_response({
            "genesis_time": g.time,
            "genesis_id": g.genesis_id.hex(),
            "layer_duration": self.node.cfg.layer_duration,
            "layers_per_epoch": self.node.cfg.layers_per_epoch,
        })

    async def mesh_layer(self, req) -> web.Response:
        try:
            layer = int(req.match_info["layer"])
        except ValueError:
            raise web.HTTPBadRequest(text="layer must be an integer")
        blocks = blockstore.in_layer(self.node.state, layer)
        return web.json_response({
            "layer": layer,
            "blocks": [{
                "id": b.id.hex(),
                "tx_ids": [t.hex() for t in b.tx_ids],
                "rewards": [{"coinbase": Address(r.coinbase).encode(),
                             "weight": r.weight} for r in b.rewards],
            } for b in blocks],
            "ballots": [b.hex() for b in
                        ballotstore.ids_in_layer(self.node.state, layer)],
            "applied_block": _hex(layerstore.applied_block(self.node.state,
                                                           layer)),
            "state_hash": _hex(layerstore.state_hash(self.node.state, layer)),
            "certified": _hex(miscstore.certified_block(self.node.state,
                                                        layer)),
        })

    async def epoch_atxs(self, req) -> web.Response:
        try:
            epoch = int(req.match_info["epoch"])
        except ValueError:
            raise web.HTTPBadRequest(text="epoch must be an integer")
        ids = atxstore.ids_in_epoch(self.node.state, epoch)
        return web.json_response({"epoch": epoch,
                                  "atxs": [i.hex() for i in ids]})

    # --- GlobalState -------------------------------------------------

    def _addr(self, req) -> bytes:
        raw = req.match_info["address"]
        try:
            if raw.startswith("0x"):
                return Address(bytes.fromhex(raw[2:])).raw  # length-checked
            return Address.decode(raw).raw
        except ValueError as e:
            raise web.HTTPBadRequest(text=f"bad address: {e}")

    async def account(self, req) -> web.Response:
        addr = self._addr(req)
        row = txstore.account(self.node.state, addr)
        return web.json_response({
            "address": Address(addr).encode(),
            "balance": row["balance"] if row else 0,
            "next_nonce": row["next_nonce"] if row else 0,
            "template": _hex(row["template"]) if row else None,
        })

    async def account_rewards(self, req) -> web.Response:
        addr = self._addr(req)
        rewards = miscstore.rewards_for(self.node.state, addr)
        return web.json_response({
            "rewards": [{"layer": lyr, "total": total}
                        for lyr, total in rewards]})

    async def state_root(self, req) -> web.Response:
        layer = layerstore.last_applied(self.node.state)
        return web.json_response({
            "layer": layer,
            "root": _hex(layerstore.state_hash(self.node.state, layer))})

    # --- Transactions ------------------------------------------------

    async def tx_submit(self, req) -> web.Response:
        try:
            body = await req.json()
            raw = bytes.fromhex(body["raw"])
        except (json.JSONDecodeError, KeyError, ValueError, TypeError):
            raise web.HTTPBadRequest(text='expected {"raw": "<hex>"}')
        tx = Transaction(raw=raw)
        validity = self.node.cstate.add(tx)
        if validity == TxValidity.VALID:
            from ..p2p.pubsub import TOPIC_TX

            await self.node.pubsub.publish(TOPIC_TX, raw)
        return web.json_response({
            "tx_id": tx.id.hex(),
            "status": validity.name,
            "accepted": validity == TxValidity.VALID,
        }, status=200 if validity == TxValidity.VALID else 422)

    async def tx_get(self, req) -> web.Response:
        try:
            tx_id = bytes.fromhex(req.match_info["tx_id"])
        except ValueError:
            raise web.HTTPBadRequest(text="tx id must be hex")
        tx = txstore.get_tx(self.node.state, tx_id)
        if tx is None:
            raise web.HTTPNotFound(text="unknown transaction")
        res = txstore.result(self.node.state, tx_id)
        return web.json_response({
            "tx_id": tx_id.hex(),
            "raw": tx.raw.hex(),
            "result": None if res is None else {
                "status": res.status, "message": res.message,
                "gas_consumed": res.gas_consumed, "fee": res.fee,
                "layer": res.layer,
            }})

    # --- Activation / Smesher ----------------------------------------

    async def atx_get(self, req) -> web.Response:
        try:
            atx_id = bytes.fromhex(req.match_info["atx_id"])
        except ValueError:
            raise web.HTTPBadRequest(text="atx id must be hex")
        atx = atxstore.get(self.node.state, atx_id)
        if atx is None:
            raise web.HTTPNotFound(text="unknown atx")
        return web.json_response({
            "id": atx_id.hex(),
            "node_id": atx.node_id.hex(),
            "publish_epoch": atx.publish_epoch,
            "num_units": atx.num_units,
            "coinbase": Address(atx.coinbase).encode(),
            "prev_atx": atx.prev_atx.hex(),
            "tick_height": atxstore.tick_height(self.node.state, atx_id),
        })

    async def smesher_status(self, req) -> web.Response:
        n = self.node
        registered = (n.post_service.registered()
                      if n.post_service is not None else [])
        return web.json_response({
            "smeshing": n.atx_builder is not None,
            "node_id": n.signer.node_id.hex(),
            "registered_post_identities": [i.hex() for i in registered],
        })

    # --- Debug / Admin -----------------------------------------------

    async def debug_state(self, req) -> web.Response:
        n = self.node
        return web.json_response({
            "verified_layer": n.tortoise.verified,
            "processed_layer": layerstore.processed(n.state),
            "last_applied": layerstore.last_applied(n.state),
            "tortoise_mode": n.tortoise.mode,
            "sync_state": n.syncer.state.value if n.syncer else None,
            "identities": [s.node_id.hex() for s in n.signers],
            "mempool": n.cstate.pending_count(),
            "malicious_identities":
                [i.hex() for i in miscstore.all_malicious(n.state)],
        })

    async def node_peers(self, req) -> web.Response:
        """Connected peers with fetch scores (reference admin/debug peer
        listings)."""
        n = self.node
        peers = []
        if n.server is not None:
            for pid in n.server.peers():
                entry = {"node_id": pid.hex(),
                         "failure_score": (n.fetch.failure_score(pid)
                                           if n.fetch else 0)}
                host = getattr(n, "host", None)
                if host is not None and pid in host.nodes:
                    conn = host.nodes[pid]
                    if conn.listen_addr:
                        entry["address"] = (f"{conn.listen_addr[0]}:"
                                            f"{conn.listen_addr[1]}")
                    entry["outbound"] = conn.outbound
                peers.append(entry)
        return web.json_response({"peers": peers})

    async def admin_checkpoint(self, req) -> web.Response:
        try:
            body = await req.json()
            path = body["path"]
        except (json.JSONDecodeError, KeyError, TypeError):
            raise web.HTTPBadRequest(text='expected {"path": ...}')
        # off the event loop: a large snapshot must not stall consensus
        snap = await asyncio.to_thread(checkpoint_mod.write,
                                       self.node.state, path)
        return web.json_response({"layer": snap["layer"],
                                  "accounts": len(snap["accounts"]),
                                  "atxs": len(snap["atxs"])})

    async def admin_recover(self, req) -> web.Response:
        try:
            body = await req.json()
            path = body["path"]
        except (json.JSONDecodeError, KeyError, TypeError):
            raise web.HTTPBadRequest(text='expected {"path": ...}')
        snap = await asyncio.to_thread(
            checkpoint_mod.recover_file, self.node.state, path,
            self.node.signer.node_id)
        return web.json_response({"recovered_layer": snap["layer"]})

    # --- debug/profiling (reference node/node.go:2121-2151 pprof) -----

    async def debug_stacks(self, req) -> web.Response:
        """Every thread's stack plus every asyncio task — the
        goroutine-dump equivalent for diagnosing a wedged node."""
        import io
        import sys
        import traceback

        buf = io.StringIO()
        frames = sys._current_frames()
        for tid, frame in frames.items():
            buf.write(f"--- thread {tid} ---\n")
            traceback.print_stack(frame, file=buf)
        buf.write(f"\n=== asyncio tasks "
                  f"({len(asyncio.all_tasks())}) ===\n")
        for task in asyncio.all_tasks():
            buf.write(f"--- {task.get_name()}"
                      f"{' (current)' if task == asyncio.current_task() else ''}\n")
            stack = task.get_stack(limit=8)
            for frame in stack:
                buf.write("".join(traceback.format_stack(frame, limit=1)))
        return web.Response(text=buf.getvalue(),
                            content_type="text/plain")

    async def debug_profile(self, req) -> web.Response:
        """CPU-profile the node for ?seconds=N (default 5, max 60) and
        return cProfile stats ordered by cumulative time — the
        /debug/pprof/profile analogue."""
        import cProfile
        import io
        import pstats

        try:
            seconds = min(float(req.query.get("seconds", 5)), 60.0)
        except ValueError:
            raise web.HTTPBadRequest(text="seconds must be a number")
        prof = cProfile.Profile()
        try:
            prof.enable()
        except ValueError:
            # another profiler is live: the node's --profile whole-run
            # profiler (node/__main__.py), or a concurrent request —
            # only one cProfile may be active per interpreter
            raise web.HTTPConflict(
                text="another profiler is already active")
        try:
            await asyncio.sleep(seconds)
        finally:
            prof.disable()
        buf = io.StringIO()
        pstats.Stats(prof, stream=buf).sort_stats("cumulative") \
            .print_stats(40)
        return web.Response(text=buf.getvalue(),
                            content_type="text/plain")

    # --- health & SLO engine (obs/health.py) --------------------------

    def _engine(self):
        return getattr(self.node, "health_engine", None)

    async def healthz(self, req) -> web.Response:
        """Liveness: 200 while the health engine's tick loop is not
        wedged (or when no engine is attached — a serving process with
        nothing registered is alive by definition)."""
        engine = self._engine()
        if engine is None:
            return web.json_response({"status": "ok", "engine": False})
        if not engine.live():
            return web.json_response(
                {"status": "wedged",
                 "detail": "health tick loop missed 3+ intervals"},
                status=503)
        return web.json_response({"status": "ok", "engine": True})

    async def readyz(self, req) -> web.Response:
        """Per-component readiness with reasons + SLO state. 503 while
        any registered component probe fails."""
        engine = self._engine()
        if engine is not None:
            # serves the background loop's cached report when fresh; a
            # loop-less embedder evaluates inline with the flight dump
            # deferred, and the dump (trace-ring serialization + disk
            # writes) is flushed off the loop so a readiness poll can't
            # stall gossip exactly when the node is unhealthy
            report = engine.current_report()
            if engine._pending_dump is not None:
                await asyncio.to_thread(engine.flush_dump)
        else:
            # no engine (stub embedders): probes from the global health
            # registry still answer, without SLI/SLO evaluation
            from ..obs import health as health_mod

            components = health_mod.HEALTH.report()
            report = {"ready": all(e["healthy"]
                                   for e in components.values()),
                      "components": components, "slos": {}, "slis": {}}
        from ..obs import remediate as remediate_mod

        breakers = remediate_mod.BREAKERS.states()
        if breakers:
            # breaker states ride the readiness report (a COPY — the
            # engine's cached report must not accrete keys): an open
            # breaker is not unreadiness (the fallback is carrying the
            # load), but it is the first thing an operator should see
            report = {**report, "breakers": breakers}
        return web.json_response(
            report, status=200 if report["ready"] else 503)

    async def debug_remediation(self, req) -> web.Response:
        """Breaker states, action history, and budgets — the
        self-healing node's introspection surface."""
        from ..obs import remediate as remediate_mod

        engine = getattr(self.node, "remediation", None)
        if engine is not None:
            doc = engine.snapshot()
        else:
            doc = {"breakers": remediate_mod.BREAKERS.snapshot(),
                   "actions": [], "budgets": {}, "quarantined": []}
        fv = getattr(self.node, "failover_verifier", None)
        if fv is not None:
            doc["failover"] = fv.state_doc()
        return web.json_response(doc)

    async def debug_flight(self, req) -> web.Response:
        """Spool a flight bundle NOW (manual trigger; bypasses the
        breach rate limit)."""
        engine = self._engine()
        if engine is None:
            raise web.HTTPConflict(text="no health engine attached")
        reason = req.query.get("reason", "manual")
        path = await asyncio.to_thread(engine.dump_flight, reason)
        if path is None:
            raise web.HTTPConflict(
                text="no flight spool dir configured on the engine")
        return web.json_response({"bundle": path, "reason": reason})

    # --- span-trace capture (docs/OBSERVABILITY.md) -------------------

    async def trace_start(self, req) -> web.Response:
        """Begin (or restart) a span capture. ?capacity=N bounds the
        ring; ?jax=1 bridges spans into jax.profiler annotations."""
        from ..utils import metrics, tracing

        try:
            capacity = req.query.get("capacity")
            capacity = int(capacity) if capacity else None
            jax_q = req.query.get("jax")
            jax_bridge = (jax_q not in ("", "0", "off", None)
                          if jax_q is not None else None)
        except ValueError:
            raise web.HTTPBadRequest(text="capacity must be an integer")
        tracing.start(capacity=capacity, jax_bridge=jax_bridge)
        metrics.trace_enabled_gauge.set(1)
        metrics.trace_spans_gauge.set(0)
        return web.json_response({
            "enabled": True,
            "capacity": tracing.TRACER.capacity,
            "jax_bridge": tracing.TRACER.jax_bridge,
        })

    async def trace_stop(self, req) -> web.Response:
        from ..utils import metrics, tracing

        retained = tracing.stop()
        metrics.trace_enabled_gauge.set(0)
        metrics.trace_spans_gauge.set(tracing.TRACER.recorded())
        return web.json_response({
            "enabled": False,
            "spans_retained": retained,
            "spans_recorded": tracing.TRACER.recorded(),
        })

    async def trace_export(self, req) -> web.Response:
        """The capture as Chrome trace-event JSON — save the body and
        open it at https://ui.perfetto.dev. Exporting does not stop the
        capture; a live capture exports its current ring."""
        from ..utils import metrics, tracing

        metrics.trace_spans_gauge.set(tracing.TRACER.recorded())
        # a big ring materializes AND serializes slowly; do both off the
        # loop (export() tolerates concurrent recording)
        body = await asyncio.to_thread(
            lambda: json.dumps(tracing.export()))
        return web.Response(text=body, content_type="application/json")

    # --- chaos fault injection (systest harness; reference
    # systest/chaos/{partition,timeskew}.go) ---------------------------

    async def admin_chaos_block(self, req) -> web.Response:
        """Sever + refuse peers by listen address: the partition lever
        the cluster harness pulls (transport Host.chaos_block)."""
        host = getattr(self.node, "host", None)
        if host is None:
            raise web.HTTPConflict(text="no transport host")
        try:
            body = await req.json()
            addrs = []
            for spec in body.get("addrs", []):
                h, _, p = spec.rpartition(":")
                addrs.append((h, int(p)))
        except (json.JSONDecodeError, ValueError, TypeError, AttributeError):
            raise web.HTTPBadRequest(text='expected {"addrs": ["ip:port"]}')
        host.chaos_block(addrs=addrs)
        return web.json_response({"blocked": len(addrs)})

    async def admin_chaos_clear(self, req) -> web.Response:
        host = getattr(self.node, "host", None)
        if host is None:
            raise web.HTTPConflict(text="no transport host")
        host.chaos_clear()
        return web.json_response({"ok": True})

    async def admin_chaos_link(self, req) -> web.Response:
        """Degrade this node's gossip relays (loss/delay/jitter/dup):
        the link-quality lever for scripted scenarios over real
        transports (Host.chaos_link; sim/faults.py link_policy is the
        in-proc twin). Empty body = clean links."""
        host = getattr(self.node, "host", None)
        if host is None:
            raise web.HTTPConflict(text="no transport host")
        try:
            body = await req.json() if req.can_read_body else {}
            # AttributeError below: valid JSON that isn't an object
            # ('[1]', 'null') must be a 400, not an unhandled 500
            kwargs = {k: float(body.get(k, 0.0))
                      for k in ("loss", "delay", "jitter", "dup")}
            kwargs["seed"] = int(body.get("seed", 0))
        except (json.JSONDecodeError, ValueError, TypeError,
                AttributeError):
            raise web.HTTPBadRequest(
                text='expected {"loss": p, "delay": s, "jitter": s, '
                     '"dup": p, "seed": n}')
        host.chaos_link(**kwargs)
        return web.json_response({"ok": True, **{
            k: v for k, v in kwargs.items() if k != "seed"}})

    async def admin_chaos_timeskew(self, req) -> web.Response:
        """Shift this node's clock by offset seconds (0 heals)."""
        try:
            body = await req.json()
            offset = float(body["offset"])
        except (json.JSONDecodeError, KeyError, ValueError, TypeError):
            raise web.HTTPBadRequest(text='expected {"offset": seconds}')
        self.node.time_offset = offset
        return web.json_response({"offset": offset})

    async def metrics(self, req) -> web.Response:
        from ..consensus.tortoise import FULL
        from ..utils.metrics import (
            REGISTRY,
            applied_gauge,
            layer_gauge,
            peers_gauge,
            sync_state_gauge,
            tortoise_mode_gauge,
            verified_gauge,
        )

        n = self.node
        layer_gauge.set(int(n.clock.current_layer()))
        verified_gauge.set(n.tortoise.verified)
        applied_gauge.set(layerstore.last_applied(n.state))
        peers_gauge.set(len(n.server.peers()) if n.server else 0)
        tortoise_mode_gauge.set(1 if n.tortoise.mode == FULL else 0)
        if n.syncer is not None:
            from ..p2p.sync import SyncState

            sync_state_gauge.set({SyncState.NOT_SYNCED: 0,
                                  SyncState.GOSSIP: 1,
                                  SyncState.SYNCED: 2}[n.syncer.state])
        from ..obs.federate import FEDERATION

        # local registry, then every federated child's proc= series
        return web.Response(text=REGISTRY.expose() + FEDERATION.expose(),
                            content_type="text/plain")

    # --- Events ------------------------------------------------------

    _EVENT_TYPES = (events_mod.LayerUpdate, events_mod.AtxEvent,
                    events_mod.TxEvent, events_mod.BeaconEvent,
                    events_mod.PostEvent, events_mod.AtxPublished,
                    events_mod.Malfeasance)
    _RING = 1024

    def _ensure_event_pump(self) -> None:
        """ONE persistent subscription feeding a seq-numbered ring buffer:
        long-poll clients resume from ?since=<seq> and never lose events
        that fired between two polls (the reference's streaming services
        are persistent for the same reason)."""
        if getattr(self, "_event_pump", None) is not None:
            return
        self._event_ring: list = []
        self._event_seq = 0
        self._event_waiters: list[asyncio.Event] = []
        sub = self.node.events.subscribe(*self._EVENT_TYPES,
                                         size=self._RING)

        async def pump():
            while True:
                ev = await sub.next()
                self._event_seq += 1
                self._event_ring.append((self._event_seq, ev))
                del self._event_ring[:-self._RING]
                for w in self._event_waiters:
                    w.set()

        self._event_pump = asyncio.ensure_future(pump())

    async def events(self, req) -> web.Response:
        self._ensure_event_pump()
        try:
            timeout = min(max(float(req.query.get("timeout", "1.0")), 0.0),
                          60.0)
            since = int(req.query.get("since", "0"))
        except ValueError:
            raise web.HTTPBadRequest(text="timeout/since must be numeric")

        def collect():
            return [(seq, ev) for seq, ev in self._event_ring if seq > since]

        got = collect()
        if not got and timeout > 0:
            waiter = asyncio.Event()
            self._event_waiters.append(waiter)
            try:
                await asyncio.wait_for(waiter.wait(), timeout)
            except asyncio.TimeoutError:
                pass
            finally:
                self._event_waiters.remove(waiter)
            got = collect()
        out = [{"seq": seq, "type": type(ev).__name__,
                **{k: (v.hex() if isinstance(v, bytes) else v)
                   for k, v in ev.__dict__.items()}} for seq, ev in got]
        return web.json_response({"events": out,
                                  "next_since": got[-1][0] if got else since})

    async def stop_event_pump(self) -> None:
        pump = getattr(self, "_event_pump", None)
        if pump is not None:
            pump.cancel()
