"""spacemesh.v2alpha1 services: the reference's paginated query API.

Reference api/grpcserver/v2alpha1/{activation,account,layer,malfeasance,
network,node,reward,transaction}.go — eight unary services with the
limit-capped-at-100 pagination contract, plus the five Stream services
(stored rows matching the filter first; with ``watch=true`` the stream
then follows live events until the client cancels — activation.go:51-160
Stream).

Registered as generic handlers on the same grpc.aio server as the v1
surface (api/rpc.py GrpcApiServer)."""

from __future__ import annotations

import grpc

from ..core.types import Address
from ..node import events as events_mod
from ..storage import atxs as atxstore
from ..storage import layers as layerstore
from ..storage import misc as miscstore
from .gen import v2alpha1_pb2 as v2
from .rpc import _server_stream, _unary

_DOMAINS = {1: "multiple_atxs", 2: "multiple_ballots", 3: "hare_equivocation",
            4: "invalid_post_index", 5: "invalid_prev_atx"}


class _RecentSet:
    """Bounded membership window for stream dedup: ids only ever repeat
    within the drain/subscribe overlap, so a sliding window gives the
    same dedup as an unbounded set without growing for the lifetime of a
    long-lived watch stream."""

    def __init__(self, cap: int = 8192):
        from collections import deque

        self._cap = cap
        self._set: set = set()
        self._order = deque()

    def add(self, item) -> None:
        if item in self._set:
            return
        self._set.add(item)
        self._order.append(item)
        if len(self._order) > self._cap:
            self._set.discard(self._order.popleft())

    def __contains__(self, item) -> bool:
        return item in self._set


async def _check_limit(req, ctx) -> bool:
    """The reference's pagination contract (activation.go:193-199)."""
    if req.limit > 100:
        await ctx.abort(grpc.StatusCode.INVALID_ARGUMENT,
                        "limit is capped at 100")
    if req.limit == 0:
        await ctx.abort(grpc.StatusCode.INVALID_ARGUMENT,
                        "limit must be set to <= 100")
    return True


class V2AlphaServices:
    """All v2alpha1 handlers over one App (the db handles + event bus)."""

    def __init__(self, app):
        self.node = app

    def handlers(self) -> tuple:
        h = grpc.method_handlers_generic_handler
        return (
            h("spacemesh.v2alpha1.ActivationService", {
                "List": _unary(self._atx_list, v2.ActivationRequest,
                               v2.ActivationList),
                "ActivationsCount": _unary(
                    self._atx_count, v2.ActivationsCountRequest,
                    v2.ActivationsCountResponse),
            }),
            h("spacemesh.v2alpha1.ActivationStreamService", {
                "Stream": _server_stream(
                    self._atx_stream, v2.ActivationStreamRequest,
                    v2.Activation),
            }),
            h("spacemesh.v2alpha1.RewardService", {
                "List": _unary(self._reward_list, v2.RewardRequest,
                               v2.RewardList),
            }),
            h("spacemesh.v2alpha1.RewardStreamService", {
                "Stream": _server_stream(
                    self._reward_stream, v2.RewardStreamRequest, v2.Reward),
            }),
            h("spacemesh.v2alpha1.LayerService", {
                "List": _unary(self._layer_list, v2.LayerRequest,
                               v2.LayerList),
            }),
            h("spacemesh.v2alpha1.LayerStreamService", {
                "Stream": _server_stream(
                    self._layer_stream, v2.LayerStreamRequest, v2.Layer),
            }),
            h("spacemesh.v2alpha1.MalfeasanceService", {
                "List": _unary(self._malfeasance_list, v2.MalfeasanceRequest,
                               v2.MalfeasanceList),
            }),
            h("spacemesh.v2alpha1.MalfeasanceStreamService", {
                "Stream": _server_stream(
                    self._malfeasance_stream, v2.MalfeasanceStreamRequest,
                    v2.MalfeasanceProof),
            }),
            h("spacemesh.v2alpha1.NetworkService", {
                "Info": _unary(self._network_info, v2.NetworkInfoRequest,
                               v2.NetworkInfoResponse),
            }),
            h("spacemesh.v2alpha1.NodeService", {
                "Status": _unary(self._node_status, v2.NodeStatusRequest,
                                 v2.NodeStatusResponse),
            }),
            h("spacemesh.v2alpha1.AccountService", {
                "List": _unary(self._account_list, v2.AccountRequest,
                               v2.AccountList),
            }),
            h("spacemesh.v2alpha1.TransactionService", {
                "List": _unary(self._tx_list, v2.TransactionRequest,
                               v2.TransactionList),
            }),
            h("spacemesh.v2alpha1.TransactionStreamService", {
                "Stream": _server_stream(
                    self._tx_stream, v2.TransactionStreamRequest,
                    v2.TransactionV2),
            }),
        )

    # --- activations ---------------------------------------------------

    def _atx_msg_from_row(self, row) -> v2.Activation:
        view = atxstore._view(row)
        target = view.publish_epoch + 1
        info = self.node.cache.get(target, view.id)
        return v2.Activation(
            id=view.id, smesher_id=view.node_id,
            publish_epoch=view.publish_epoch,
            coinbase=row["coinbase"] or b"",
            num_units=view.num_units,
            weight=info.weight if info else 0,
            height=info.height if info else 0)

    async def _atx_list(self, req, ctx):
        await _check_limit(req, ctx)
        rows = atxstore.list_rows(
            self.node.state, limit=req.limit, offset=req.offset,
            epoch=req.epoch if req.HasField("epoch") else None,
            smesher=req.smesher_id or None, coinbase=req.coinbase or None)
        return v2.ActivationList(
            activations=[self._atx_msg_from_row(r) for r in rows])

    async def _atx_count(self, req, ctx):
        n = atxstore.count(
            self.node.state,
            epoch=req.epoch if req.HasField("epoch") else None)
        return v2.ActivationsCountResponse(count=n)

    async def _atx_stream(self, req, ctx):
        sub = None
        if req.watch:
            sub = self.node.events.subscribe(events_mod.AtxEvent, size=256)
        try:
            # stored first (reference Stream: db chan drains before events)
            seen = _RecentSet()
            offset = 0
            while True:
                rows = atxstore.list_rows(
                    self.node.state, limit=100, offset=offset,
                    epoch=req.epoch if req.HasField("epoch") else None,
                    smesher=req.smesher_id or None)
                for row in rows:
                    msg = self._atx_msg_from_row(row)
                    if msg.publish_epoch + 1 >= req.start_epoch:
                        seen.add(msg.id)
                        yield msg
                if len(rows) < 100:
                    break
                offset += 100
            if sub is None:
                return
            while True:
                ev = await sub.next()
                if sub.overflowed:
                    await ctx.abort(grpc.StatusCode.CANCELLED,
                                    "event buffer overflow")
                if req.smesher_id and ev.node_id != req.smesher_id:
                    continue
                # AtxEvent.epoch is the PUBLISH epoch (app._on_atx), the
                # same axis the stored drain filters on
                if req.HasField("epoch") and ev.epoch != req.epoch:
                    continue
                if ev.epoch + 1 < req.start_epoch or ev.atx_id in seen:
                    continue
                seen.add(ev.atx_id)
                row = self.node.state.one(
                    "SELECT * FROM atxs WHERE id=?", (ev.atx_id,))
                if row is not None:
                    yield self._atx_msg_from_row(row)
        finally:
            if sub is not None:
                sub.close()

    # --- rewards -------------------------------------------------------

    def _reward_rows(self, coinbase: bytes | None, start_layer: int,
                     limit: int, offset: int):
        where = "WHERE layer >= ?"
        args: list = [start_layer]
        if coinbase:
            where += " AND coinbase = ?"
            args.append(coinbase)
        return self.node.state.all(
            f"SELECT * FROM rewards {where} ORDER BY layer, coinbase"
            " LIMIT ? OFFSET ?", (*args, limit, offset))

    @staticmethod
    def _reward_msg(row) -> v2.Reward:
        return v2.Reward(layer=row["layer"], total=row["total_reward"],
                         layer_reward=row["layer_reward"],
                         coinbase=row["coinbase"])

    async def _reward_list(self, req, ctx):
        await _check_limit(req, ctx)
        rows = self._reward_rows(req.coinbase or None, req.start_layer,
                                 req.limit, req.offset)
        return v2.RewardList(rewards=[self._reward_msg(r) for r in rows])

    def _reward_pages(self, coinbase, start_layer: int):
        """Reward rows from ``start_layer`` in 100-row pages — a scan
        over a long range must not materialize it in one query
        (ADVICE r4). The start layer stays FIXED across pages (offset
        paging): advancing it per page would skip rows when several
        coinbases share the page-boundary layer."""
        offset = 0
        while True:
            page = self._reward_rows(coinbase, start_layer, 100, offset)
            yield from page
            if len(page) < 100:
                return
            offset += 100

    async def _reward_stream(self, req, ctx):
        sub = None
        if req.watch:
            sub = self.node.events.subscribe(events_mod.LayerUpdate, size=256)
        try:
            last = req.start_layer - 1
            for row in self._reward_pages(req.coinbase or None,
                                          req.start_layer):
                last = max(last, row["layer"])
                yield self._reward_msg(row)
            if sub is None:
                return
            while True:
                ev = await sub.next()
                # an overflowed queue is safe here: the next event
                # triggers a DB re-scan from `last`, nothing is lost
                if ev.status != "applied" or ev.layer <= last:
                    continue
                for row in self._reward_pages(req.coinbase or None,
                                              last + 1):
                    last = max(last, row["layer"])
                    yield self._reward_msg(row)
        finally:
            if sub is not None:
                sub.close()

    # --- layers --------------------------------------------------------

    def _layer_msg(self, layer: int) -> v2.Layer:
        return v2.Layer(
            number=layer,
            applied_block=layerstore.applied_block(self.node.state, layer)
            or b"",
            state_hash=layerstore.state_hash(self.node.state, layer) or b"",
            aggregated_hash=layerstore.aggregated_hash(
                self.node.state, layer) or b"")

    async def _layer_list(self, req, ctx):
        await _check_limit(req, ctx)
        # exclusive upper bound; processed() is -1 on a fresh db so an
        # empty node yields an empty list, not a fabricated layer 0
        end = req.end_layer + 1 if req.HasField("end_layer") \
            else layerstore.processed(self.node.state) + 1
        first = req.start_layer + req.offset
        layers = range(first, min(first + req.limit, end))
        return v2.LayerList(layers=[self._layer_msg(x) for x in layers])

    async def _layer_stream(self, req, ctx):
        sub = None
        if req.watch:
            sub = self.node.events.subscribe(events_mod.LayerUpdate, size=256)
        try:
            last = req.start_layer - 1
            for layer in range(
                    req.start_layer,
                    layerstore.processed(self.node.state) + 1):
                last = layer
                yield self._layer_msg(layer)
            if sub is None:
                return
            while True:
                ev = await sub.next()
                # overflow-safe: the range below re-reads the DB gap
                if ev.status != "applied" or ev.layer <= last:
                    continue
                for layer in range(last + 1, ev.layer + 1):
                    yield self._layer_msg(layer)
                last = ev.layer
        finally:
            if sub is not None:
                sub.close()

    # --- malfeasance ---------------------------------------------------

    def _malfeasance_msg(self, node_id: bytes) -> v2.MalfeasanceProof | None:
        proof = miscstore.malfeasance_proof(self.node.state, node_id)
        if proof is None:
            return None
        return v2.MalfeasanceProof(
            smesher_id=node_id,
            domain=_DOMAINS.get(proof.domain, str(proof.domain)),
            proof=proof.to_bytes())

    async def _malfeasance_list(self, req, ctx):
        await _check_limit(req, ctx)
        ids = list(req.smesher_id) or miscstore.all_malicious(self.node.state)
        out = []
        for nid in ids[req.offset:req.offset + req.limit]:
            msg = self._malfeasance_msg(nid)
            if msg is not None:
                out.append(msg)
        return v2.MalfeasanceList(proofs=out)

    async def _malfeasance_stream(self, req, ctx):
        sub = None
        if req.watch:
            sub = self.node.events.subscribe(events_mod.Malfeasance, size=256)
        try:
            wanted = set(req.smesher_id)
            sent = _RecentSet()
            for nid in miscstore.all_malicious(self.node.state):
                if wanted and nid not in wanted:
                    continue
                msg = self._malfeasance_msg(nid)
                if msg is not None:
                    sent.add(nid)
                    yield msg
            if sub is None:
                return
            while True:
                ev = await sub.next()
                if sub.overflowed:
                    await ctx.abort(grpc.StatusCode.CANCELLED,
                                    "event buffer overflow")
                if (wanted and ev.node_id not in wanted) \
                        or ev.node_id in sent:
                    continue
                msg = self._malfeasance_msg(ev.node_id)
                if msg is not None:
                    sent.add(ev.node_id)
                    yield msg
        finally:
            if sub is not None:
                sub.close()

    # --- network / node ------------------------------------------------

    async def _network_info(self, req, ctx):
        cfg = self.node.cfg
        return v2.NetworkInfoResponse(
            genesis_time=self.node.clock.genesis_time,
            layer_duration=cfg.layer_duration,
            genesis_id=cfg.genesis.genesis_id,
            hrp=Address.HRP,
            effective_genesis_layer=0,
            layers_per_epoch=cfg.layers_per_epoch,
            labels_per_unit=cfg.post.labels_per_unit)

    async def _node_status(self, req, ctx):
        n = self.node
        synced = n.syncer.is_synced() if n.syncer else True
        return v2.NodeStatusResponse(
            connected_peers=len(n.server.peers()) if n.server else 0,
            status=(v2.NodeStatusResponse.SYNC_STATUS_SYNCED if synced
                    else v2.NodeStatusResponse.SYNC_STATUS_SYNCING),
            latest_layer=max(layerstore.processed(n.state), 0),
            applied_layer=max(layerstore.last_applied(n.state), 0),
            processed_layer=max(layerstore.processed(n.state), 0),
            current_layer=max(int(n.clock.current_layer()), 0))

    # --- accounts ------------------------------------------------------

    async def _account_list(self, req, ctx):
        await _check_limit(req, ctx)
        from ..storage import transactions as txstore

        state = self.node.state
        if req.addresses:
            addrs = list(req.addresses)[req.offset:req.offset + req.limit]
        else:
            rows = state.all(
                "SELECT DISTINCT address FROM accounts ORDER BY address"
                " LIMIT ? OFFSET ?", (req.limit, req.offset))
            addrs = [r["address"] for r in rows]
        out = []
        for addr in addrs:
            acct = txstore.account(state, addr)
            cur = v2.AccountState(
                balance=acct["balance"] if acct else 0,
                counter=acct["next_nonce"] if acct else 0,
                layer=acct["layer"] if acct else 0)
            nonce_p, balance_p = self.node.cstate.projected(addr)
            out.append(v2.Account(
                address=addr, current=cur,
                projected=v2.AccountState(balance=balance_p, counter=nonce_p),
                template=(acct["template"] or b"").hex() if acct else ""))
        return v2.AccountList(accounts=out)

    # --- transactions --------------------------------------------------

    def _tx_msg(self, row) -> v2.TransactionV2:
        from ..core.types import TransactionResult

        res = row["result"]
        layer, block, status = 0, b"", 0
        if res:
            tr = TransactionResult.from_bytes(res)
            layer, block, status = tr.layer, tr.block, tr.status
        return v2.TransactionV2(
            id=row["id"], principal=row["principal"] or b"",
            nonce=row["nonce"] or 0, raw=row["raw"],
            layer=layer, block=block, status=status)

    def _tx_rows(self, *, principal=None, txids=(), start_layer=None,
                 end_layer=None, limit: int, offset: int):
        """Layer bounds are part of the WHERE clause — filtering after
        LIMIT/OFFSET would break the pagination contract (a full page of
        out-of-range rows reads as end-of-data)."""
        where, args = [], []
        if principal:
            where.append("principal=?")
            args.append(principal)
        if txids:
            where.append("id IN (%s)" % ",".join("?" * len(txids)))
            args.extend(txids)
        if start_layer is not None:
            where.append("layer>=?")
            args.append(start_layer)
        if end_layer is not None:
            where.append("layer<=?")
            args.append(end_layer)
        clause = (" WHERE " + " AND ".join(where)) if where else ""
        return self.node.state.all(
            f"SELECT * FROM transactions{clause} ORDER BY layer, id"
            " LIMIT ? OFFSET ?", (*args, limit, offset))

    async def _tx_list(self, req, ctx):
        await _check_limit(req, ctx)
        rows = self._tx_rows(
            principal=req.principal or None, txids=list(req.txid),
            start_layer=req.start_layer if req.HasField("start_layer")
            else None,
            end_layer=req.end_layer if req.HasField("end_layer") else None,
            limit=req.limit, offset=req.offset)
        return v2.TransactionList(transactions=[self._tx_msg(r)
                                                for r in rows])

    async def _tx_stream(self, req, ctx):
        sub = None
        if req.watch:
            sub = self.node.events.subscribe(events_mod.TxEvent, size=256)
        try:
            sent = _RecentSet()
            offset = 0
            while True:
                rows = self._tx_rows(principal=req.principal or None,
                                     limit=100, offset=offset)
                for row in rows:
                    sent.add(row["id"])
                    yield self._tx_msg(row)
                if len(rows) < 100:
                    break
                offset += 100
            if sub is None:
                return
            while True:
                ev = await sub.next()
                if sub.overflowed:
                    await ctx.abort(grpc.StatusCode.CANCELLED,
                                    "event buffer overflow")
                if ev.tx_id in sent:
                    continue
                row = self.node.state.one(
                    "SELECT * FROM transactions WHERE id=?", (ev.tx_id,))
                if row is None:
                    continue
                if req.principal and row["principal"] != req.principal:
                    continue
                sent.add(ev.tx_id)
                yield self._tx_msg(row)
        finally:
            if sub is not None:
                sub.close()
