"""Generated protobuf modules (protoc --python_out of ../protos/*.proto).

Regenerate with:  protoc -I spacemesh_tpu/api/protos \
    --python_out spacemesh_tpu/api/gen spacemesh_tpu/api/protos/*.proto
"""
