"""Node API: the operator/client surface.

Mirrors the reference api/grpcserver service set (reference
api/grpcserver/config.go: Node, Mesh, GlobalState, Transaction, Smesher,
Debug, Admin, Activation services + the grpc-gateway JSON endpoint
http_server.go). Served as JSON-over-HTTP (aiohttp) with the same
public/private listener split; an event stream endpoint replaces the gRPC
streaming services.
"""

from .http import ApiServer  # noqa: F401
