"""gRPC API server: the reference's grpcserver surface on grpc.aio.

Two halves:

* ``PostGrpcService`` — the node<->post-service seam served field-for-field
  per the public spacemesh.v1 contract (reference
  api/grpcserver/post_service.go:24-174).  The post worker DIALS the node
  and calls ``Register``; the node then drives the bidirectional stream:
  MetadataRequest first (identity handshake), GenProofRequest on demand,
  polled until the proof is ready (reference post_client.go:70-146).
  A registered identity is exposed to the activation builder as a
  ``GrpcPostClient`` with the same blocking ``info()``/``proof()`` surface
  as the in-proc and JSON-RPC clients.

* ``GrpcApiServer`` — Node/Mesh/GlobalState/Transaction/Smesher/Admin
  services (reference api/grpcserver/{node,mesh,globalstate,transaction,
  smesher,admin}_service.go) over real gRPC, sharing the app internals the
  JSON gateway (api/http.py) reads.  Hand-wired with
  ``grpc.method_handlers_generic_handler`` — the environment ships grpcio
  + protoc but not grpc_tools, so service registration is explicit instead
  of generated (the wire format is identical).
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import contextlib
import time

import grpc

from ..core.types import Address, Transaction
from ..node import checkpoint as checkpoint_mod
from ..node import events as events_mod
from ..storage import atxs as atxstore
from ..storage import blocks as blockstore
from ..storage import layers as layerstore
from ..storage import misc as miscstore
from ..storage import transactions as txstore
from ..vm.vm import TxValidity
from .gen import core_pb2 as cpb
from .gen import post_pb2 as ppb
from .http import API_VERSION

POST_REGISTER = "/spacemesh.v1.PostService/Register"


def pack_indices(indices: list[int]) -> bytes:
    """K2 label indices on the wire: fixed 8-byte LE each (the reference
    bit-packs to ceil(log2(num_labels)) bits — post/proving.rs equivalent;
    fixed-width keeps the codec branch-free for the TPU verifier path)."""
    import struct

    return b"".join(struct.pack("<Q", i) for i in indices)


def unpack_indices(blob: bytes) -> list[int]:
    import struct

    return [struct.unpack_from("<Q", blob, o)[0]
            for o in range(0, len(blob), 8)]


# --- PostService (the seam) ------------------------------------------------


class GrpcPostClient:
    """The node's view of one identity registered over a Register stream.

    Blocking ``info()``/``proof()`` (the activation builder calls these via
    ``asyncio.to_thread``); each call round-trips one NodeRequest over the
    stream via the service's command queue, mirroring the reference
    postClient (post_client.go:37-146 incl. the GenProof poll loop).
    """

    def __init__(self, service: "PostGrpcService", node_id: bytes,
                 queue: asyncio.Queue, query_interval: float = 2.0,
                 timeout: float = 600.0):
        self._service = service
        self.node_id = node_id
        self._queue = queue
        self.query_interval = query_interval
        self.timeout = timeout

    async def _roundtrip_async(self, req: ppb.NodeRequest) -> ppb.ServiceResponse:
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        await self._queue.put((req, fut))
        return await fut

    def _roundtrip(self, req: ppb.NodeRequest) -> ppb.ServiceResponse:
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is self._service.loop:
            # blocking on our own event loop would deadlock the stream —
            # callers must use asyncio.to_thread (activation does)
            raise RuntimeError(
                "GrpcPostClient called from the node's event loop")
        cfut = asyncio.run_coroutine_threadsafe(
            self._roundtrip_async(req), self._service.loop)
        try:
            return cfut.result(self.timeout)
        except concurrent.futures.TimeoutError:
            cfut.cancel()
            raise TimeoutError("post service did not answer") from None

    def info(self):
        from ..post.service import PostInfo

        resp = self._roundtrip(
            ppb.NodeRequest(metadata=ppb.MetadataRequest()))
        if resp.WhichOneof("kind") != "metadata":
            raise RuntimeError("post service: expected metadata response")
        return _info_from_meta(resp.metadata.meta, PostInfo)

    def proof(self, challenge: bytes):
        from ..post.data import PostMetadata
        from ..post.prover import Proof
        from ..post.service import PostInfo

        req = ppb.NodeRequest(
            gen_proof=ppb.GenProofRequest(challenge=challenge))
        deadline = time.monotonic() + self.timeout
        while True:
            resp = self._roundtrip(req)
            gp = resp.gen_proof
            if resp.WhichOneof("kind") != "gen_proof":
                raise RuntimeError("post service: expected gen_proof response")
            if gp.status != ppb.GEN_PROOF_STATUS_OK:
                raise RuntimeError(
                    f"post service: proof generation failed (status {gp.status})")
            if gp.HasField("proof"):
                break
            if time.monotonic() > deadline:
                raise TimeoutError("proof generation timed out")
            time.sleep(self.query_interval)  # reference queryInterval poll
        meta = gp.metadata.meta
        if gp.metadata.challenge != challenge:
            raise RuntimeError("post service: challenge mismatch")
        info = _info_from_meta(meta, PostInfo)
        # scrypt_n / max_file_size aren't part of the public seam — the node
        # knows them from its post config; the builder only reads
        # num_units/labels_per_unit/vrf_nonce (consensus/activation.py:266-272)
        pm = PostMetadata(
            node_id=info.node_id.hex(), commitment=info.commitment.hex(),
            num_units=info.num_units, labels_per_unit=info.labels_per_unit,
            scrypt_n=0, max_file_size=0, vrf_nonce=info.vrf_nonce)
        indices = unpack_indices(gp.proof.indices)
        return Proof(nonce=gp.proof.nonce, indices=indices,
                     pow_nonce=gp.proof.pow, k2=len(indices)), pm


def _info_from_meta(meta: ppb.Metadata, PostInfo):
    return PostInfo(
        node_id=bytes(meta.node_id),
        commitment=bytes(meta.commitment_atx_id),
        num_units=meta.num_units,
        labels_per_unit=meta.labels_per_unit,
        scrypt_n=0,  # not part of the public seam; verifier reads it from the ATX
        vrf_nonce=meta.nonce if meta.HasField("nonce") else -1)


class PostGrpcService:
    """Node-side PostService: accepts Register streams from post workers
    (reference post_service.go:91-174)."""

    def __init__(self, query_interval: float = 2.0):
        self.loop: asyncio.AbstractEventLoop | None = None
        self.query_interval = query_interval
        self.clients: dict[bytes, GrpcPostClient] = {}
        self._allow = True
        self._registered_ev: asyncio.Event | None = None

    def allow_connections(self, allow: bool) -> None:
        self._allow = allow

    def registered(self) -> list[bytes]:
        return list(self.clients)

    def client(self, node_id: bytes) -> GrpcPostClient | None:
        return self.clients.get(node_id)

    async def wait_registered(self, node_ids: list[bytes],
                              timeout: float = 60.0) -> None:
        """Block until every expected identity has a live Register stream."""
        deadline = time.monotonic() + timeout
        while not all(n in self.clients for n in node_ids):
            if time.monotonic() > deadline:
                missing = [n.hex()[:12] for n in node_ids
                           if n not in self.clients]
                raise TimeoutError(f"post identities never registered: {missing}")
            ev = self._registered_ev = asyncio.Event()
            with contextlib.suppress(asyncio.TimeoutError):
                await asyncio.wait_for(ev.wait(), 1.0)

    async def register(self, request_iterator, context) -> None:
        """The bidirectional stream handler (reader/writer style)."""
        if not self._allow:
            await context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                                "connection not allowed")
        self.loop = asyncio.get_running_loop()
        # identity handshake: ask for metadata before anything else
        await context.write(ppb.NodeRequest(metadata=ppb.MetadataRequest()))
        resp = await context.read()
        if resp == grpc.aio.EOF or resp.WhichOneof("kind") != "metadata":
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                                "expected metadata response")
        meta = resp.metadata.meta
        node_id = bytes(meta.node_id)
        if len(node_id) != 32:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                                "node id must be 32 bytes")
        if node_id in self.clients:
            await context.abort(grpc.StatusCode.ALREADY_EXISTS,
                                "identity already registered")
        queue: asyncio.Queue = asyncio.Queue()
        self.clients[node_id] = GrpcPostClient(
            self, node_id, queue, query_interval=self.query_interval)
        if self._registered_ev is not None:
            self._registered_ev.set()
        try:
            while True:
                req, fut = await queue.get()
                try:
                    await context.write(req)
                    answer = await context.read()
                except Exception as e:  # stream died mid-command
                    if not fut.done():
                        fut.set_exception(
                            ConnectionError(f"post stream failed: {e}"))
                    raise
                if answer == grpc.aio.EOF:
                    if not fut.done():
                        fut.set_exception(
                            ConnectionError("post service disconnected"))
                    return
                if not fut.done():
                    fut.set_result(answer)
        finally:
            self.clients.pop(node_id, None)
            # fail queued commands so callers don't hang on a dead stream
            while not queue.empty():
                _, fut = queue.get_nowait()
                if not fut.done():
                    fut.set_exception(
                        ConnectionError("post service disconnected"))

    def handler(self) -> grpc.GenericRpcHandler:
        return grpc.method_handlers_generic_handler(
            "spacemesh.v1.PostService", {
                "Register": grpc.stream_stream_rpc_method_handler(
                    self.register,
                    request_deserializer=ppb.ServiceResponse.FromString,
                    response_serializer=ppb.NodeRequest.SerializeToString),
            })


# --- query services --------------------------------------------------------


def _unary(fn, req_cls, resp_cls):
    return grpc.unary_unary_rpc_method_handler(
        fn, request_deserializer=req_cls.FromString,
        response_serializer=resp_cls.SerializeToString)


def _server_stream(fn, req_cls, resp_cls):
    return grpc.unary_stream_rpc_method_handler(
        fn, request_deserializer=req_cls.FromString,
        response_serializer=resp_cls.SerializeToString)


class GrpcApiServer:
    """All spacemesh.v1 services on one grpc.aio server (the reference
    splits them across public/private/post/json listeners —
    api/grpcserver/config.go:31-57; one listener suffices here, the
    public/private split is a config matter, not a protocol one)."""

    def __init__(self, app, listen: str = "127.0.0.1:0",
                 post_query_interval: float = 2.0,
                 public_only: bool = False):
        self.node = app
        self.listen = listen
        # public_only serves just the query surface — no Admin (Recover
        # wipes state), no Smesher, no PostService Register seam. The
        # reference splits listeners by audience for exactly this reason
        # (api/grpcserver/config.go:31-57: public vs private vs post).
        self.public_only = public_only
        # the Register seam only exists on the private listener — a public
        # server never even constructs it, so auditing the public attack
        # surface starts and ends here
        self.post_service = None if public_only else PostGrpcService(
            query_interval=post_query_interval)
        self.server: grpc.aio.Server | None = None
        self.actual_port: int | None = None

    # -- lifecycle --

    async def start(self) -> int:
        from .rpc_v2 import V2AlphaServices

        self.server = grpc.aio.server()
        handlers = (
            self._node_handler(), self._mesh_handler(),
            self._globalstate_handler(), self._transaction_handler(),
            *V2AlphaServices(self.node).handlers())
        if not self.public_only:
            handlers = (self.post_service.handler(),
                        self._smesher_handler(), self._admin_handler(),
                        *handlers)
        self.server.add_generic_rpc_handlers(handlers)
        self.actual_port = self.server.add_insecure_port(self.listen)
        await self.server.start()
        return self.actual_port

    async def stop(self) -> None:
        if self.server is not None:
            await self.server.stop(grace=0.5)

    # -- NodeService (reference node_service.go) --

    def _node_handler(self):
        return grpc.method_handlers_generic_handler("spacemesh.v1.NodeService", {
            "Echo": _unary(self._echo, cpb.EchoRequest, cpb.EchoResponse),
            "Version": _unary(self._version, cpb.EchoRequest, cpb.VersionResponse),
            "Build": _unary(self._build, cpb.EchoRequest, cpb.BuildResponse),
            "Status": _unary(self._status, cpb.StatusRequest, cpb.StatusResponse),
            "StatusStream": _server_stream(
                self._status_stream, cpb.StatusRequest, cpb.StatusResponse),
        })

    async def _echo(self, req, ctx):
        return cpb.EchoResponse(msg=req.msg)

    async def _version(self, req, ctx):
        return cpb.VersionResponse(version=API_VERSION)

    async def _build(self, req, ctx):
        return cpb.BuildResponse(build="spacemesh-tpu")

    def _status_msg(self) -> cpb.StatusResponse:
        n = self.node
        return cpb.StatusResponse(status=cpb.NodeStatus(
            connected_peers=len(n.server.peers()) if n.server else 0,
            is_synced=n.syncer.is_synced() if n.syncer else True,
            synced_layer=max(0, layerstore.processed(n.state)),
            top_layer=max(0, int(n.clock.current_layer())),
            verified_layer=max(0, n.tortoise.verified)))  # -1 pre-genesis

    async def _status(self, req, ctx):
        return self._status_msg()

    async def _status_stream(self, req, ctx):
        sub = self.node.events.subscribe(events_mod.LayerUpdate, size=64)
        try:
            yield self._status_msg()
            while True:
                await sub.next()
                yield self._status_msg()
        finally:
            sub.close()

    # -- MeshService (reference mesh_service.go) --

    def _mesh_handler(self):
        return grpc.method_handlers_generic_handler("spacemesh.v1.MeshService", {
            "GenesisTime": _unary(self._genesis_time, cpb.GenesisTimeRequest,
                                  cpb.GenesisTimeResponse),
            "GenesisID": _unary(self._genesis_id, cpb.GenesisIDRequest,
                                cpb.GenesisIDResponse),
            "CurrentLayer": _unary(self._current_layer, cpb.CurrentLayerRequest,
                                   cpb.CurrentLayerResponse),
            "CurrentEpoch": _unary(self._current_epoch, cpb.CurrentEpochRequest,
                                   cpb.CurrentEpochResponse),
            "EpochNumLayers": _unary(self._epoch_num_layers,
                                     cpb.EpochNumLayersRequest,
                                     cpb.EpochNumLayersResponse),
            "LayerDuration": _unary(self._layer_duration,
                                    cpb.LayerDurationRequest,
                                    cpb.LayerDurationResponse),
            "LayersQuery": _unary(self._layers_query, cpb.LayersQueryRequest,
                                  cpb.LayersQueryResponse),
            "LayerStream": _server_stream(self._layer_stream,
                                          cpb.LayerStreamRequest,
                                          cpb.LayerStreamResponse),
            "EpochStream": _server_stream(self._epoch_stream,
                                          cpb.EpochStreamRequest,
                                          cpb.EpochStreamResponse),
            "MalfeasanceQuery": _unary(self._malfeasance_query,
                                       cpb.MalfeasanceQueryRequest,
                                       cpb.MalfeasanceQueryResponse),
        })

    async def _genesis_time(self, req, ctx):
        return cpb.GenesisTimeResponse(unixtime=int(self.node.cfg.genesis.time))

    async def _genesis_id(self, req, ctx):
        return cpb.GenesisIDResponse(genesis_id=self.node.cfg.genesis.genesis_id)

    async def _current_layer(self, req, ctx):
        return cpb.CurrentLayerResponse(
            layernum=int(self.node.clock.current_layer()))

    async def _current_epoch(self, req, ctx):
        n = self.node
        return cpb.CurrentEpochResponse(
            epochnum=int(n.clock.current_layer()) // n.cfg.layers_per_epoch)

    async def _epoch_num_layers(self, req, ctx):
        return cpb.EpochNumLayersResponse(
            numlayers=self.node.cfg.layers_per_epoch)

    async def _layer_duration(self, req, ctx):
        return cpb.LayerDurationResponse(
            duration=int(self.node.cfg.layer_duration))

    def _layer_msg(self, layer: int) -> cpb.Layer:
        n = self.node
        applied = layerstore.applied_block(n.state, layer)
        last_applied = layerstore.last_applied(n.state)
        if layer <= last_applied:
            status = cpb.Layer.LAYER_STATUS_APPLIED
        elif layer <= n.tortoise.verified:
            status = cpb.Layer.LAYER_STATUS_CONFIRMED
        elif applied is not None or miscstore.certified_block(n.state, layer):
            status = cpb.Layer.LAYER_STATUS_APPROVED
        else:
            status = cpb.Layer.LAYER_STATUS_UNSPECIFIED
        blocks = []
        for b in blockstore.in_layer(n.state, layer):
            txs = []
            for tid in b.tx_ids:
                tx = txstore.get_tx(n.state, tid)
                txs.append(cpb.Transaction(
                    id=tid, raw=tx.raw if tx else b""))
            blocks.append(cpb.Block(id=b.id, layer=layer, transactions=txs))
        return cpb.Layer(
            number=layer, status=status,
            hash=layerstore.state_hash(n.state, layer) or b"",
            aggregated_hash=layerstore.aggregated_hash(n.state, layer) or b"",
            blocks=blocks)

    async def _layers_query(self, req, ctx):
        last = layerstore.processed(self.node.state)
        start = req.start_layer
        end = min(req.end_layer, last) if req.HasField("end_layer") else last
        if end - start > 1000:
            await ctx.abort(grpc.StatusCode.INVALID_ARGUMENT,
                            "layer range too wide (max 1000)")
        return cpb.LayersQueryResponse(
            layer=[self._layer_msg(i) for i in range(start, end + 1)])

    async def _layer_stream(self, req, ctx):
        sub = self.node.events.subscribe(events_mod.LayerUpdate, size=256)
        try:
            while True:
                ev = await sub.next()
                yield cpb.LayerStreamResponse(layer=self._layer_msg(ev.layer))
        finally:
            sub.close()

    async def _epoch_stream(self, req, ctx):
        # reference mesh_service.go:563: stream the ATX ids targeting an epoch
        for atx_id in atxstore.ids_in_epoch(self.node.state, req.epoch - 1):
            yield cpb.EpochStreamResponse(id=atx_id)

    async def _malfeasance_query(self, req, ctx):
        n = self.node
        smesher = bytes(req.smesher_id)
        proof = miscstore.malfeasance_proof(n.state, smesher)
        if proof is None:
            await ctx.abort(grpc.StatusCode.NOT_FOUND, "no proof for identity")
        return cpb.MalfeasanceQueryResponse(proof=cpb.MalfeasanceProof(
            smesher_id=smesher, kind=str(proof.domain),
            proof=proof.to_bytes()))

    # -- GlobalStateService (reference globalstate_service.go) --

    def _globalstate_handler(self):
        return grpc.method_handlers_generic_handler(
            "spacemesh.v1.GlobalStateService", {
                "GlobalStateHash": _unary(self._global_state_hash,
                                          cpb.GlobalStateHashRequest,
                                          cpb.GlobalStateHashResponse),
                "Account": _unary(self._account, cpb.AccountRequest,
                                  cpb.AccountResponse),
                "AccountDataQuery": _unary(self._account_data_query,
                                           cpb.AccountDataQueryRequest,
                                           cpb.AccountDataQueryResponse),
            })

    async def _global_state_hash(self, req, ctx):
        layer = layerstore.last_applied(self.node.state)
        return cpb.GlobalStateHashResponse(response=cpb.GlobalStateHash(
            root_hash=layerstore.state_hash(self.node.state, layer) or b"",
            layer=layer))

    def _parse_addr(self, text: str, ctx):
        try:
            if text.startswith("0x"):
                return Address(bytes.fromhex(text[2:])).raw
            return Address.decode(text).raw
        except ValueError:
            return None

    def _account_msg(self, addr: bytes) -> cpb.Account:
        row = txstore.account(self.node.state, addr)
        bal = row["balance"] if row else 0
        nonce = row["next_nonce"] if row else 0
        projected = self.node.cstate.projected(addr) \
            if hasattr(self.node.cstate, "projected") else None
        return cpb.Account(
            address=Address(addr).encode(),
            state_current=cpb.AccountState(balance=bal, counter=nonce),
            state_projected=cpb.AccountState(
                balance=projected[0] if projected else bal,
                counter=projected[1] if projected else nonce),
            template=(row["template"].hex() if row and row["template"]
                      else ""))

    async def _account(self, req, ctx):
        addr = self._parse_addr(req.address, ctx)
        if addr is None:
            await ctx.abort(grpc.StatusCode.INVALID_ARGUMENT, "bad address")
        return cpb.AccountResponse(account_wrapper=self._account_msg(addr))

    async def _account_data_query(self, req, ctx):
        addr = self._parse_addr(req.address, ctx)
        if addr is None:
            await ctx.abort(grpc.StatusCode.INVALID_ARGUMENT, "bad address")
        items = [cpb.AccountData(account_wrapper=self._account_msg(addr))]
        for lyr, total in miscstore.rewards_for(self.node.state, addr):
            items.append(cpb.AccountData(reward=cpb.Reward(
                layer=lyr, total=total, coinbase=Address(addr).encode())))
        total_results = len(items)
        off = req.offset
        if req.max_results:
            items = items[off:off + req.max_results]
        else:
            items = items[off:]
        return cpb.AccountDataQueryResponse(
            total_results=total_results, account_item=items)

    # -- TransactionService (reference transaction_service.go) --

    def _transaction_handler(self):
        return grpc.method_handlers_generic_handler(
            "spacemesh.v1.TransactionService", {
                "SubmitTransaction": _unary(self._submit_tx,
                                            cpb.SubmitTransactionRequest,
                                            cpb.SubmitTransactionResponse),
                "TransactionsState": _unary(self._txs_state,
                                            cpb.TransactionsStateRequest,
                                            cpb.TransactionsStateResponse),
            })

    async def _submit_tx(self, req, ctx):
        tx = Transaction(raw=bytes(req.transaction))
        validity = self.node.cstate.add(tx)
        if validity == TxValidity.VALID:
            from ..p2p.pubsub import TOPIC_TX

            await self.node.pubsub.publish(TOPIC_TX, tx.raw)
            state = cpb.TransactionState.TRANSACTION_STATE_MEMPOOL
        else:
            state = cpb.TransactionState.TRANSACTION_STATE_REJECTED
        return cpb.SubmitTransactionResponse(
            status_code=0 if validity == TxValidity.VALID else 3,
            txstate=cpb.TransactionState(id=tx.id, state=state))

    async def _txs_state(self, req, ctx):
        states, txs = [], []
        for tid in req.transaction_id:
            tid = bytes(tid)
            tx = txstore.get_tx(self.node.state, tid)
            if tx is None:
                states.append(cpb.TransactionState(
                    id=tid,
                    state=cpb.TransactionState.TRANSACTION_STATE_UNSPECIFIED))
                continue
            res = txstore.result(self.node.state, tid)
            states.append(cpb.TransactionState(
                id=tid,
                state=(cpb.TransactionState.TRANSACTION_STATE_PROCESSED
                       if res is not None else
                       cpb.TransactionState.TRANSACTION_STATE_MEMPOOL)))
            if req.include_transactions:
                txs.append(cpb.Transaction(id=tid, raw=tx.raw))
        return cpb.TransactionsStateResponse(
            transactions_state=states, transactions=txs)

    # -- SmesherService (reference smesher_service.go) --

    def _smesher_handler(self):
        return grpc.method_handlers_generic_handler(
            "spacemesh.v1.SmesherService", {
                "IsSmeshing": _unary(self._is_smeshing, cpb.IsSmeshingRequest,
                                     cpb.IsSmeshingResponse),
                "SmesherIDs": _unary(self._smesher_ids, cpb.SmesherIDsRequest,
                                     cpb.SmesherIDsResponse),
                "PostSetupStatus": _unary(self._post_setup_status,
                                          cpb.PostSetupStatusRequest,
                                          cpb.PostSetupStatusResponse),
            })

    async def _is_smeshing(self, req, ctx):
        return cpb.IsSmeshingResponse(
            is_smeshing=self.node.atx_builder is not None)

    async def _smesher_ids(self, req, ctx):
        return cpb.SmesherIDsResponse(
            ids=[s.node_id for s in self.node.signers])

    async def _post_setup_status(self, req, ctx):
        n = self.node
        registered = (n.post_service.registered()
                      if n.post_service is not None else [])
        state = (cpb.PostSetupStatus.STATE_COMPLETE if registered
                 else cpb.PostSetupStatus.STATE_NOT_STARTED)
        return cpb.PostSetupStatusResponse(
            status=cpb.PostSetupStatus(state=state))

    # -- AdminService (reference admin_service.go) --

    def _admin_handler(self):
        return grpc.method_handlers_generic_handler(
            "spacemesh.v1.AdminService", {
                "CheckpointStream": _server_stream(self._checkpoint_stream,
                                                   cpb.CheckpointStreamRequest,
                                                   cpb.CheckpointStreamResponse),
                "Recover": _unary(self._recover, cpb.RecoverRequest,
                                  cpb.RecoverResponse),
                "EventsStream": _server_stream(self._events_stream,
                                               cpb.EventStreamRequest,
                                               cpb.Event),
                "PeerInfoStream": _server_stream(self._peer_info_stream,
                                                 cpb.PeerInfoRequest,
                                                 cpb.PeerInfo),
            })

    async def _checkpoint_stream(self, req, ctx):
        # reference admin_service.go:73: write the checkpoint, stream it in
        # chunks
        import os
        import tempfile

        with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as f:
            path = f.name
        try:
            await asyncio.to_thread(checkpoint_mod.write, self.node.state, path)
            # open + chunk reads off the loop: a mainnet-shape checkpoint
            # is large and every sync 64KiB read stalled the event loop
            # between yielded chunks (spacecheck SC002)
            f = await asyncio.to_thread(open, path, "rb")
            try:
                while chunk := await asyncio.to_thread(f.read, 64 << 10):
                    yield cpb.CheckpointStreamResponse(data=chunk)
            finally:
                f.close()
        finally:
            # unlinking a multi-GB checkpoint can take hundreds of ms
            # in the kernel — off the loop like the reads
            await asyncio.to_thread(os.unlink, path)

    async def _recover(self, req, ctx):
        await asyncio.to_thread(
            checkpoint_mod.recover_file, self.node.state, req.uri,
            self.node.signer.node_id)
        return cpb.RecoverResponse()

    _EVENT_TYPES = (events_mod.LayerUpdate, events_mod.AtxEvent,
                    events_mod.TxEvent, events_mod.BeaconEvent,
                    events_mod.PostEvent, events_mod.AtxPublished,
                    events_mod.Malfeasance)

    async def _events_stream(self, req, ctx):
        import json

        sub = self.node.events.subscribe(*self._EVENT_TYPES, size=1024)
        try:
            while True:
                ev = await sub.next()
                detail = {k: (v.hex() if isinstance(v, bytes) else v)
                          for k, v in ev.__dict__.items()}
                yield cpb.Event(timestamp=int(time.time()),
                                kind=type(ev).__name__,
                                detail=json.dumps(detail))
        finally:
            sub.close()

    async def _peer_info_stream(self, req, ctx):
        n = self.node
        if n.server is None:
            return
        for pid in n.server.peers():
            connections = []
            host = getattr(n, "host", None)
            if host is not None and pid in host.nodes:
                conn = host.nodes[pid]
                if conn.listen_addr:
                    connections.append(
                        f"{conn.listen_addr[0]}:{conn.listen_addr[1]}")
            yield cpb.PeerInfo(id=pid.hex(), connections=connections)
