"""Out-of-process POST worker transport: length-prefixed JSON-RPC.

The process boundary the reference puts between node and post-service
(reference api/grpcserver/post_service.go:24-174 Register bidirectional
stream, post_client.go:69 Proof; the Rust post-service dials the node).
Here the worker LISTENS and the node dials — same contract, simpler
topology for a single-operator setup:

  node  --"info"/"proof"-->  worker (owns the POST data + TPU)

Frames: u32 LE length + JSON object. Requests carry {"method", ...};
responses {"ok": true, ...} or {"ok": false, "error"}. Proof generation
runs in a worker thread so one slow prove doesn't block the event loop
(the reference worker is similarly concurrent per identity).

The node-side RemotePostClient implements the PostClient surface
(info()/proof()) with blocking sockets — the node already calls proof()
via asyncio.to_thread (activation.Builder phase 2).
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import socket
import struct
from pathlib import Path

from .data import PostMetadata
from .prover import Proof
from .service import PostInfo, PostService

MAX_MSG = 16 << 20


# --- framing ---------------------------------------------------------------


def _send_msg(sock: socket.socket, obj: dict) -> None:
    data = json.dumps(obj).encode()
    sock.sendall(struct.pack("<I", len(data)) + data)


def _recv_msg(sock: socket.socket) -> dict:
    head = _recv_exact(sock, 4)
    (length,) = struct.unpack("<I", head)
    if length > MAX_MSG:
        raise ConnectionError(f"oversized message ({length})")
    return json.loads(_recv_exact(sock, length))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("connection closed")
        buf += chunk
    return buf


# --- worker side -----------------------------------------------------------


class WorkerServer:
    """Serves a PostService registry over TCP (the worker process)."""

    def __init__(self, service: PostService, listen: str = "127.0.0.1:0"):
        self.service = service
        self.listen = listen
        self.address: tuple[str, int] | None = None
        self._server: asyncio.AbstractServer | None = None

    async def start(self) -> tuple[str, int]:
        host, _, port = self.listen.rpartition(":")
        self._server = await asyncio.start_server(
            self._client, host or "127.0.0.1", int(port or 0))
        self.address = self._server.sockets[0].getsockname()[:2]
        return self.address

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    async def _client(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                head = await reader.readexactly(4)
                (length,) = struct.unpack("<I", head)
                if length > MAX_MSG:
                    break
                req = json.loads(await reader.readexactly(length))
                resp = await self._dispatch(req)
                data = json.dumps(resp).encode()
                writer.write(struct.pack("<I", len(data)) + data)
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        finally:
            writer.close()

    async def _dispatch(self, req: dict) -> dict:
        try:
            method = req.get("method")
            if method == "registered":
                return {"ok": True,
                        "node_ids": [n.hex() for n in
                                     self.service.registered()]}
            node_id = bytes.fromhex(req["node_id"])
            client = self.service.client(node_id)
            if client is None:
                return {"ok": False, "error": "identity not registered"}
            if method == "info":
                info = client.info()
                return {"ok": True, "info": dataclasses.asdict(info) | {
                    "node_id": info.node_id.hex(),
                    "commitment": info.commitment.hex()}}
            if method == "proof":
                challenge = bytes.fromhex(req["challenge"])
                # prove in a thread: scrypt recompute + nonce search is slow
                proof, meta = await asyncio.to_thread(client.proof, challenge)
                return {"ok": True, "proof": proof.to_dict(),
                        "meta": dataclasses.asdict(meta)}
            return {"ok": False, "error": f"unknown method {method!r}"}
        except Exception as e:  # noqa: BLE001 — error travels to the node
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}


# --- node side -------------------------------------------------------------


class RemotePostClient:
    """PostClient surface over the wire: the node's view of one identity
    served by an out-of-process worker."""

    def __init__(self, address: tuple[str, int], node_id: bytes,
                 timeout: float = 600.0):
        self.address = tuple(address)
        self.node_id = node_id
        self.timeout = timeout

    def _call(self, req: dict) -> dict:
        with socket.create_connection(self.address, timeout=self.timeout) as s:
            _send_msg(s, req)
            resp = _recv_msg(s)
        if not resp.get("ok"):
            raise RuntimeError(f"post worker: {resp.get('error')}")
        return resp

    def info(self) -> PostInfo:
        d = self._call({"method": "info", "node_id": self.node_id.hex()})
        i = d["info"]
        return PostInfo(
            node_id=bytes.fromhex(i["node_id"]),
            commitment=bytes.fromhex(i["commitment"]),
            num_units=i["num_units"], labels_per_unit=i["labels_per_unit"],
            scrypt_n=i["scrypt_n"], vrf_nonce=i["vrf_nonce"],
            labels_written=i.get("labels_written", 0))

    def proof(self, challenge: bytes) -> tuple[Proof, PostMetadata]:
        d = self._call({"method": "proof", "node_id": self.node_id.hex(),
                        "challenge": challenge.hex()})
        return Proof.from_dict(d["proof"]), PostMetadata(**d["meta"])

    def ping(self) -> list[bytes]:
        d = self._call({"method": "registered"})
        return [bytes.fromhex(x) for x in d["node_ids"]]


def discover_identities(base_dir: str | Path, params=None,
                        **prove_opts) -> PostService:
    """Build a PostService from a directory of per-identity POST data dirs
    (what the worker CLI serves). ``prove_opts`` are the streaming-prover
    pipeline knobs, passed through to every identity's PostClient."""
    from .service import PostClient

    service = PostService()
    base = Path(base_dir)
    candidates = [base] + [p for p in base.iterdir() if p.is_dir()] \
        if base.is_dir() else []
    for p in candidates:
        if (p / "postdata_metadata.json").exists():
            meta = PostMetadata.load(p)
            service.register(bytes.fromhex(meta.node_id),
                             PostClient(p, params, **prove_opts))
    return service
