"""Deterministic disk-fault injection for the POST data plane.

A :class:`FaultFS` is a drop-in ``fs`` for post/data.py (LabelStore,
LabelWriter, PostMetadata via utils/fsio.py): it delegates every
primitive to the real filesystem while (a) counting operations and
(b) firing scripted faults at **exact operation counts** — same plan,
same op stream, same fault, replay-stable the way ``sim/`` scenarios
are.  No wall clock, no randomness outside the plan's own seed.

Fault kinds (``FaultSpec.kind``):

* ``eio``      — the op raises ``OSError(EIO)`` once.
* ``enospc``   — the op raises ``OSError(ENOSPC)``; with ``hold_ops``
  every mutating op until the counter passes ``op + hold_ops`` also
  raises — "the disk stays full until the plan releases space".  The
  LabelWriter's degraded-mode retries advance the op counter, so the
  release point is deterministic in *operations*, not seconds.
* ``short``    — a ``pwrite`` persists only a seeded byte-prefix and
  returns the short count (POSIX allows this; callers must loop).
* ``torn``     — a ``pwrite`` persists a seeded byte-prefix and then
  the power fails (:class:`PowerCut`).
* ``powercut`` — the op raises :class:`PowerCut` before doing anything.

Power-cut semantics: the shim tracks, per file, the last **fsynced**
image (files that existed before the shim first touched them count as
durable).  ``reboot()`` rewinds the real directory to exactly that
durable state — un-fsynced bytes vanish, un-dir-fsynced renames and
unlinks roll back — which is the pessimistic-but-legal outcome a real
power cut may produce.  The harness then reopens the store and the
recovery path (post/data.py ``recover_store``) must converge.

The shadow images are whole-file copies, refreshed on every fsync:
this shim is for tests and the ``crash-recovery`` sim scenario, not
for production-sized stores.
"""

from __future__ import annotations

import dataclasses
import errno
import os
import random
import threading
from pathlib import Path

from ..utils import fsio, metrics

WRITE_KINDS = ("eio", "enospc", "short", "torn", "powercut")


class PowerCut(BaseException):
    """Simulated power loss. Derives from BaseException so it rips
    through ordinary ``except Exception`` recovery the way a real cord
    pull would; the crash harness catches it (or finds it behind a
    pool error's ``__cause__``) and calls ``FaultFS.reboot()``."""


def power_cut_behind(exc: BaseException) -> PowerCut | None:
    """The PowerCut hiding behind ``exc``'s cause/context chain, if
    any — writer-pool failures surface as LabelWriteError *from* the
    PowerCut that hit the pool thread."""
    seen: set[int] = set()
    node: BaseException | None = exc
    while node is not None and id(node) not in seen:
        if isinstance(node, PowerCut):
            return node
        seen.add(id(node))
        node = node.__cause__ or node.__context__
    return None


@dataclasses.dataclass
class FaultSpec:
    """One scripted fault: fire at mutating op number ``op`` (1-based,
    counted across the whole FaultFS lifetime, reboots included)."""

    op: int
    kind: str                 # one of WRITE_KINDS
    hold_ops: int = 0         # enospc: ops the disk stays full for
    on: str = "write"         # "write" | "read" (reads: eio only)

    def __post_init__(self):
        if self.kind not in WRITE_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}")


class FaultPlan:
    """The seeded script a FaultFS executes. ``seed`` pins the torn/
    short prefix lengths; ``on_inject(spec, count)`` is a test hook
    called (on the faulting thread) each time a fault fires."""

    def __init__(self, faults=(), seed: int = 0, on_inject=None):
        self.faults = sorted((f if isinstance(f, FaultSpec)
                              else FaultSpec(**f) for f in faults),
                             key=lambda f: (f.on, f.op))
        self.seed = int(seed)
        self.on_inject = on_inject

    def prefix_len(self, op: int, total: int) -> int:
        """Deterministic torn/short prefix for the write at ``op``."""
        if total <= 1:
            return 0
        return random.Random(f"{self.seed}:{op}").randrange(0, total)


class FaultFS(fsio.RealFS):
    """fsio.RealFS with op counting, fault injection, and a durability
    shadow that makes power cuts rewindable. Thread-safe: writer-pool
    threads and the dispatch thread share one instance."""

    def __init__(self, plan: FaultPlan | None = None):
        self.plan = plan or FaultPlan()
        self._lock = threading.Lock()
        self.write_ops = 0          # mutating ops performed or faulted
        self.read_ops = 0
        self.injected: list[dict] = []   # log: {"op","kind","path"}
        self._fd_paths: dict[int, str] = {}
        self._durable: dict[str, bytes | None] = {}  # path -> image
        # namespace ops (rename/unlink) waiting on their dir fsync:
        # dir -> [commit closure]
        self._pending_dir: dict[str, list] = {}
        self._enospc_until = 0

    # -- shadow-state helpers -------------------------------------------

    def _norm(self, path) -> str:
        return os.path.abspath(str(path))

    def _path_of(self, fd: int) -> str | None:
        with self._lock:
            return self._fd_paths.get(fd)

    # guarded by: self._lock — every caller holds it around the shadow-map update
    def _baseline(self, path: str) -> None:
        """First touch of a path: whatever is on disk NOW predates the
        plan and counts as durable. Directories are not shadowed — the
        shim rewinds file CONTENT; fsio.persist's directory payloads
        pass through uncorrupted but untracked."""
        if path in self._durable:
            return
        try:
            with open(path, "rb") as fh:
                self._durable[path] = fh.read()
        except FileNotFoundError:
            self._durable[path] = None
        except IsADirectoryError:
            pass

    # guarded by: self._lock — every caller holds it around the shadow-map update
    def _mark_durable(self, path: str) -> None:
        try:
            with open(path, "rb") as fh:
                self._durable[path] = fh.read()
        except FileNotFoundError:
            self._durable[path] = None
        except IsADirectoryError:
            pass

    # -- fault dispatch --------------------------------------------------

    def _next_op(self, on: str, path: str | None,
                 total: int | None = None,
                 can_partial: bool = False):
        """Advance the op counter; return None or a fired (spec, n,
        prefix) directive. Counter advances even on faulted ops, so an
        ENOSPC hold window measured in ops self-releases. Only pwrite
        sites (``can_partial``) can honor a byte-prefix directive — at
        every other op a torn/short spec degenerates to the power cut
        it models (an fsync or rename has no half-done return path)."""
        with self._lock:
            if on == "read":
                self.read_ops += 1
                n = self.read_ops
            else:
                self.write_ops += 1
                n = self.write_ops
            fired: FaultSpec | None = None
            if on == "write" and n < self._enospc_until:
                fired = FaultSpec(op=n, kind="enospc")
            else:
                for spec in self.plan.faults:
                    if spec.on == on and spec.op == n:
                        fired = spec
                        if spec.kind == "enospc" and spec.hold_ops:
                            self._enospc_until = n + spec.hold_ops
                        break
            if fired is None:
                return None
            entry = {"op": n, "on": on, "kind": fired.kind,
                     "path": os.path.basename(path) if path else None}
            self.injected.append(entry)
        metrics.post_store_fault_injections.inc(kind=fired.kind)
        if self.plan.on_inject is not None:
            self.plan.on_inject(fired, n)
        if fired.kind == "eio":
            raise OSError(errno.EIO, f"injected EIO (op {n})", path)
        if fired.kind == "enospc":
            raise OSError(errno.ENOSPC,
                          f"injected ENOSPC (op {n})", path)
        if fired.kind == "powercut" or not can_partial:
            raise PowerCut(f"injected power cut (op {n}, "
                           f"{fired.kind}) at {path}")
        # short / torn at a pwrite: the caller performs the prefix write
        return fired, n, (self.plan.prefix_len(n, total or 0))

    # -- intercepted primitives ------------------------------------------

    def open(self, path, flags: int, mode: int = 0o644) -> int:
        p = self._norm(path)
        writable = flags & (os.O_WRONLY | os.O_RDWR | os.O_CREAT)
        with self._lock:
            if writable:
                self._baseline(p)
        fd = os.open(p, flags, mode)
        with self._lock:
            self._fd_paths[fd] = p
        return fd

    def close(self, fd: int) -> None:
        with self._lock:
            self._fd_paths.pop(fd, None)
        os.close(fd)

    def pread(self, fd: int, n: int, offset: int) -> bytes:
        self._next_op("read", self._path_of(fd))
        return os.pread(fd, n, offset)

    def pwrite(self, fd: int, data, offset: int) -> int:
        path = self._path_of(fd)
        data = bytes(data)
        directive = self._next_op("write", path, total=len(data),
                                  can_partial=True)
        if directive is not None:
            spec, n, prefix = directive
            if spec.kind == "short":
                # a POSIX short write is 1..len-1 bytes; zero would read
                # as "disk refused" and callers rightly error on it
                prefix = max(1, prefix)
            written = os.pwrite(fd, data[:prefix], offset)
            if spec.kind == "torn":
                raise PowerCut(
                    f"injected torn write (op {n}, {written}/{len(data)}"
                    f" bytes) at {path}")
            return written  # short write: POSIX-legal partial count
        return os.pwrite(fd, data, offset)

    def fsync(self, fd: int) -> None:
        path = self._path_of(fd)
        self._next_op("write", path)
        os.fsync(fd)
        if path is not None:
            with self._lock:
                self._mark_durable(path)

    def replace(self, src, dst) -> None:
        s, d = self._norm(src), self._norm(dst)
        self._next_op("write", d)
        with self._lock:
            self._baseline(s)
            self._baseline(d)
        os.replace(s, d)  # spacecheck: ok=SC009 fault-shim twin of the fsio primitive; durability is modeled by the shadow map
        with self._lock:
            # the rename is volatile until the parent dir is fsynced
            self._pending_dir.setdefault(
                os.path.dirname(d), []).append(("rename", s, d))

    def truncate(self, path, length: int) -> None:
        p = self._norm(path)
        self._next_op("write", p)
        with self._lock:
            self._baseline(p)
        os.truncate(p, length)

    def unlink(self, path) -> None:
        p = self._norm(path)
        self._next_op("write", p)
        with self._lock:
            self._baseline(p)
        os.unlink(p)
        with self._lock:
            self._pending_dir.setdefault(
                os.path.dirname(p), []).append(("unlink", None, p))

    def fsync_dir(self, path) -> None:
        p = self._norm(path)
        self._next_op("write", p)
        fsio.REAL.fsync_dir(p)
        with self._lock:
            for kind, src, tgt in self._pending_dir.pop(p, ()):
                if kind == "rename":
                    self._mark_durable(tgt)
                    self._durable[src] = None
                else:
                    self._durable[tgt] = None

    # -- the crash/reboot cycle ------------------------------------------

    def reboot(self) -> list[str]:
        """Rewind the real tree to the durable shadow — every byte that
        was never fsynced (and every rename/unlink whose directory was
        never fsynced) vanishes, exactly once, deterministically.
        Returns the paths that changed. Op counters keep running so a
        multi-crash plan stays addressable across reboots."""
        changed: list[str] = []
        with self._lock:
            self._pending_dir.clear()
            images = dict(self._durable)
        for path, image in sorted(images.items()):
            try:
                current: bytes | None = Path(path).read_bytes()
            except (FileNotFoundError, IsADirectoryError):
                current = None
            if current == image:
                continue
            changed.append(path)
            if image is None:
                Path(path).unlink(missing_ok=True)
            else:
                with open(path, "wb") as fh:
                    fh.write(image)
                    fh.flush()
                    os.fsync(fh.fileno())
        return changed
