"""POST proving: k2pow gate + nonce search over the stored labels.

The post-service equivalent (reference's external Rust prover, spawned by
activation/post_supervisor.go:220-298 with --nonces/--threads flags; proof
shape reference common/types/poet.go `Post{Nonce, Indices, Pow}`). Here the
label stream is read back from disk in batches and swept through
``proving_scan_jit`` — a (n_nonces x batch) qualification mask per program —
so a whole nonce group rides one device dispatch per label batch.

A proof for challenge ``ch`` is:
    nonce     — the winning proving nonce
    indices   — the first k2 label indices qualifying under nonce
    pow_nonce — k2pow witness for (ch, node_id) (ops/pow.py)
"""

from __future__ import annotations

import dataclasses
from pathlib import Path

import jax.numpy as jnp
import numpy as np

from ..ops import pow as k2pow
from ..ops import proving, scrypt
from .data import LabelStore, PostMetadata


@dataclasses.dataclass
class Proof:
    nonce: int
    indices: list[int]          # k2 qualifying label indices, ascending
    pow_nonce: int
    k2: int

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "Proof":
        return cls(**d)


@dataclasses.dataclass
class ProofParams:
    """Difficulty parameters (reference defaults activation/post.go:148,
    mainnet config/mainnet.go:187-189)."""

    k1: int = 26
    k2: int = 37
    k3: int = 37
    pow_difficulty: bytes = bytes([0, 255]) + bytes([255]) * 30


class Prover:
    def __init__(self, data_dir: str | Path, params: ProofParams | None = None,
                 batch_labels: int = 1 << 14, nonce_group: int = 16,
                 use_pallas: bool | None = None):
        self.meta = PostMetadata.load(data_dir)
        if self.meta.labels_written < self.meta.total_labels:
            raise ValueError("POST data is not fully initialized")
        self.store = LabelStore(data_dir, self.meta)
        self.params = params or ProofParams()
        self.batch_labels = batch_labels
        self.nonce_group = nonce_group
        if use_pallas is None:  # the Mosaic kernel path is TPU-only
            import jax

            use_pallas = jax.devices()[0].platform == "tpu"
        self.use_pallas = use_pallas

    def prove(self, challenge: bytes) -> Proof:
        meta, p = self.meta, self.params
        node_id = bytes.fromhex(meta.node_id)
        pow_nonce = k2pow.search(challenge, node_id, p.pow_difficulty)
        if pow_nonce is None:
            raise RuntimeError("k2pow search exhausted")

        t = proving.threshold_u32(p.k1, meta.total_labels)
        cw = jnp.asarray(proving.challenge_words(challenge))
        group = 0
        while True:
            hits: list[list[int]] = [[] for _ in range(self.nonce_group)]
            start = 0
            while start < meta.total_labels:
                count = min(self.batch_labels, meta.total_labels - start)
                idx = np.arange(start, start + count, dtype=np.uint64)
                labels = np.frombuffer(
                    self.store.read_labels(start, count), dtype=np.uint8
                ).reshape(count, scrypt.LABEL_BYTES)
                lo, hi = scrypt.split_indices(idx)
                lw = scrypt.labels_to_words(labels)
                nonce0 = group * self.nonce_group
                from ..ops import proving_pallas

                if self.use_pallas and count % proving_pallas.LANE_TILE == 0:

                    mask = np.asarray(proving_pallas.proving_scan_pallas(
                        cw, jnp.uint32(nonce0), jnp.asarray(lo),
                        jnp.asarray(hi), jnp.asarray(lw), jnp.uint32(t),
                        n_nonces=self.nonce_group)).astype(bool)
                else:
                    mask = np.asarray(proving.proving_scan_jit(
                        cw, jnp.uint32(nonce0),
                        jnp.asarray(lo), jnp.asarray(hi), jnp.asarray(lw),
                        jnp.uint32(t), n_nonces=self.nonce_group))
                for k in range(self.nonce_group):
                    if len(hits[k]) < p.k2:
                        found = np.nonzero(mask[k])[0]
                        hits[k].extend((start + found).tolist())
                start += count
            for k in range(self.nonce_group):
                if len(hits[k]) >= p.k2:
                    return Proof(nonce=group * self.nonce_group + k,
                                 indices=[int(i) for i in hits[k][:p.k2]],
                                 pow_nonce=pow_nonce, k2=p.k2)
            group += 1
            if group > 1024:
                raise RuntimeError("no winning nonce found (k1/k2 mismatch?)")
