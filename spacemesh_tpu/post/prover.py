"""POST proving: k2pow gate + streaming nonce search over the stored labels.

The post-service equivalent (reference's external Rust prover, spawned by
activation/post_supervisor.go:220-298 with --nonces/--threads flags; proof
shape reference common/types/poet.go `Post{Nonce, Indices, Pow}`).

The default path is a streaming pipeline (docs/POST_PROVING.md) mirroring
the init side's (post/initializer.py):

  read      — a bounded background reader pool (post/data.py LabelReader)
              prefetches label batches while the device scans;
  dispatch  — up to K batches in flight, each one compiled program
              (``prove_scan_step_jit`` / ``prove_scan_step_pallas``) that
              scans a nonce group, compacts hits on device and merges them
              into a *donated* running hit state — ragged tails are padded
              to the full batch shape so one shape compiles per pass;
  retire    — the only per-batch D2H is a (nonce_group,) count vector; the
              packed (nonce, index) hit pairs are fetched once per pass.

One disk pass covers a whole nonce *window* (``window_groups`` groups per
read — on TPU disk bytes are the scarce resource and device FLOPs nearly
free, so the default widens there), and a pass stops early as soon as the
winning nonce is decided: the lowest nonce with >= k2 hits, once every
lower nonce provably cannot reach k2 with the labels left in the pass.
That rule makes the pipelined proof bit-identical to the legacy serial
scan's (kept as ``prove_serial`` — the bench baseline and fallback).

On multi-device the label lanes are sharded over the mesh per batch
(parallel/mesh.py prove_step_sharded), the way init shards its batches.

A proof for challenge ``ch`` is:
    nonce     — the winning proving nonce
    indices   — the first k2 label indices qualifying under nonce
    pow_nonce — k2pow witness for (ch, node_id) (ops/pow.py)
"""

from __future__ import annotations

import dataclasses
import functools
import os
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import pow as k2pow
from ..ops import proving, proving_pallas, scrypt
from ..runtime import engine
from ..utils import metrics, tracing
from .data import LabelStore, PostMetadata

DEFAULT_NONCE_GROUP = 16
DEFAULT_INFLIGHT = 3      # device batches in flight before the oldest retires
DEFAULT_READERS = 2       # background reader threads
DEFAULT_READER_QUEUE = 4  # prefetched batches before reader backpressure
MAX_GROUPS = 1025         # nonce search gives up past this many groups


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


@dataclasses.dataclass
class Proof:
    nonce: int
    indices: list[int]          # k2 qualifying label indices, ascending
    pow_nonce: int
    k2: int

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "Proof":
        return cls(**d)


@dataclasses.dataclass
class ProofParams:
    """Difficulty parameters (reference defaults activation/post.go:148,
    mainnet config/mainnet.go:187-189)."""

    k1: int = 26
    k2: int = 37
    k3: int = 37
    pow_difficulty: bytes = bytes([0, 255]) + bytes([255]) * 30


@dataclasses.dataclass
class ProverStats:
    """Per-prove pipeline accounting (tools/profiler.py --prove)."""

    windows: int = 0          # nonce windows swept
    batches: int = 0          # label batches dispatched
    labels_swept: int = 0     # labels covered across all passes
    read_wait_s: float = 0.0  # blocked on the reader pool
    read_io_s: float = 0.0    # filesystem time inside the reader pool
    dispatch_s: float = 0.0   # host time converting + enqueueing batches
    retire_s: float = 0.0     # blocked fetching per-batch count vectors
    d2h_bytes: int = 0        # compacted device->host traffic
    early_exited: bool = False
    elapsed_s: float = 0.0

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class Prover:
    def __init__(self, data_dir: str | Path, params: ProofParams | None = None,
                 batch_labels: int = 1 << 14,
                 nonce_group: int = DEFAULT_NONCE_GROUP,
                 use_pallas: bool | None = None,
                 pipelined: bool | None = None,
                 window_groups: int | None = None,
                 inflight: int | None = None,
                 readers: int | None = None,
                 reader_queue: int | None = None,
                 mesh="auto",
                 stall_deadline_s: float = 30.0,
                 fs=None):
        # load() raises typed PostMetaCorrupt on a torn/truncated
        # metadata file and clears crash-leftover staging tmps; label
        # reads below get bounded EIO retry (LabelStore._pread_retry),
        # so one transient medium error cannot abort a multi-window
        # disk pass
        self.meta = PostMetadata.load(data_dir, fs=fs)
        if self.meta.labels_written < self.meta.total_labels:
            raise ValueError("POST data is not fully initialized")
        self.store = LabelStore(data_dir, self.meta, fs=fs)
        self.params = params or ProofParams()
        self.nonce_group = nonce_group
        self._platform = jax.devices()[0].platform
        if use_pallas is None:  # the Mosaic kernel path is TPU-only
            use_pallas = self._platform == "tpu"
        self.use_pallas = use_pallas
        # pipelined batches share one compiled shape: round the batch up to
        # the compaction segment (and the Pallas lane tile on that path),
        # then to its power-of-two shape bucket, so two Provers configured
        # with nearby batch sizes (grpc worker tenants, test fixtures)
        # land on ONE prove_scan_step executable instead of minting one
        # each (ops/scrypt.py shape_bucket; both tiles are powers of two,
        # so bucketing preserves the tile multiple)
        tile = proving_pallas.LANE_TILE if use_pallas else proving.HIT_SEGMENT
        self.batch_labels = scrypt.shape_bucket(
            -(-max(batch_labels, tile) // tile) * tile)
        if pipelined is None:
            pipelined = os.environ.get(
                "SPACEMESH_PROVE_PIPELINE", "1") not in ("0", "off")
        self.pipelined = pipelined
        self.window_groups = max(window_groups if window_groups is not None
                                 else _env_int("SPACEMESH_PROVE_WINDOW_GROUPS",
                                               4 if self._platform == "tpu"
                                               else 1), 1)
        self.inflight = max(inflight if inflight is not None
                            else _env_int("SPACEMESH_PROVE_INFLIGHT",
                                          DEFAULT_INFLIGHT), 1)
        self.readers = max(readers if readers is not None
                           else _env_int("SPACEMESH_PROVE_READERS",
                                         DEFAULT_READERS), 1)
        self.reader_queue = max(reader_queue if reader_queue is not None
                                else _env_int("SPACEMESH_PROVE_QUEUE",
                                              DEFAULT_READER_QUEUE), 1)
        self._mesh_arg = mesh
        self.stall_deadline_s = stall_deadline_s
        self.last_stats: ProverStats | None = None

    # -- mesh routing (mirrors post/initializer.py) -------------------------

    def _resolve_mesh(self):
        if self._mesh_arg is None:
            return None
        if self._mesh_arg != "auto":
            mesh = self._mesh_arg
            if mesh.size > 1 and self.batch_labels % mesh.size:
                # an explicitly requested mesh must not silently degrade
                # to a single device
                raise ValueError(
                    f"batch_labels {self.batch_labels} not divisible by "
                    f"the {mesh.size}-device mesh; pick a multiple")
        else:
            from ..ops import autotune

            # ONE definition of the auto routing, shared with
            # post/initializer.py (autotune.resolve_auto_mesh). The race
            # measures the label kernel, not the proving scan — but both
            # are op-dispatch-bound embarrassingly-lane-parallel sweeps,
            # so the tuned device count transfers.
            devs, _ = autotune.resolve_auto_mesh(self.meta.scrypt_n,
                                                 self.batch_labels)
            if devs is None:
                return None
            from ..parallel import mesh as pmesh
            mesh = pmesh.data_mesh(devs)
        if mesh.size <= 1 or self.batch_labels % mesh.size:
            return None
        return mesh

    # -- entry points -------------------------------------------------------

    def prove(self, challenge: bytes) -> Proof:
        if self.pipelined:
            session = self.session(challenge)
            try:
                while True:
                    proof = session.step()
                    if proof is not None:
                        return proof
            finally:
                session.close()
        try:
            return self._prove_serial(challenge, self._pow(challenge))
        finally:
            # drop the store's cached read fds: PostClient builds a fresh
            # Prover per challenge, so a long-lived worker would otherwise
            # leak one fd per postdata file per proving session
            self.store.close()

    def session(self, challenge: bytes, tenant: str = "-") -> "ProveSession":
        """A resumable streaming prove: each ``step()`` is one quantum —
        the k2pow gate first, then one nonce-window disk pass apiece —
        so the multi-tenant scheduler can gang-schedule windows between
        other tenants' work (runtime/scheduler.py). ``prove()`` is just
        a session driven to completion."""
        return ProveSession(self, challenge, tenant=tenant)

    def _prove_pipelined(self, challenge: bytes, pow_nonce: int) -> Proof:
        """Drive a session to completion with the pow gate pre-paid —
        the bench/profiler comparator's entry (post/workload.py), which
        measures the label scan without re-searching the pow per rep."""
        session = self.session(challenge)
        session.pow_nonce = pow_nonce
        try:
            while True:
                proof = session.step()
                if proof is not None:
                    return proof
        finally:
            session.close()

    def prove_serial(self, challenge: bytes) -> Proof:
        """The legacy synchronous scan (read -> scan -> full-mask fetch ->
        host nonzero per group) — kept as the bench baseline and fallback."""
        try:
            return self._prove_serial(challenge, self._pow(challenge))
        finally:
            self.store.close()

    def _pow(self, challenge: bytes) -> int:
        node_id = bytes.fromhex(self.meta.node_id)
        with tracing.span("prove.k2pow"):
            pow_nonce = k2pow.search(challenge, node_id,
                                     self.params.pow_difficulty)
        if pow_nonce is None:
            raise RuntimeError("k2pow search exhausted")
        return pow_nonce

    # -- legacy serial path -------------------------------------------------

    def _prove_serial(self, challenge: bytes, pow_nonce: int) -> Proof:
        meta, p = self.meta, self.params
        t = proving.threshold_u32(p.k1, meta.total_labels)
        cw = jnp.asarray(proving.challenge_words(challenge))
        ng = self.nonce_group
        # Pallas-vs-XLA decided ONCE per prove; ragged tail batches are
        # padded-and-trimmed inside proving_pallas.proving_scan instead of
        # flipping to the XLA path mid-pass (one compiled shape per path)
        use_pallas = self.use_pallas
        interpret = self._platform != "tpu"
        group = 0
        while True:
            hits: list[list[int]] = [[] for _ in range(ng)]
            start = 0
            while start < meta.total_labels:
                count = min(self.batch_labels, meta.total_labels - start)
                idx = np.arange(start, start + count, dtype=np.uint64)
                labels = np.frombuffer(
                    self.store.read_labels(start, count), dtype=np.uint8
                ).reshape(count, scrypt.LABEL_BYTES)
                nonce0 = group * ng
                if use_pallas:
                    mask = proving_pallas.proving_scan(
                        challenge, nonce0, idx, labels, t, n_nonces=ng,
                        interpret=interpret)
                else:
                    lo, hi = scrypt.split_indices(idx)
                    lw = scrypt.labels_to_words(labels)
                    mask = np.asarray(proving.proving_scan_jit(
                        cw, jnp.uint32(nonce0),
                        jnp.asarray(lo), jnp.asarray(hi), jnp.asarray(lw),
                        jnp.uint32(t), n_nonces=ng))
                for k in range(ng):
                    if len(hits[k]) < p.k2:
                        found = np.nonzero(mask[k])[0]
                        hits[k].extend((start + found).tolist())
                start += count
            for k in range(ng):
                if len(hits[k]) >= p.k2:
                    metrics.proofs_generated.inc()
                    return Proof(nonce=group * ng + k,
                                 indices=[int(i) for i in hits[k][:p.k2]],
                                 pow_nonce=pow_nonce, k2=p.k2)
            group += 1
            if group > MAX_GROUPS - 1:
                raise RuntimeError("no winning nonce found (k1/k2 mismatch?)")

    # -- streaming pipeline -------------------------------------------------

    def _make_step(self, mesh):
        """Bind the scan-step backend ONCE per prove (no per-batch paths)."""
        ng, cap = self.nonce_group, max(self.params.k2, 1)
        if mesh is not None:
            from ..parallel import mesh as pmesh
            return functools.partial(pmesh.prove_step_sharded, mesh,
                                     n_nonces=ng, max_hits=cap)
        if self.use_pallas:
            return functools.partial(
                proving_pallas.prove_scan_step_pallas, n_nonces=ng,
                max_hits=cap, interpret=self._platform != "tpu")
        return functools.partial(proving.prove_scan_step_jit,
                                 n_nonces=ng, max_hits=cap)

    def _scan_window(self, cw, thr, nonce_base, groups, step, mesh, stats,
                     tenant: str = "-"):
        """One disk pass over the store scanning ``groups`` nonce groups.
        Returns (winner_nonce, indices) or (None, None).

        The bounded read->dispatch->retire window is the shared runtime
        engine's (runtime/engine.py); this method supplies the prove
        callbacks. Under a trace capture the pass is one ``prove.window``
        span and every per-batch read/dispatch/retire span carries the
        SAME ``window`` attribute (the pass's base nonce), so a timeline
        groups a window's whole ladder even when batches from two
        windows interleave."""
        meta, p = self.meta, self.params
        total = meta.total_labels
        b = self.batch_labels
        ng = self.nonce_group
        cap = max(p.k2, 1)
        traced = tracing.is_enabled()
        wsp = tracing.span("prove.window",
                           {"window": nonce_base, "groups": groups,
                            "labels": total} if traced else None)
        wsp.__enter__()
        reader = None
        try:
            ranges = [(s, min(b, total - s)) for s in range(0, total, b)]
            states = []
            for _ in range(groups):
                counts, carry = proving.init_hit_state(ng, cap)
                if mesh is not None:
                    from ..parallel import mesh as pmesh
                    counts = pmesh.replicate(mesh, counts)
                    carry = pmesh.replicate(mesh, carry)
                states.append([counts, carry])
            host_counts = np.zeros(ng * groups, dtype=np.int64)
            reader = self.store.start_reader(ranges, self.readers,
                                             self.reader_queue)
            metrics.post_prove_windows.inc()
            stats.windows += 1
            retired_end = [0]

            def dispatch(item):
                start, count = item
                tr = time.perf_counter()
                with tracing.span("prove.read_wait",
                                  {"window": nonce_base, "start": start}
                                  if traced else None):
                    raw = reader.get()
                stats.read_wait_s += time.perf_counter() - tr
                labels = np.frombuffer(raw, dtype=np.uint8).reshape(
                    count, scrypt.LABEL_BYTES)
                if count < b:  # pad-and-trim: one shape per pass
                    labels = np.concatenate([
                        labels,
                        np.zeros((b - count, scrypt.LABEL_BYTES),
                                 np.uint8)])
                idx = np.arange(start, start + b, dtype=np.uint64)
                lo, hi = scrypt.split_indices(idx)
                lw = scrypt.labels_to_words(labels)
                jlo, jhi, jlw = (jnp.asarray(lo), jnp.asarray(hi),
                                 jnp.asarray(lw))
                bcs = []
                for g in range(groups):
                    counts, carry = states[g]
                    counts, bc, carry = step(
                        cw, jnp.uint32(nonce_base + g * ng), jlo, jhi,
                        jlw, thr, counts, carry, jnp.uint32(count),
                        jnp.uint32(start & 0xFFFFFFFF),
                        jnp.uint32(start >> 32))
                    states[g] = [counts, carry]
                    bcs.append(bc)
                # progress must advance PER BATCH, here in the callback
                # — folding the engine's count in after the pass would
                # freeze the liveness watchdog for the whole disk pass
                # (ProveSession registers it on stats.batches)
                stats.batches += 1
                metrics.post_prove_batches.inc()
                return start + count, bcs

            def retire(ticket):
                retired_end[0] = ticket[0]
                if self._retire(ticket, host_counts, total, stats,
                                nonce_base):
                    return ticket[0]  # sound early exit: scanned_end
                return None

            pipe = engine.Pipeline(
                kind="prove", tenant=tenant, inflight=self.inflight,
                span="prove",
                attrs=lambda it: {"window": nonce_base, "start": it[0],
                                  "count": it[1]})
            rw0 = stats.read_wait_s
            res = pipe.run(ranges, dispatch, retire)
            exited = res is not None
            # the engine's dispatch stage wraps the whole callback; keep
            # the historical read-wait vs dispatch split in the stats
            stats.dispatch_s += max(
                pipe.stats.dispatch_s - (stats.read_wait_s - rw0), 0.0)
            scanned = retired_end[0] if exited else total
        finally:
            if reader is not None:
                reader.close()
                stats.read_io_s += reader.read_seconds
            wsp.__exit__(None, None, None)
        if exited:
            metrics.post_prove_early_exits.inc()
            stats.early_exited = True
        stats.labels_swept += scanned
        qualified = np.nonzero(host_counts >= p.k2)[0]
        if qualified.size == 0:
            return None, None
        w = int(qualified[0])
        counts, carry = states[w // ng]
        indices = proving.decode_hits(counts, carry, w % ng, p.k2)
        stats.d2h_bytes += carry.nbytes + counts.nbytes
        metrics.post_prove_d2h_bytes.inc(carry.nbytes + counts.nbytes)
        return nonce_base + w, indices

    def _retire(self, item, host_counts, total, stats,
                nonce_base: int = 0) -> bool:
        """Fetch one batch's per-nonce count vectors; True on sound early
        exit: some nonce has k2 hits and every lower nonce in the window
        provably cannot reach k2 with the labels left in this pass (lower
        windows already failed their full pass, so the winner is final and
        identical to the serial prover's end-of-pass pick)."""
        scanned_end, bcs = item
        p = self.params
        ng = self.nonce_group
        tr = time.perf_counter()
        with tracing.span("prove.retire",
                          {"window": nonce_base, "end": scanned_end}
                          if tracing.is_enabled() else None):
            for g, bc in enumerate(bcs):
                vec = np.asarray(bc)
                host_counts[g * ng:(g + 1) * ng] += vec
                stats.d2h_bytes += vec.nbytes
                metrics.post_prove_d2h_bytes.inc(vec.nbytes)
        stats.retire_s += time.perf_counter() - tr
        qualified = host_counts >= p.k2
        if not qualified.any():
            return False
        w = int(np.argmax(qualified))
        remaining = total - scanned_end
        exit_now = bool(np.all(host_counts[:w] + remaining < p.k2))
        if exit_now:
            # the decision point the pipelined prover's speedup hinges
            # on: mark it so a timeline shows WHERE the pass stopped
            tracing.instant("prove.early_exit",
                            {"window": nonce_base, "nonce": nonce_base + w,
                             "scanned": scanned_end}
                            if tracing.is_enabled() else None)
        return exit_now


class ProveSession:
    """One resumable streaming prove over an initialized store.

    ``step()`` runs exactly one quantum — the k2pow gate on the first
    call, then one nonce-window disk pass per call — and returns the
    Proof once decided (None until then).  The multi-tenant scheduler
    gang-schedules these quanta between tenants (runtime/scheduler.py);
    ``Prover.prove`` drives a session to completion inline.  ``close()``
    is idempotent and must run on every path: it unregisters the
    liveness watchdog, finalizes the stats/metrics, and drops the
    store's cached read fds (the PR 3 fd-leak class).
    """

    def __init__(self, prover: Prover, challenge: bytes, tenant: str = "-"):
        self.prover = prover
        self.challenge = challenge
        self.tenant = tenant
        self.stats = ProverStats()
        prover.last_stats = self.stats
        self.pow_nonce: int | None = None
        self.proof: Proof | None = None
        self._t0 = time.monotonic()
        self._base = 0
        self._max_nonce = MAX_GROUPS * prover.nonce_group
        self._prep = None
        self._closed = False
        self._scanning = False
        self._span = tracing.span(
            "prove.run",
            {"challenge": challenge.hex()[:16],
             "labels": prover.meta.total_labels, "tenant": tenant}
            if tracing.is_enabled() else None)
        self._span.__enter__()  # spacecheck: ok=SC004 session-lifecycle span; ProveSession.close() exits it on every path (prove()'s finally, the scheduler's abort hook)
        # liveness (obs/health.py): while the session runs, progress must
        # advance PER BATCH, not per window — a healthy disk pass can
        # legitimately outlive the deadline (the window histogram buckets
        # reach 600s), so a per-window counter would false-stall every
        # realistic prove
        from ..obs import health as health_mod

        # active only WHILE a window scan runs (the historical scope:
        # the old code registered after the k2pow gate) — a session
        # parked between scheduler quanta, or one searching pow, has no
        # batch counter to advance and must not read as stalled
        self._wd = health_mod.Watchdog(
            "post.prove",
            progress=lambda: (self.stats.batches, self.stats.labels_swept),
            deadline_s=prover.stall_deadline_s,
            active=lambda: self._scanning)
        health_mod.HEALTH.register("post.prove", self._wd.check)

    @property
    def done(self) -> bool:
        return self.proof is not None

    def step(self) -> Proof | None:
        if self._closed:
            raise RuntimeError("prove session is closed")
        if self.proof is not None:
            return self.proof
        p = self.prover
        if self.pow_nonce is None:
            self.pow_nonce = p._pow(self.challenge)
            return None
        if self._prep is None:
            thr = jnp.uint32(proving.threshold_u32(
                p.params.k1, p.meta.total_labels))
            cw = jnp.asarray(proving.challenge_words(self.challenge))
            mesh = p._resolve_mesh()
            self._prep = (cw, thr, mesh, p._make_step(mesh))
        cw, thr, mesh, stepfn = self._prep
        if self._base >= self._max_nonce:
            raise RuntimeError("no winning nonce found (k1/k2 mismatch?)")
        # clamp the last window to the serial prover's give-up bound so
        # the two paths search the exact same nonce range
        groups = min(p.window_groups,
                     (self._max_nonce - self._base) // p.nonce_group)
        tw = time.perf_counter()
        self._scanning = True
        try:
            winner, indices = p._scan_window(cw, thr, self._base, groups,
                                             stepfn, mesh, self.stats,
                                             tenant=self.tenant)
        finally:
            self._scanning = False
        metrics.post_prove_window_seconds.observe(time.perf_counter() - tw)
        self._base += groups * p.nonce_group
        if winner is None:
            return None
        metrics.proofs_generated.inc()
        self.proof = Proof(nonce=winner, indices=indices,
                           pow_nonce=self.pow_nonce, k2=p.params.k2)
        return self.proof

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        from ..obs import health as health_mod

        health_mod.HEALTH.unregister("post.prove", self._wd.check)
        self._span.__exit__(None, None, None)
        stats = self.stats
        stats.elapsed_s = time.monotonic() - self._t0
        if stats.elapsed_s > 0:
            metrics.post_prove_labels_per_sec.set(
                stats.labels_swept / stats.elapsed_s)
        for stage, secs in (("read", stats.read_wait_s),
                            ("dispatch", stats.dispatch_s),
                            ("retire", stats.retire_s)):
            metrics.post_prove_stage_seconds.inc(secs, stage=stage)
        self.prover.store.close()
