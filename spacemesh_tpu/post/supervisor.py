"""PostSupervisor: spawn + babysit the out-of-process POST worker.

Mirrors the reference's subprocess management (reference
activation/post_supervisor.go:66-299: runCmd spawns the Rust post-service
with its flags, captures logs, restarts it on exit until stopped). The
worker here is this package's own CLI (`python -m spacemesh_tpu.post
serve`), so one binary covers init/prove/verify/serve.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from pathlib import Path


class PostSupervisor:
    def __init__(self, base_dir: str | Path, listen: str = "127.0.0.1:0",
                 restart_backoff: float = 1.0, env: dict | None = None,
                 params=None, node_address: str | None = None):
        self.base_dir = str(base_dir)
        self.listen = listen
        # gRPC mode (reference topology): worker dials the node's
        # PostService instead of listening (activation/post_supervisor.go
        # passes --address the same way)
        self.node_address = node_address
        self.restart_backoff = restart_backoff
        self.env = env
        self.params = params  # ProofParams for the worker's provers
        self.address: tuple[str, int] | None = None
        self._proc: subprocess.Popen | None = None
        self._stopped = threading.Event()
        self._ready = threading.Event()
        self._thread: threading.Thread | None = None
        self.restarts = -1  # first start is not a restart

    def start(self, timeout: float = 60.0) -> tuple[str, int]:
        """Spawn the worker and wait until it reports its listen port."""
        self._thread = threading.Thread(target=self._babysit, daemon=True)
        self._thread.start()
        if not self._ready.wait(timeout):
            self.stop()
            raise TimeoutError("post worker did not come up")
        if self.node_address is None:
            assert self.address is not None
        return self.address  # None in gRPC dial mode (worker has no port)

    def _spawn(self) -> subprocess.Popen:
        env = dict(os.environ if self.env is None else self.env)
        repo_root = str(Path(__file__).resolve().parent.parent.parent)
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        # every (re)spawned worker shares the machine's persistent XLA
        # compile cache — a crash-restart must not pay the per-shape
        # compile again (utils/accel.py enable_persistent_cache)
        if "SPACEMESH_JAX_CACHE" not in env:
            cache = os.environ.get("SPACEMESH_JAX_CACHE")
            if cache is not None:
                env["SPACEMESH_JAX_CACHE"] = cache
        # keep the worker's port stable across restarts so clients reconnect
        listen = self.listen
        if self.address is not None:
            listen = f"{self.address[0]}:{self.address[1]}"
        cmd = [sys.executable, "-u", "-m", "spacemesh_tpu.post", "serve",
               "--data-dir", self.base_dir, "--listen", listen]
        if self.node_address is not None:
            cmd += ["--node-address", self.node_address]
        if self.params is not None:
            cmd += ["--k1", str(self.params.k1), "--k2", str(self.params.k2),
                    "--k3", str(self.params.k3),
                    "--pow-difficulty", self.params.pow_difficulty.hex()]
        return subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            env=env, text=True)

    def _babysit(self) -> None:
        while not self._stopped.is_set():
            self._proc = self._spawn()
            self.restarts += 1
            if self._stopped.is_set():
                # stop() raced our spawn; it may have terminated only the
                # previous proc — reap this one ourselves
                self._proc.terminate()
                self._proc.wait(timeout=10)
                return
            for line in self._proc.stdout:  # type: ignore[union-attr]
                try:
                    ev = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if ev.get("event") == "Serving":
                    self.address = (ev["host"], ev["port"])
                    self._ready.set()
                elif ev.get("event") == "Registering":
                    self._ready.set()
            self._proc.wait()
            if self._stopped.is_set():
                return
            time.sleep(self.restart_backoff)  # crash: restart

    def stop(self) -> None:
        self._stopped.set()
        # _babysit may be mid-restart: keep terminating whatever proc is
        # current until the babysitter thread exits
        for _ in range(5):
            proc = self._proc
            if proc is not None and proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    proc.kill()
            if self._thread is None or not self._thread.is_alive():
                return
            self._thread.join(timeout=3)
            if not self._thread.is_alive():
                return

    def alive(self) -> bool:
        return self._proc is not None and self._proc.poll() is None
