"""On-disk POST data layout: label files + resume metadata.

Mirrors the reference initializer's data directory contract (post-rs writes
``postdata_N.bin`` label files plus a metadata file; resume is driven by the
number of labels already on disk — reference activation/post.go:267-270
"initialization will resume from NumLabelsWritten"). Here metadata is JSON,
written durably (tmp + fsync + rename + dir-fsync, utils/fsio.py) on an
interval so a killed init resumes exactly where the *fsynced* bytes stopped.

Durability contract (docs/CRASH_SAFETY.md):

* the LabelWriter tracks two cursors — ``flushed()`` (contiguous bytes
  handed to the OS) and ``durable()`` (contiguous bytes **fsynced**);
  only the durable cursor is ever persisted as ``labels_written``;
* every metadata checkpoint carries a CRC32 of the label interval it
  covers (``PostMetadata.intervals``), so reopen can verify the tail
  and truncate torn bytes back to the last checkpoint that checks out
  (:func:`recover_store`);
* all file I/O goes through an injectable ``fs`` (utils/fsio.RealFS by
  default) so the deterministic disk-fault shim (post/faultfs.py) can
  crash the pipeline at exact operation counts;
* ENOSPC in the writer pool is graceful degradation, not death: the
  pool parks in a retry loop, the ``post.store`` health probe flips
  (/readyz degraded), and the init resumes when space returns.
"""

from __future__ import annotations

import dataclasses
import errno
import json
import os
import queue
import threading
import time
import zlib
from pathlib import Path

from ..ops.scrypt import LABEL_BYTES
from ..utils import fsio, metrics, sanitize, tracing

METADATA_FILE = "postdata_metadata.json"

# bounded retry for transient read errors (the prover's disk passes):
# mirrors p2p/fetch.py's capped exponential backoff idiom
READ_RETRIES = 3
READ_BACKOFF_BASE_S = 0.05
READ_BACKOFF_CAP_S = 1.0

# ledger backfill segment for pre-checksum stores (recover_store): the
# tail interval is what every reopen re-reads to verify, so it must
# stay bounded — matches the initializer's default checkpoint interval
BACKFILL_INTERVAL_LABELS = 1 << 20


class PostMetaCorrupt(ValueError):
    """postdata_metadata.json exists but cannot be decoded (truncated
    write, torn sector, wrong schema). Carries the offending path so
    the operator knows WHICH identity's resume state is gone."""

    def __init__(self, path, detail: str):
        super().__init__(f"corrupt POST metadata at {path}: {detail}")
        self.path = str(path)


class LabelWriteError(RuntimeError):
    """The background label writer failed; ``errno`` is set for OS-level
    failures so callers can branch on ENOSPC/EIO without string
    matching. Message kept compatible with the historical
    "background label writer failed" surface."""

    def __init__(self, msg: str = "background label writer failed",
                 errno_: int | None = None):
        super().__init__(msg)
        self.errno = errno_


@dataclasses.dataclass
class PostMetadata:
    """Identity + geometry of one smesher's POST data directory."""

    node_id: str               # hex, 32 bytes
    commitment: str            # hex, 32 bytes (commitment = H(node_id, atx))
    scrypt_n: int
    num_units: int
    labels_per_unit: int
    max_file_size: int         # bytes per postdata file
    labels_written: int = 0    # resume cursor: contiguous FSYNCED labels
    vrf_nonce: int | None = None       # index of the numerically smallest label
    vrf_nonce_value: str | None = None  # hex of that label (16 bytes)
    # checkpoint ledger: [[end_label, crc32-of-[prev_end, end)], ...] —
    # reopen verifies the tail interval and steps back through this list
    # until one checks out (recover_store). Empty on pre-checksum stores.
    intervals: list = dataclasses.field(default_factory=list)

    @property
    def total_labels(self) -> int:
        return self.num_units * self.labels_per_unit

    @property
    def labels_per_file(self) -> int:
        return self.max_file_size // LABEL_BYTES

    def save(self, data_dir: str | Path, fs=None) -> None:
        path = Path(data_dir) / METADATA_FILE
        fsio.atomic_write_text(
            path, json.dumps(dataclasses.asdict(self), indent=1), fs=fs)

    @classmethod
    def load(cls, data_dir: str | Path, fs=None) -> "PostMetadata":
        path = Path(data_dir) / METADATA_FILE
        # a crash between tmp write and rename leaves a stray staging
        # file whose payload was never published; the durable truth is
        # ``path`` itself — drop the stragglers
        fsio.cleanup_stale_tmps(path, fs=fs)
        text = path.read_text()  # FileNotFoundError propagates: no store
        try:
            doc = json.loads(text)
        except ValueError as e:
            raise PostMetaCorrupt(path, f"unparseable JSON ({e})") from e
        if not isinstance(doc, dict):
            raise PostMetaCorrupt(path, "document is not an object")
        try:
            return cls(**doc)
        except TypeError as e:
            raise PostMetaCorrupt(path, f"wrong schema ({e})") from e


class LabelStore:
    """Reads/writes the ``postdata_N.bin`` files for one data directory."""

    def __init__(self, data_dir: str | Path, meta: PostMetadata, fs=None):
        self.dir = Path(data_dir)
        self.meta = meta
        self.fs = fs if fs is not None else fsio.REAL
        self.dir.mkdir(parents=True, exist_ok=True)
        self._fd_lock = sanitize.lock("post.data.LabelStore.fds")
        self._read_fds: dict[int, int] = {}
        self._dirty: set[int] = set()  # file indices written, not fsynced

    def _file(self, i: int) -> Path:
        return self.dir / f"postdata_{i}.bin"

    def _read_fd(self, i: int) -> int:
        """Cached O_RDONLY fd for file ``i`` — the prover issues thousands
        of positioned reads per pass and an open() per call is pure syscall
        overhead (and defeats readahead heuristics on some filesystems)."""
        with self._fd_lock:
            fd = self._read_fds.get(i)
            if fd is None:
                fd = self.fs.open(self._file(i), os.O_RDONLY)
                self._read_fds[i] = fd
            return fd

    def _drop_read_fd(self, i: int) -> None:
        with self._fd_lock:
            fd = self._read_fds.pop(i, None)
        if fd is not None:
            try:
                self.fs.close(fd)
            except OSError:
                pass

    def close(self) -> None:
        """Drop cached read fds (safe to call repeatedly; reads reopen)."""
        with self._fd_lock:
            fds, self._read_fds = self._read_fds, {}
        for fd in fds.values():
            try:
                self.fs.close(fd)
            except OSError:
                pass

    def invalidate(self) -> None:
        """Recovery hook: a cached read fd pins the pre-truncation inode
        — after recovery rewrites or truncates label files, cached fds
        must not serve stale bytes. Alias of close(); reads reopen."""
        self.close()

    def write_labels(self, start_index: int, labels: bytes) -> None:
        """Write ``labels`` (concatenated 16B records) at ``start_index``.

        Thread-safe: O_CREAT without O_TRUNC plus positioned pwrite, so
        concurrent writers (the background pool, per-shard stripes) landing
        in the same file never truncate or clobber each other's ranges.
        Short writes (POSIX-legal, and one of faultfs's injected faults)
        are retried until the range is fully handed to the OS.
        """
        lpf = self.meta.labels_per_file
        idx = start_index
        off = 0
        while off < len(labels):
            fi, within = divmod(idx, lpf)
            take = min(len(labels) - off, (lpf - within) * LABEL_BYTES)
            fd = self.fs.open(self._file(fi),
                              os.O_CREAT | os.O_WRONLY, 0o644)
            try:
                view = memoryview(labels)[off:off + take]
                pos = within * LABEL_BYTES
                while len(view):
                    n = self.fs.pwrite(fd, view, pos)
                    if n <= 0:
                        raise IOError(
                            f"zero-length write at label {idx} "
                            f"(file {fi})")
                    view = view[n:]
                    pos += n
            finally:
                self.fs.close(fd)
            with self._fd_lock:
                self._dirty.add(fi)
            off += take
            idx += take // LABEL_BYTES

    def sync(self) -> None:
        """fsync every label file written since the last sync — the
        durability boundary the writer pool's durable cursor (and so
        the persisted resume cursor) advances over. On failure the
        un-synced files stay marked dirty."""
        with self._fd_lock:
            dirty, self._dirty = self._dirty, set()
        done = set()
        try:
            for fi in sorted(dirty):
                path = self._file(fi)
                try:
                    fd = self.fs.open(path, os.O_RDONLY)
                except FileNotFoundError:
                    done.add(fi)  # recovery removed it; nothing to sync
                    continue
                try:
                    self.fs.fsync(fd)
                finally:
                    self.fs.close(fd)
                metrics.post_store_fsyncs.inc()
                done.add(fi)
        finally:
            failed = dirty - done
            if failed:
                with self._fd_lock:
                    self._dirty |= failed

    def start_writer(self, threads: int = 2, queue_depth: int = 8,
                     **writer_opts) -> "LabelWriter":
        """A background writer pool bound to this store (``writer_opts``
        pass through: enospc_wait, enospc_retry_s)."""
        return LabelWriter(self, threads=threads, queue_depth=queue_depth,
                           **writer_opts)

    def start_reader(self, ranges, threads: int = 2,
                     depth: int = 4) -> "LabelReader":
        """A background prefetching reader pool bound to this store."""
        return LabelReader(self, ranges, threads=threads, depth=depth)

    def _pread_retry(self, fi: int, nbytes: int, offset: int) -> bytes:
        """One positioned read with bounded EIO retry (the p2p/fetch.py
        capped-backoff idiom): a transient medium error mid-prove costs
        a short pause and a reopen, not the whole multi-window pass.
        Anything past the retry budget (or any other errno) propagates."""
        attempt = 0
        while True:
            try:
                return self.fs.pread(self._read_fd(fi), nbytes, offset)
            except OSError as e:
                if e.errno != errno.EIO or attempt >= READ_RETRIES:
                    raise
                metrics.post_store_read_retries.inc()
                # the cached fd may be the problem (stale mapping,
                # revoked descriptor): reopen before retrying
                self._drop_read_fd(fi)
                time.sleep(min(READ_BACKOFF_CAP_S,
                               READ_BACKOFF_BASE_S * (2 ** attempt)))
                attempt += 1

    def read_labels(self, start_index: int, count: int) -> bytes:
        lpf = self.meta.labels_per_file
        out = bytearray()
        idx = start_index
        remaining = count
        while remaining > 0:
            fi, within = divmod(idx, lpf)
            take = min(remaining, lpf - within)
            chunk = self._pread_retry(fi, take * LABEL_BYTES,
                                      within * LABEL_BYTES)
            if len(chunk) != take * LABEL_BYTES:
                raise IOError(
                    f"short read at label {idx}: file {fi} truncated")
            out += chunk
            idx += take
            remaining -= take
        metrics.post_store_read_calls.inc()
        metrics.post_store_read_bytes.inc(count * LABEL_BYTES)
        return bytes(out)


class LabelWriter:
    """Bounded-queue background writer pool over one LabelStore.

    The streaming initializer hands fetched label bytes here instead of
    writing inline, so disk IO overlaps accelerator compute and PCIe
    fetches. The bounded queue gives backpressure: when disk falls behind,
    ``submit`` blocks the dispatch loop (a visible stall, counted by the
    caller) instead of buffering unboundedly.

    Durability ordering: ``flushed()`` is the label index up to which ALL
    bytes are contiguously handed to the OS (writes may complete out of
    order across pool threads and mesh shard stripes); ``durable()`` is
    the index up to which they are contiguously **fsynced** — it advances
    only at checkpoint/drain boundaries, after the dirty label files are
    synced. The initializer never persists a metadata cursor beyond
    ``durable()`` — that is the crash-consistency contract the resume
    path (and :func:`recover_store`) relies on.

    ENOSPC is graceful degradation, not death (``enospc_wait=True``):
    the failing worker parks in a bounded-interval retry loop,
    ``degraded()`` reports why (the ``post.store`` health probe serves
    it on /readyz), backpressure pauses the dispatch loop, and the
    pipeline resumes by itself when space returns. Any other OS error —
    or ENOSPC with the wait disabled — fails the pool with a typed
    :class:`LabelWriteError` and unblocks queued submitters.
    """

    _STOP = object()

    def __init__(self, store: LabelStore, threads: int = 2,
                 queue_depth: int = 8, enospc_wait: bool = True,
                 enospc_retry_s: float = 0.5):
        self.store = store
        self.enospc_wait = enospc_wait
        self.enospc_retry_s = enospc_retry_s
        self._q: queue.Queue = queue.Queue(maxsize=max(queue_depth, 1))
        self._lock = sanitize.lock("post.data.LabelWriter")
        self._idle = sanitize.condition("post.data.LabelWriter.idle",
                                        self._lock)
        # the cursors and their completion map are DECLARED SHARED
        # (SPACEMESH_SANITIZE=race): the dispatch thread, the pool
        # threads and the watchdog all meet here, always under _lock
        self._shared = sanitize.SharedField("post.data.LabelWriter.cursor")
        self._done: dict[int, tuple[int, bytes]] = {}  # start -> (end, bytes)
        self._flushed = store.meta.labels_written
        self._durable = store.meta.labels_written
        # running CRC32 over the contiguous flushed bytes of the OPEN
        # checkpoint interval; cut (and reset) at checkpoint() — feeding
        # happens in completion order under _lock, so at any instant the
        # CRC covers exactly [interval start, _flushed)
        self._crc = 0
        self._degraded: str | None = None
        self._ckpt_active = False  # parks the flushed/CRC advance
        self._inflight = 0
        self._error: BaseException | None = None
        self._closed = False
        self.labels_submitted = 0
        self.bytes_written = 0
        self.write_seconds = 0.0
        self._threads = [
            threading.Thread(target=self._worker, daemon=True,
                             name=f"label-writer-{i}")
            for i in range(max(threads, 1))]
        for t in self._threads:
            t.start()

    # -- dispatch side ------------------------------------------------------

    def submit(self, start_index: int, labels: bytes) -> None:
        """Enqueue one write; blocks when the queue is full (backpressure).

        A blocked submitter re-checks the pool's failure flag between
        bounded put attempts, so a writer that dies with the queue full
        unblocks every waiting submitter with the typed error instead
        of deadlocking them against a queue nobody will drain."""
        self._raise_if_failed()
        with self._lock:
            self._shared.touch()
            if self._closed:
                raise RuntimeError("writer is closed")
            self._inflight += 1
        self.labels_submitted += len(labels) // LABEL_BYTES
        # pool threads are long-lived and cannot inherit the submitter's
        # contextvars; the span parent rides along with the work item
        item = (start_index, labels, tracing.current_id())
        while True:
            try:
                self._q.put(item, timeout=0.2)
                return
            except queue.Full:
                try:
                    self._raise_if_failed()
                except LabelWriteError:
                    with self._lock:
                        self._shared.touch()
                        self._inflight -= 1
                    raise

    def flushed(self) -> int:
        """Highest label index with every prior label contiguously handed
        to the OS (NOT necessarily on the platter — see durable())."""
        with self._lock:
            self._shared.touch(write=False)
            return self._flushed

    def durable(self) -> int:
        """Highest label index with every prior label contiguously
        FSYNCED. Advances at checkpoint()/drain() boundaries only."""
        with self._lock:
            self._shared.touch(write=False)
            return self._durable

    def degraded(self) -> str | None:
        """Why the pool is parked (ENOSPC retry loop), or None while
        healthy — the ``post.store`` health probe's source."""
        with self._lock:
            self._shared.touch(write=False)
            return self._degraded

    def kick(self) -> None:
        """Wake a parked ENOSPC retry immediately (tests, and the
        operator's 'I freed space, go' signal)."""
        with self._idle:
            self._shared.touch()
            self._idle.notify_all()

    def wait_for_space(self, what: str) -> None:
        """Park the caller in the ENOSPC degraded state for one retry
        interval: flips ``degraded()`` (the ``post.store`` probe), then
        waits ``enospc_retry_s`` or a ``kick()``. The pool's own write
        path parks itself here; the initializer's checkpoint/metadata
        saves park through it too, so EVERY ENOSPC in the storage plane
        pauses the pipeline instead of killing the session."""
        with self._idle:
            self._shared.touch()
            if self._closed:
                raise LabelWriteError("writer closed while waiting "
                                      "for disk space",
                                      errno_=errno.ENOSPC)
            self._degraded = f"enospc: {what} waiting for space"
            metrics.post_store_degraded.set(1.0)
            metrics.post_store_enospc_waits.inc()
            self._idle.wait(timeout=self.enospc_retry_s)

    def clear_degraded(self) -> None:
        with self._idle:
            self._shared.touch()
            was = self._degraded is not None
            self._degraded = None
        if was:
            metrics.post_store_degraded.set(0.0)

    def checkpoint(self) -> tuple[int, int]:
        """Make the flushed prefix durable: snapshot the flushed cursor
        and the open interval's CRC, fsync the dirty label files, then
        advance the durable cursor (and cut the CRC interval) at the
        snapshot. Returns ``(durable, interval_crc)`` where the CRC
        covers [previous checkpoint, durable) — the pair the
        initializer persists in ``PostMetadata.intervals``.

        The contiguous-flushed advance is held parked while the fsync
        runs (completed chunks buffer in the out-of-order map), so the
        CRC cut lands exactly at the durable cursor even when pool
        threads complete writes mid-checkpoint — and a FAILED fsync
        (ENOSPC wait-and-retry) leaves the interval intact for the
        retry instead of zeroing it."""
        with self._lock:
            self._shared.touch()
            self._ckpt_active = True
            f = self._flushed
            crc = self._crc
        try:
            self.store.sync()
        except BaseException:
            with self._idle:
                self._shared.touch()
                self._ckpt_active = False
                self._advance_locked()
                self._idle.notify_all()
            raise
        with self._idle:
            self._shared.touch()
            self._durable = f
            self._crc = 0
            self._ckpt_active = False
            self._advance_locked()
            self._idle.notify_all()
        return f, crc

    def pending(self) -> int:
        """Writes submitted but not yet on disk — the stall watchdog's
        activity gate (obs/health.py writer_watchdog): while this is
        non-zero the durable cursor must keep advancing."""
        with self._lock:
            self._shared.touch(write=False)
            return self._inflight

    def queue_depth(self) -> int:
        return self._q.qsize()

    def drain(self) -> None:
        """Block until every submitted write is durably on disk: waits
        the pool idle, fsyncs the dirty files, advances the durable
        cursor. Does NOT cut the checkpoint CRC interval — a checkpoint
        after drain still covers [last checkpoint, here)."""
        with self._idle:
            self._shared.touch(write=False)
            while self._inflight > 0 and self._error is None:
                self._idle.wait(timeout=0.1)
        self._raise_if_failed()
        with self._lock:
            self._shared.touch(write=False)
            f = self._flushed
        while True:
            try:
                self.store.sync()
                break
            except OSError as e:
                if e.errno != errno.ENOSPC or not self.enospc_wait:
                    raise
                self.wait_for_space("label fsync at drain")
        self.clear_degraded()
        with self._lock:
            self._shared.touch()
            self._durable = f

    def close(self, drain: bool = True) -> None:
        try:
            # the error/closed flags are written by pool threads under
            # the lock; an unlocked read here could miss a just-landed
            # failure and drain() a pool that will never go idle (SC007)
            with self._lock:
                self._shared.touch(write=False)
                if self._closed:
                    return
                failed = self._error is not None
            if drain and not failed:
                self.drain()
        finally:
            # a drain() error must still stop the pool: workers keep
            # consuming the queue even after a write failure, so the STOP
            # sentinels always get through. A worker parked in the ENOSPC
            # retry loop is kicked awake (it sees _closed and surfaces),
            # so a full queue can always make room for the sentinels.
            with self._lock:
                self._shared.touch()
                self._closed = True
            self.kick()
            for _ in self._threads:
                while True:
                    try:
                        self._q.put(self._STOP, timeout=0.2)
                        break
                    except queue.Full:
                        self.kick()
            for t in self._threads:
                t.join(timeout=10)

    def _raise_if_failed(self) -> None:
        with self._lock:
            error = self._error
        if error is not None:
            raise LabelWriteError(
                errno_=getattr(error, "errno", None)) from error

    # -- pool side ----------------------------------------------------------

    def _write_with_enospc_wait(self, start: int, labels: bytes) -> None:
        """One write; ENOSPC parks this worker in a retry loop (the
        degraded mode) instead of failing the pool. Every retry is a
        real write attempt — under faultfs the attempts advance the op
        counter, so a plan's ``hold_ops`` window releases space at a
        deterministic attempt number, sleep-free for tests via
        ``kick()`` + a short ``enospc_retry_s``."""
        while True:
            try:
                self.store.write_labels(start, labels)
                self.clear_degraded()
                return
            except OSError as e:
                if e.errno != errno.ENOSPC or not self.enospc_wait:
                    raise
                self.wait_for_space(f"label write at {start}")

    def _worker(self) -> None:
        while True:
            item = self._q.get()
            if item is self._STOP:
                return
            start, labels, parent = item
            t0 = time.perf_counter()
            try:
                with tracing.span("init.write",
                                  {"start": start,
                                   "labels": len(labels) // LABEL_BYTES}
                                  if tracing.is_enabled() else None,
                                  parent=parent):
                    self._write_with_enospc_wait(start, labels)
            except BaseException as e:  # noqa: BLE001 — surfaced to caller
                with self._idle:
                    self._shared.touch()
                    if self._error is None:
                        self._error = e
                    self._inflight -= 1
                    self._idle.notify_all()
                continue
            count = len(labels) // LABEL_BYTES
            with self._idle:
                self._shared.touch()
                self.write_seconds += time.perf_counter() - t0
                self.bytes_written += len(labels)
                self._done[start] = (start + count, labels)
                self._advance_locked()
                self._inflight -= 1
                self._idle.notify_all()

    # guarded by: self._lock — callers advance the cursor with the lock held
    def _advance_locked(self) -> None:
        """Advance the contiguous-flushed cursor, feeding each chunk to
        the open checkpoint interval's CRC in order. Parked while a
        checkpoint snapshot is being fsynced so the CRC cut lands
        exactly at the durable cursor."""
        if self._ckpt_active:
            return
        while self._flushed in self._done:
            end, chunk = self._done.pop(self._flushed)
            self._crc = zlib.crc32(chunk, self._crc)
            self._flushed = end


class LabelReader:
    """Bounded read-ahead pool over one LabelStore — the prover-side mirror
    of LabelWriter.

    The streaming prover hands the whole pass plan (an ordered list of
    ``(start_index, count)`` ranges) here; pool threads read ahead while the
    device scans, and ``get()`` yields each range's bytes *in plan order*.
    At most ``depth`` ranges are buffered or being read at once, so a stalled
    consumer (device backpressure) caps reader memory at
    ``depth * batch * LABEL_BYTES`` instead of the whole store.
    """

    def __init__(self, store: LabelStore, ranges, threads: int = 2,
                 depth: int = 4):
        self.store = store
        self.ranges: list[tuple[int, int]] = list(ranges)
        # pool threads can't inherit contextvars; reads parent under the
        # span that planned the pass (the prover's window span)
        self._trace_parent = tracing.current_id()
        self._cond = sanitize.condition("post.data.LabelReader")
        self._shared = sanitize.SharedField("post.data.LabelReader.state")
        self._results: dict[int, bytes] = {}
        self._claim = 0          # next plan slot a worker may take
        self._consume = 0        # next plan slot get() returns
        self._budget = max(depth, 1)  # free read-ahead slots
        self._error: BaseException | None = None
        self._closed = False
        self.read_seconds = 0.0
        self.bytes_read = 0
        self._threads = [
            threading.Thread(target=self._worker, daemon=True,
                             name=f"label-reader-{i}")
            for i in range(max(threads, 1))]
        for t in self._threads:
            t.start()

    def get(self) -> bytes:
        """Bytes of the next range in plan order; blocks until prefetched.

        In-order results buffered before a background failure are still
        delivered; the error surfaces on the first range that is actually
        missing (so an error past an early-exit point cannot abort a prove
        that never needed those bytes)."""
        with self._cond:
            self._shared.touch()
            while (self._consume not in self._results
                   and self._error is None):
                if self._consume >= len(self.ranges):
                    raise IndexError("read plan exhausted")
                self._cond.wait(timeout=0.1)
            if self._consume in self._results:
                data = self._results.pop(self._consume)
                self._consume += 1
                self._budget += 1
                self._cond.notify_all()
                return data
            raise RuntimeError("background label reader failed") \
                from self._error

    def close(self) -> None:
        """Stop the pool; safe mid-plan (early exit drops pending reads)."""
        with self._cond:
            self._shared.touch()
            self._closed = True
            self._cond.notify_all()
        for t in self._threads:
            t.join(timeout=10)

    def _worker(self) -> None:
        while True:
            with self._cond:
                self._shared.touch()
                while (not self._closed and self._error is None
                       and (self._budget <= 0
                            or self._claim >= len(self.ranges))):
                    if self._claim >= len(self.ranges):
                        return  # plan fully claimed
                    self._cond.wait(timeout=0.1)
                if self._closed or self._error is not None:
                    return
                slot = self._claim
                self._claim += 1
                self._budget -= 1
            start, count = self.ranges[slot]
            t0 = time.perf_counter()
            try:
                with tracing.span("prove.read_io",
                                  {"start": start, "count": count}
                                  if tracing.is_enabled() else None,
                                  parent=self._trace_parent):
                    data = self.store.read_labels(start, count)
            except BaseException as e:  # noqa: BLE001 — surfaced via get()
                with self._cond:
                    self._shared.touch()
                    if self._error is None:
                        self._error = e
                    self._cond.notify_all()
                return
            with self._cond:
                self._shared.touch()
                self.read_seconds += time.perf_counter() - t0
                self.bytes_read += len(data)
                self._results[slot] = data
                self._cond.notify_all()


# --- crash recovery ------------------------------------------------------


@dataclasses.dataclass
class RecoveryReport:
    """What reopen had to repair (all zero on a clean shutdown)."""

    verified_labels: int = 0       # tail-interval labels crc-checked
    truncated_bytes: int = 0       # torn/un-fsynced bytes removed
    removed_files: int = 0         # label files wholly past the cursor
    intervals_dropped: int = 0     # checkpoints that failed their CRC
    rolled_back_labels: int = 0    # cursor retreat across dropped intervals
    cursor: int = 0                # the verified resume cursor

    @property
    def acted(self) -> bool:
        return bool(self.truncated_bytes or self.removed_files
                    or self.intervals_dropped)


def _disk_extent(store: LabelStore, total: int) -> int:
    """Contiguous labels actually present on disk from index 0."""
    lpf = store.meta.labels_per_file
    extent = 0
    fi = 0
    while extent < total:
        path = store._file(fi)
        try:
            size = store.fs.getsize(path)
        except OSError:
            break
        extent += min(size // LABEL_BYTES, lpf)
        if size < lpf * LABEL_BYTES:
            break
        fi += 1
    return min(extent, total)


def _crc_of_range(store: LabelStore, start: int, end: int,
                  chunk: int = 1 << 16) -> int:
    crc = 0
    idx = start
    while idx < end:
        take = min(chunk, end - idx)
        crc = zlib.crc32(store.read_labels(idx, take), crc)
        idx += take
    return crc


def recover_store(data_dir: str | Path, meta: PostMetadata, fs=None,
                  store: LabelStore | None = None) -> RecoveryReport:
    """Reopen-time recovery: converge the on-disk label files and the
    metadata cursor to a verified, mutually consistent state.

    1. Clamp the cursor to the contiguous on-disk extent (a durable
       claim past the actual bytes means the metadata survived a crash
       its label fsync did not — step back to a checkpoint that did).
    2. Verify the tail checkpoint interval's CRC32, stepping back
       through ``meta.intervals`` until one checks out (pre-checksum
       stores with no ledger are trusted as-is, the historical
       behavior).
    3. Truncate torn/un-fsynced bytes past the verified cursor, remove
       label files wholly past it, fsync what was touched.
    4. Persist the repaired metadata (durable write, utils/fsio).

    Every reopen runs this; a clean shutdown no-ops. Emits
    ``post_store_recovery_*`` metrics and an ``init.recover`` span.
    Raises nothing store-specific on a healthy directory; I/O errors
    propagate (under a fault plan, possibly as further injected
    faults — the crash harness reboots and reopens again).
    """
    own_store = store is None
    st = store if store is not None else LabelStore(data_dir, meta, fs=fs)
    report = RecoveryReport()
    span = tracing.span("init.recover", {"dir": str(data_dir)}
                        if tracing.is_enabled() else None)
    span.__enter__()
    try:
        total = meta.total_labels
        lpf = meta.labels_per_file
        cursor = min(meta.labels_written, total)
        intervals = [list(map(int, iv)) for iv in (meta.intervals or [])]
        extent = _disk_extent(st, total)

        if intervals:
            # the ledger's last entry IS the durable claim; a cursor
            # past it (or past the disk) steps back through checkpoints
            cursor = min(cursor, intervals[-1][0])
            while intervals and intervals[-1][0] > extent:
                intervals.pop()
                report.intervals_dropped += 1
            cursor = min(cursor,
                         intervals[-1][0] if intervals else 0)
            # tail verification: re-read the newest surviving interval
            # and step back until a checkpoint's CRC checks out
            while intervals:
                end, want = intervals[-1]
                prev = intervals[-2][0] if len(intervals) > 1 else 0
                st.invalidate()  # never verify through stale fds
                try:
                    got = _crc_of_range(st, prev, end)
                except (OSError, IOError):
                    got = None  # unreadable tail counts as failed
                if got == want:
                    report.verified_labels += end - prev
                    cursor = end
                    break
                intervals.pop()
                report.intervals_dropped += 1
                cursor = prev
            if not intervals:
                cursor = 0
        else:
            # pre-checksum metadata: trust the cursor up to the bytes
            # actually present (the historical contract), and backfill
            # the ledger so the NEXT checkpoint's interval starts from
            # a boundary recovery can verify — without this, the first
            # post-upgrade checkpoint would claim [0, durable) with a
            # CRC that only covers the new bytes. Backfill in bounded
            # SEGMENTS: one whole-store interval would make every later
            # reopen's tail verification a full-store scan.
            cursor = min(cursor, extent)
            if cursor > 0:
                st.invalidate()
                intervals = []
                start = 0
                while start < cursor:
                    end = min(start + BACKFILL_INTERVAL_LABELS, cursor)
                    intervals.append([end, _crc_of_range(st, start, end)])
                    start = end

        report.rolled_back_labels = max(meta.labels_written - cursor, 0)

        # drop torn/un-fsynced bytes past the verified cursor — every
        # postdata file on disk, holes included (a stray high-index
        # file is exactly what an un-fsynced out-of-order stripe leaves)
        touched = False
        for path in sorted(Path(st.dir).glob("postdata_*.bin")):
            try:
                fi = int(path.stem.rsplit("_", 1)[1])
            except ValueError:
                continue
            try:
                size = st.fs.getsize(path)
            except OSError:
                continue
            expect = max(0, min(cursor - fi * lpf, lpf)) * LABEL_BYTES
            if size > expect:
                if expect == 0 and fi * lpf >= cursor:
                    st.fs.unlink(path)
                    report.removed_files += 1
                else:
                    st.fs.truncate(path, expect)
                report.truncated_bytes += size - expect
                touched = True
        if touched:
            st.invalidate()
            fsio.fsync_dir(st.dir, fs=st.fs)

        changed = (cursor != meta.labels_written
                   or intervals != [list(map(int, iv))
                                    for iv in (meta.intervals or [])])
        meta.labels_written = cursor
        meta.intervals = intervals
        report.cursor = cursor
        if changed or report.acted:
            meta.save(st.dir, fs=st.fs)
            metrics.post_store_recovery_runs.inc()
            metrics.post_store_recovery_truncated_bytes.inc(
                report.truncated_bytes)
            metrics.post_store_recovery_intervals_dropped.inc(
                report.intervals_dropped)
        span.set(cursor=cursor, truncated=report.truncated_bytes,
                 dropped=report.intervals_dropped)
        return report
    finally:
        span.__exit__(None, None, None)
        if own_store:
            st.close()
