"""On-disk POST data layout: label files + resume metadata.

Mirrors the reference initializer's data directory contract (post-rs writes
``postdata_N.bin`` label files plus a metadata file; resume is driven by the
number of labels already on disk — reference activation/post.go:267-270
"initialization will resume from NumLabelsWritten"). Here metadata is JSON,
written atomically (tmp + rename) after every flushed batch so a killed
init resumes exactly where the bytes stopped.
"""

from __future__ import annotations

import dataclasses
import json
import os
from pathlib import Path

from ..ops.scrypt import LABEL_BYTES

METADATA_FILE = "postdata_metadata.json"


@dataclasses.dataclass
class PostMetadata:
    """Identity + geometry of one smesher's POST data directory."""

    node_id: str               # hex, 32 bytes
    commitment: str            # hex, 32 bytes (commitment = H(node_id, atx))
    scrypt_n: int
    num_units: int
    labels_per_unit: int
    max_file_size: int         # bytes per postdata file
    labels_written: int = 0    # resume cursor
    vrf_nonce: int | None = None       # index of the numerically smallest label
    vrf_nonce_value: str | None = None  # hex of that label (16 bytes)

    @property
    def total_labels(self) -> int:
        return self.num_units * self.labels_per_unit

    @property
    def labels_per_file(self) -> int:
        return self.max_file_size // LABEL_BYTES

    def save(self, data_dir: str | Path) -> None:
        path = Path(data_dir) / METADATA_FILE
        tmp = path.with_suffix(".tmp")
        tmp.write_text(json.dumps(dataclasses.asdict(self), indent=1))
        os.replace(tmp, path)

    @classmethod
    def load(cls, data_dir: str | Path) -> "PostMetadata":
        return cls(**json.loads((Path(data_dir) / METADATA_FILE).read_text()))


class LabelStore:
    """Reads/writes the ``postdata_N.bin`` files for one data directory."""

    def __init__(self, data_dir: str | Path, meta: PostMetadata):
        self.dir = Path(data_dir)
        self.meta = meta
        self.dir.mkdir(parents=True, exist_ok=True)

    def _file(self, i: int) -> Path:
        return self.dir / f"postdata_{i}.bin"

    def write_labels(self, start_index: int, labels: bytes) -> None:
        """Append ``labels`` (concatenated 16B records) at ``start_index``."""
        lpf = self.meta.labels_per_file
        idx = start_index
        off = 0
        while off < len(labels):
            fi, within = divmod(idx, lpf)
            take = min(len(labels) - off, (lpf - within) * LABEL_BYTES)
            with open(self._file(fi), "r+b" if self._file(fi).exists() else "wb") as f:
                f.seek(within * LABEL_BYTES)
                f.write(labels[off:off + take])
            off += take
            idx += take // LABEL_BYTES

    def read_labels(self, start_index: int, count: int) -> bytes:
        lpf = self.meta.labels_per_file
        out = bytearray()
        idx = start_index
        remaining = count
        while remaining > 0:
            fi, within = divmod(idx, lpf)
            take = min(remaining, lpf - within)
            with open(self._file(fi), "rb") as f:
                f.seek(within * LABEL_BYTES)
                chunk = f.read(take * LABEL_BYTES)
            if len(chunk) != take * LABEL_BYTES:
                raise IOError(
                    f"short read at label {idx}: file {fi} truncated")
            out += chunk
            idx += take
            remaining -= take
        return bytes(out)
