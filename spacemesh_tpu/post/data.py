"""On-disk POST data layout: label files + resume metadata.

Mirrors the reference initializer's data directory contract (post-rs writes
``postdata_N.bin`` label files plus a metadata file; resume is driven by the
number of labels already on disk — reference activation/post.go:267-270
"initialization will resume from NumLabelsWritten"). Here metadata is JSON,
written atomically (tmp + rename) after every flushed batch so a killed
init resumes exactly where the bytes stopped.
"""

from __future__ import annotations

import dataclasses
import json
import os
import queue
import threading
import time
from pathlib import Path

from ..ops.scrypt import LABEL_BYTES
from ..utils import metrics, sanitize, tracing

METADATA_FILE = "postdata_metadata.json"


@dataclasses.dataclass
class PostMetadata:
    """Identity + geometry of one smesher's POST data directory."""

    node_id: str               # hex, 32 bytes
    commitment: str            # hex, 32 bytes (commitment = H(node_id, atx))
    scrypt_n: int
    num_units: int
    labels_per_unit: int
    max_file_size: int         # bytes per postdata file
    labels_written: int = 0    # resume cursor
    vrf_nonce: int | None = None       # index of the numerically smallest label
    vrf_nonce_value: str | None = None  # hex of that label (16 bytes)

    @property
    def total_labels(self) -> int:
        return self.num_units * self.labels_per_unit

    @property
    def labels_per_file(self) -> int:
        return self.max_file_size // LABEL_BYTES

    def save(self, data_dir: str | Path) -> None:
        path = Path(data_dir) / METADATA_FILE
        tmp = path.with_suffix(".tmp")
        tmp.write_text(json.dumps(dataclasses.asdict(self), indent=1))
        os.replace(tmp, path)

    @classmethod
    def load(cls, data_dir: str | Path) -> "PostMetadata":
        return cls(**json.loads((Path(data_dir) / METADATA_FILE).read_text()))


class LabelStore:
    """Reads/writes the ``postdata_N.bin`` files for one data directory."""

    def __init__(self, data_dir: str | Path, meta: PostMetadata):
        self.dir = Path(data_dir)
        self.meta = meta
        self.dir.mkdir(parents=True, exist_ok=True)
        self._fd_lock = sanitize.lock("post.data.LabelStore.fds")
        self._read_fds: dict[int, int] = {}

    def _file(self, i: int) -> Path:
        return self.dir / f"postdata_{i}.bin"

    def _read_fd(self, i: int) -> int:
        """Cached O_RDONLY fd for file ``i`` — the prover issues thousands
        of positioned reads per pass and an open() per call is pure syscall
        overhead (and defeats readahead heuristics on some filesystems)."""
        with self._fd_lock:
            fd = self._read_fds.get(i)
            if fd is None:
                fd = os.open(self._file(i), os.O_RDONLY)
                self._read_fds[i] = fd
            return fd

    def close(self) -> None:
        """Drop cached read fds (safe to call repeatedly; reads reopen)."""
        with self._fd_lock:
            fds, self._read_fds = self._read_fds, {}
        for fd in fds.values():
            try:
                os.close(fd)
            except OSError:
                pass

    def write_labels(self, start_index: int, labels: bytes) -> None:
        """Write ``labels`` (concatenated 16B records) at ``start_index``.

        Thread-safe: O_CREAT without O_TRUNC plus positioned pwrite, so
        concurrent writers (the background pool, per-shard stripes) landing
        in the same file never truncate or clobber each other's ranges.
        """
        lpf = self.meta.labels_per_file
        idx = start_index
        off = 0
        while off < len(labels):
            fi, within = divmod(idx, lpf)
            take = min(len(labels) - off, (lpf - within) * LABEL_BYTES)
            fd = os.open(self._file(fi), os.O_CREAT | os.O_WRONLY, 0o644)
            try:
                os.pwrite(fd, labels[off:off + take], within * LABEL_BYTES)
            finally:
                os.close(fd)
            off += take
            idx += take // LABEL_BYTES

    def start_writer(self, threads: int = 2,
                     queue_depth: int = 8) -> "LabelWriter":
        """A background writer pool bound to this store."""
        return LabelWriter(self, threads=threads, queue_depth=queue_depth)

    def start_reader(self, ranges, threads: int = 2,
                     depth: int = 4) -> "LabelReader":
        """A background prefetching reader pool bound to this store."""
        return LabelReader(self, ranges, threads=threads, depth=depth)

    def read_labels(self, start_index: int, count: int) -> bytes:
        lpf = self.meta.labels_per_file
        out = bytearray()
        idx = start_index
        remaining = count
        while remaining > 0:
            fi, within = divmod(idx, lpf)
            take = min(remaining, lpf - within)
            chunk = os.pread(self._read_fd(fi), take * LABEL_BYTES,
                             within * LABEL_BYTES)
            if len(chunk) != take * LABEL_BYTES:
                raise IOError(
                    f"short read at label {idx}: file {fi} truncated")
            out += chunk
            idx += take
            remaining -= take
        metrics.post_store_read_calls.inc()
        metrics.post_store_read_bytes.inc(count * LABEL_BYTES)
        return bytes(out)


class LabelWriter:
    """Bounded-queue background writer pool over one LabelStore.

    The streaming initializer hands fetched label bytes here instead of
    writing inline, so disk IO overlaps accelerator compute and PCIe
    fetches. The bounded queue gives backpressure: when disk falls behind,
    ``submit`` blocks the dispatch loop (a visible stall, counted by the
    caller) instead of buffering unboundedly.

    Durability ordering: ``durable()`` is the label index up to which ALL
    bytes are contiguously on disk (writes may complete out of order across
    pool threads and mesh shard stripes). The initializer never persists a
    metadata cursor beyond this point — that is the crash-consistency
    contract the resume path relies on.
    """

    _STOP = object()

    def __init__(self, store: LabelStore, threads: int = 2,
                 queue_depth: int = 8):
        self.store = store
        self._q: queue.Queue = queue.Queue(maxsize=max(queue_depth, 1))
        self._lock = sanitize.lock("post.data.LabelWriter")
        self._idle = sanitize.condition("post.data.LabelWriter.idle",
                                        self._lock)
        # the durable cursor and its completion map are DECLARED SHARED
        # (SPACEMESH_SANITIZE=race): the dispatch thread, the pool
        # threads and the watchdog all meet here, always under _lock
        self._shared = sanitize.SharedField("post.data.LabelWriter.cursor")
        self._done: dict[int, int] = {}   # completed start -> end
        self._durable = store.meta.labels_written
        self._inflight = 0
        self._error: BaseException | None = None
        self._closed = False
        self.labels_submitted = 0
        self.bytes_written = 0
        self.write_seconds = 0.0
        self._threads = [
            threading.Thread(target=self._worker, daemon=True,
                             name=f"label-writer-{i}")
            for i in range(max(threads, 1))]
        for t in self._threads:
            t.start()

    # -- dispatch side ------------------------------------------------------

    def submit(self, start_index: int, labels: bytes) -> None:
        """Enqueue one write; blocks when the queue is full (backpressure)."""
        self._raise_if_failed()
        if self._closed:
            raise RuntimeError("writer is closed")
        with self._lock:
            self._shared.touch()
            self._inflight += 1
        self.labels_submitted += len(labels) // LABEL_BYTES
        # pool threads are long-lived and cannot inherit the submitter's
        # contextvars; the span parent rides along with the work item
        self._q.put((start_index, labels, tracing.current_id()))

    def durable(self) -> int:
        """Highest label index with every prior label contiguously on disk."""
        with self._lock:
            self._shared.touch(write=False)
            return self._durable

    def pending(self) -> int:
        """Writes submitted but not yet on disk — the stall watchdog's
        activity gate (obs/health.py writer_watchdog): while this is
        non-zero the durable cursor must keep advancing."""
        with self._lock:
            self._shared.touch(write=False)
            return self._inflight

    def queue_depth(self) -> int:
        return self._q.qsize()

    def drain(self) -> None:
        """Block until every submitted write has hit the filesystem."""
        with self._idle:
            self._shared.touch(write=False)
            while self._inflight > 0 and self._error is None:
                self._idle.wait(timeout=0.1)
        self._raise_if_failed()

    def close(self, drain: bool = True) -> None:
        if self._closed:
            return
        try:
            # the error flag is written by pool threads under the lock;
            # an unlocked read here could miss a just-landed failure
            # and drain() a pool that will never go idle (SC007)
            with self._lock:
                failed = self._error is not None
            if drain and not failed:
                self.drain()
        finally:
            # a drain() error must still stop the pool: workers keep
            # consuming the queue even after a write failure, so the STOP
            # sentinels always get through
            self._closed = True
            for _ in self._threads:
                self._q.put(self._STOP)
            for t in self._threads:
                t.join(timeout=10)

    def _raise_if_failed(self) -> None:
        with self._lock:
            error = self._error
        if error is not None:
            raise RuntimeError("background label writer failed") \
                from error

    # -- pool side ----------------------------------------------------------

    def _worker(self) -> None:
        while True:
            item = self._q.get()
            if item is self._STOP:
                return
            start, labels, parent = item
            t0 = time.perf_counter()
            try:
                with tracing.span("init.write",
                                  {"start": start,
                                   "labels": len(labels) // LABEL_BYTES}
                                  if tracing.is_enabled() else None,
                                  parent=parent):
                    self.store.write_labels(start, labels)
            except BaseException as e:  # noqa: BLE001 — surfaced to caller
                with self._idle:
                    self._shared.touch()
                    if self._error is None:
                        self._error = e
                    self._inflight -= 1
                    self._idle.notify_all()
                continue
            count = len(labels) // LABEL_BYTES
            with self._idle:
                self._shared.touch()
                self.write_seconds += time.perf_counter() - t0
                self.bytes_written += len(labels)
                self._done[start] = start + count
                while self._durable in self._done:
                    self._durable = self._done.pop(self._durable)
                self._inflight -= 1
                self._idle.notify_all()


class LabelReader:
    """Bounded read-ahead pool over one LabelStore — the prover-side mirror
    of LabelWriter.

    The streaming prover hands the whole pass plan (an ordered list of
    ``(start_index, count)`` ranges) here; pool threads read ahead while the
    device scans, and ``get()`` yields each range's bytes *in plan order*.
    At most ``depth`` ranges are buffered or being read at once, so a stalled
    consumer (device backpressure) caps reader memory at
    ``depth * batch * LABEL_BYTES`` instead of the whole store.
    """

    def __init__(self, store: LabelStore, ranges, threads: int = 2,
                 depth: int = 4):
        self.store = store
        self.ranges: list[tuple[int, int]] = list(ranges)
        # pool threads can't inherit contextvars; reads parent under the
        # span that planned the pass (the prover's window span)
        self._trace_parent = tracing.current_id()
        self._cond = sanitize.condition("post.data.LabelReader")
        self._shared = sanitize.SharedField("post.data.LabelReader.state")
        self._results: dict[int, bytes] = {}
        self._claim = 0          # next plan slot a worker may take
        self._consume = 0        # next plan slot get() returns
        self._budget = max(depth, 1)  # free read-ahead slots
        self._error: BaseException | None = None
        self._closed = False
        self.read_seconds = 0.0
        self.bytes_read = 0
        self._threads = [
            threading.Thread(target=self._worker, daemon=True,
                             name=f"label-reader-{i}")
            for i in range(max(threads, 1))]
        for t in self._threads:
            t.start()

    def get(self) -> bytes:
        """Bytes of the next range in plan order; blocks until prefetched.

        In-order results buffered before a background failure are still
        delivered; the error surfaces on the first range that is actually
        missing (so an error past an early-exit point cannot abort a prove
        that never needed those bytes)."""
        with self._cond:
            self._shared.touch()
            while (self._consume not in self._results
                   and self._error is None):
                if self._consume >= len(self.ranges):
                    raise IndexError("read plan exhausted")
                self._cond.wait(timeout=0.1)
            if self._consume in self._results:
                data = self._results.pop(self._consume)
                self._consume += 1
                self._budget += 1
                self._cond.notify_all()
                return data
            raise RuntimeError("background label reader failed") \
                from self._error

    def close(self) -> None:
        """Stop the pool; safe mid-plan (early exit drops pending reads)."""
        with self._cond:
            self._shared.touch()
            self._closed = True
            self._cond.notify_all()
        for t in self._threads:
            t.join(timeout=10)

    def _worker(self) -> None:
        while True:
            with self._cond:
                self._shared.touch()
                while (not self._closed and self._error is None
                       and (self._budget <= 0
                            or self._claim >= len(self.ranges))):
                    if self._claim >= len(self.ranges):
                        return  # plan fully claimed
                    self._cond.wait(timeout=0.1)
                if self._closed or self._error is not None:
                    return
                slot = self._claim
                self._claim += 1
                self._budget -= 1
            start, count = self.ranges[slot]
            t0 = time.perf_counter()
            try:
                with tracing.span("prove.read_io",
                                  {"start": start, "count": count}
                                  if tracing.is_enabled() else None,
                                  parent=self._trace_parent):
                    data = self.store.read_labels(start, count)
            except BaseException as e:  # noqa: BLE001 — surfaced via get()
                with self._cond:
                    self._shared.touch()
                    if self._error is None:
                        self._error = e
                    self._cond.notify_all()
                return
            with self._cond:
                self._shared.touch()
                self.read_seconds += time.perf_counter() - t0
                self.bytes_read += len(data)
                self._results[slot] = data
                self._cond.notify_all()
