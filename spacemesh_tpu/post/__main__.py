"""tpu-post-worker CLI: init / prove / verify / benchmark.

The operator surface of the standalone POST worker (SURVEY.md §7 M0
deliverable), mirroring what post-rs ships as separate binaries (the
initializer, the post-service prover, and the profiler — reference
Makefile-libs.Inc fetches them prebuilt; activation/post_supervisor.go:105-127
exposes Providers()/Benchmark()).

Usage:
  python -m spacemesh_tpu.post init --data-dir D --node-id-hex .. \
      --commitment-hex .. --num-units 1 --labels-per-unit 1024 [--scrypt-n N]
  python -m spacemesh_tpu.post prove --data-dir D --challenge-hex ..
  python -m spacemesh_tpu.post verify --data-dir D --proof-file P.json \
      --challenge-hex ..
  python -m spacemesh_tpu.post benchmark [--batch B] [--scrypt-n N]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path


def _hex32(s: str) -> bytes:
    b = bytes.fromhex(s)
    if len(b) != 32:
        raise argparse.ArgumentTypeError("expected 32 bytes of hex")
    return b


def cmd_init(a) -> int:
    from . import initializer

    def progress(done, total):
        print(f"\r{done}/{total} labels ({100 * done / total:.1f}%)",
              end="", file=sys.stderr, flush=True)

    meta, res = initializer.initialize(
        a.data_dir, node_id=a.node_id_hex, commitment=a.commitment_hex,
        num_units=a.num_units, labels_per_unit=a.labels_per_unit,
        scrypt_n=a.scrypt_n, max_file_size=a.max_file_size,
        batch_size=a.batch, progress=progress,
        inflight=a.inflight, writers=a.writers)
    print("", file=sys.stderr)
    out = {
        "labels_written": res.labels_written,
        "vrf_nonce": res.vrf_nonce,
        "labels_per_s": round(res.labels_per_s, 1),
        "elapsed_s": round(res.elapsed_s, 2),
    }
    if a.stage_timings and res.stats is not None:
        out["stages"] = {k: round(v, 3) if isinstance(v, float) else v
                         for k, v in res.stats.as_dict().items()}
    print(json.dumps(out))
    return 0


def cmd_prove(a) -> int:
    from .prover import ProofParams, Prover

    params = ProofParams(k1=a.k1, k2=a.k2, k3=a.k3)
    t0 = time.monotonic()
    prover = Prover(a.data_dir, params, batch_labels=a.batch,
                    pipelined=None if not a.serial else False,
                    window_groups=a.window_groups, inflight=a.inflight,
                    readers=a.readers)
    proof = prover.prove(a.challenge_hex)
    out = proof.to_dict() | {"elapsed_s": round(time.monotonic() - t0, 2)}
    if a.stage_timings and prover.last_stats is not None:
        out["stages"] = {k: round(v, 3) if isinstance(v, float) else v
                         for k, v in prover.last_stats.as_dict().items()}
    if a.out:
        Path(a.out).write_text(json.dumps(proof.to_dict()))
    print(json.dumps(out))
    return 0


def cmd_verify(a) -> int:
    from . import verifier
    from .data import PostMetadata
    from .prover import Proof, ProofParams

    meta = PostMetadata.load(a.data_dir)
    proof = Proof.from_dict(json.loads(Path(a.proof_file).read_text()))
    params = ProofParams(k1=a.k1, k2=a.k2, k3=a.k3)
    ok = verifier.verify(verifier.VerifyItem(
        proof=proof, challenge=a.challenge_hex,
        node_id=bytes.fromhex(meta.node_id),
        commitment=bytes.fromhex(meta.commitment),
        scrypt_n=meta.scrypt_n, total_labels=meta.total_labels), params)
    print(json.dumps({"valid": ok}))
    return 0 if ok else 1


def cmd_benchmark(a) -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..ops import scrypt
    from ..utils import accel

    accel.enable_persistent_cache()
    dev = jax.devices()[0]
    cw = jnp.asarray(scrypt.commitment_to_words(bytes(32)))
    idx = np.arange(a.batch, dtype=np.uint64)
    lo_, hi_ = scrypt.split_indices(idx)
    lo, hi = jnp.asarray(lo_), jnp.asarray(hi_)
    t0 = time.perf_counter()
    scrypt.scrypt_labels_jit(cw, lo, hi, n=a.scrypt_n).block_until_ready()
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    scrypt.scrypt_labels_jit(cw, lo, hi, n=a.scrypt_n).block_until_ready()
    dt = time.perf_counter() - t0
    print(json.dumps({
        "device": str(dev), "batch": a.batch, "scrypt_n": a.scrypt_n,
        "labels_per_s": round(a.batch / dt, 1),
        "compile_s": round(compile_s, 2),
    }))
    return 0


def cmd_serve(a) -> int:
    """Serve every identity under --data-dir to the node.

    Two transports behind the same PostService registry:
    * default: listen on --listen, node dials us (JSON-RPC framing)
    * --node-address: DIAL the node's gRPC PostService and Register each
      identity over a bidirectional stream — the reference topology
      (reference api/grpcserver/post_service.go:91; the Rust post-service
      is spawned with the node's address the same way).
    """
    import asyncio

    from ..utils import accel
    from .prover import ProofParams
    from .remote import WorkerServer, discover_identities

    accel.enable_persistent_cache()

    params = ProofParams(k1=a.k1, k2=a.k2, k3=a.k3,
                         pow_difficulty=bytes.fromhex(a.pow_difficulty))
    service = discover_identities(a.data_dir, params=params)

    async def go_grpc():
        from .grpc_worker import GrpcWorker

        worker = GrpcWorker(service, a.node_address)
        await worker.start()
        print(json.dumps({"event": "Registering",
                          "node_address": a.node_address,
                          "identities": [n.hex() for n in
                                         service.registered()]}),
              flush=True)
        try:
            await asyncio.Event().wait()  # until killed
        finally:
            await worker.stop()

    async def go_listen():
        server = WorkerServer(service, listen=a.listen)
        host, port = await server.start()
        print(json.dumps({"event": "Serving", "host": host, "port": port,
                          "identities": [n.hex() for n in
                                         service.registered()]}),
              flush=True)
        try:
            await asyncio.Event().wait()  # until killed
        finally:
            await server.stop()

    try:
        asyncio.run(go_grpc() if a.node_address else go_listen())
    except KeyboardInterrupt:
        pass
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="spacemesh_tpu.post")
    sub = p.add_subparsers(dest="cmd", required=True)

    pi = sub.add_parser("init", help="fill a POST data directory with labels")
    pi.add_argument("--data-dir", required=True)
    pi.add_argument("--node-id-hex", type=_hex32, required=True)
    pi.add_argument("--commitment-hex", type=_hex32, required=True)
    pi.add_argument("--num-units", type=int, required=True)
    pi.add_argument("--labels-per-unit", type=int, required=True)
    pi.add_argument("--scrypt-n", type=int, default=8192)
    pi.add_argument("--max-file-size", type=int, default=64 * 1024 * 1024)
    pi.add_argument("--batch", type=int, default=1 << 13)
    pi.add_argument("--inflight", type=int, default=None,
                    help="device batches in flight (default: "
                    "SPACEMESH_INFLIGHT or 3)")
    pi.add_argument("--writers", type=int, default=None,
                    help="background disk-writer threads (default: "
                    "SPACEMESH_WRITERS or 2)")
    pi.add_argument("--stage-timings", action="store_true",
                    help="include per-stage pipeline timings in the output")
    pi.set_defaults(fn=cmd_init)

    pp = sub.add_parser("prove", help="generate a proof over the challenge")
    pp.add_argument("--data-dir", required=True)
    pp.add_argument("--challenge-hex", type=_hex32, required=True)
    pp.add_argument("--k1", type=int, default=26)
    pp.add_argument("--k2", type=int, default=37)
    pp.add_argument("--k3", type=int, default=37)
    pp.add_argument("--batch", type=int, default=1 << 14)
    pp.add_argument("--serial", action="store_true",
                    help="use the legacy synchronous scan instead of the "
                    "streaming pipeline (docs/POST_PROVING.md)")
    pp.add_argument("--window-groups", type=int, default=None,
                    help="nonce groups scanned per disk pass (default: "
                    "SPACEMESH_PROVE_WINDOW_GROUPS, or 4 on TPU / 1 on CPU)")
    pp.add_argument("--inflight", type=int, default=None,
                    help="device batches in flight (default: "
                    "SPACEMESH_PROVE_INFLIGHT or 3)")
    pp.add_argument("--readers", type=int, default=None,
                    help="background label-reader threads (default: "
                    "SPACEMESH_PROVE_READERS or 2)")
    pp.add_argument("--stage-timings", action="store_true",
                    help="include per-stage prove pipeline timings")
    pp.add_argument("--out", help="write proof JSON here as well")
    pp.set_defaults(fn=cmd_prove)

    pv = sub.add_parser("verify", help="verify a proof file")
    pv.add_argument("--data-dir", required=True)
    pv.add_argument("--proof-file", required=True)
    pv.add_argument("--challenge-hex", type=_hex32, required=True)
    pv.add_argument("--k1", type=int, default=26)
    pv.add_argument("--k2", type=int, default=37)
    pv.add_argument("--k3", type=int, default=37)
    pv.set_defaults(fn=cmd_verify)

    pb = sub.add_parser("benchmark", help="time the labeler on this device")
    pb.add_argument("--batch", type=int, default=2048)
    pb.add_argument("--scrypt-n", type=int, default=8192)
    pb.set_defaults(fn=cmd_benchmark)

    ps = sub.add_parser("serve", help="serve identities to the node "
                        "(out-of-process worker)")
    ps.add_argument("--data-dir", required=True,
                    help="base dir holding per-identity POST data dirs")
    ps.add_argument("--listen", default="127.0.0.1:0")
    ps.add_argument("--node-address", default=None,
                    help="dial the node's gRPC PostService at host:port "
                    "instead of listening (reference topology)")
    ps.add_argument("--k1", type=int, default=26)
    ps.add_argument("--k2", type=int, default=37)
    ps.add_argument("--k3", type=int, default=37)
    ps.add_argument("--pow-difficulty", default="00ff" + "ff" * 30,
                    help="32-byte hex PoW difficulty")
    ps.set_defaults(fn=cmd_serve)

    a = p.parse_args(argv)
    return a.fn(a)


if __name__ == "__main__":
    sys.exit(main())
