"""Worker-side gRPC client: dial the node, Register, answer requests.

The reference post-service (a separate Rust binary) dials the node's
PostService and keeps a Register stream open per identity (reference
api/grpcserver/post_service.go:91 Register; activation/post_supervisor.go
spawns it with --address=<node grpc>).  This is the TPU worker's
equivalent: one `RegisterSession` per discovered identity, each a
bidirectional stream answering

  MetadataRequest  -> MetadataResponse (identity geometry)
  GenProofRequest  -> GenProofResponse (OK w/o proof while brewing — the
                      node re-asks every queryInterval, post_client.go:107)

Proving runs off the stream — through the multi-tenant runtime
scheduler when one is attached (per-identity job IDs, fair-share across
identities, gang-scheduled windows; runtime/scheduler.py), else in a
plain thread — so the stream stays responsive while a proof is in
flight.  Sessions reconnect with backoff when the node restarts.
"""

from __future__ import annotations

import asyncio
import contextlib

import grpc

from ..api.gen import post_pb2 as ppb
from ..api.rpc import POST_REGISTER, pack_indices
from ..utils import metrics
from .service import PostClient, PostService


class _ProofJob:
    """One in-flight proving task per identity (the reference service
    rejects a second concurrent challenge per identity the same way).

    Tracks the session in ``post_prove_inflight`` — the label-free
    total every dashboard already reads, plus a per-``tenant`` series
    so an operator can see WHICH identities are mid-prove on this
    worker (the node re-asks every queryInterval while a proof brews;
    post_client.go:107).  ``job_id`` is the runtime scheduler's job id
    when the prove was routed through it ("" on the plain-thread
    path)."""

    live = 0
    live_by_tenant: dict[str, int] = {}

    def __init__(self, challenge: bytes, task: asyncio.Task,
                 tenant: str = "-", job_id: str = ""):
        self.challenge = challenge
        self.task = task
        self.tenant = tenant
        self.job_id = job_id
        _ProofJob.live += 1
        by = _ProofJob.live_by_tenant
        by[tenant] = by.get(tenant, 0) + 1
        metrics.post_prove_inflight.set(_ProofJob.live)
        metrics.post_prove_inflight.set(by[tenant], tenant=tenant)
        task.add_done_callback(self._done)

    def _done(self, _task) -> None:
        _ProofJob.live = max(_ProofJob.live - 1, 0)
        by = _ProofJob.live_by_tenant
        by[self.tenant] = max(by.get(self.tenant, 1) - 1, 0)
        metrics.post_prove_inflight.set(_ProofJob.live)
        metrics.post_prove_inflight.set(by[self.tenant],
                                        tenant=self.tenant)

    @staticmethod
    def forget_tenant(tenant: str) -> None:
        """Drop a gone identity's series + tracking entry — a worker
        cycling identities must not grow one dead 0-valued
        post_prove_inflight{tenant=...} series per identity forever
        (the PR 7 stale-series lesson)."""
        _ProofJob.live_by_tenant.pop(tenant, None)
        metrics.post_prove_inflight.remove(tenant=tenant)


class RegisterSession:
    """One identity's Register stream to the node.

    With a runtime ``scheduler`` attached, proofs submit as per-identity
    jobs (``tenant`` = the identity's hex prefix) instead of owning a
    raw thread: many identities' proves then fair-share one device."""

    def __init__(self, node_address: str, node_id: bytes, client: PostClient,
                 reconnect_backoff: float = 1.0, scheduler=None):
        self.node_address = node_address
        self.node_id = node_id
        self.client = client
        self.backoff = reconnect_backoff
        self.scheduler = scheduler
        self.tenant = node_id.hex()[:16]
        self._job: _ProofJob | None = None
        self._stop = asyncio.Event()
        self.connected = asyncio.Event()  # true while a stream is live

    async def run(self) -> None:
        """Dial-register-serve loop; reconnects until stopped."""
        while not self._stop.is_set():
            try:
                await self._serve_once()
            except (grpc.aio.AioRpcError, ConnectionError, OSError):
                pass
            finally:
                self.connected.clear()
            if self._stop.is_set():
                return
            # node down or stream dropped: retry after backoff
            with contextlib.suppress(asyncio.TimeoutError):
                await asyncio.wait_for(self._stop.wait(), self.backoff)

    def stop(self) -> None:
        self._stop.set()

    async def _serve_once(self) -> None:
        async with grpc.aio.insecure_channel(self.node_address) as channel:
            stub = channel.stream_stream(
                POST_REGISTER,
                request_serializer=ppb.ServiceResponse.SerializeToString,
                response_deserializer=ppb.NodeRequest.FromString)
            call = stub()
            self.connected.set()
            try:
                while True:
                    req = await call.read()
                    if req == grpc.aio.EOF:
                        return
                    await call.write(await self._answer(req))
            finally:
                self.connected.clear()
                with contextlib.suppress(Exception):
                    call.cancel()

    async def _answer(self, req: ppb.NodeRequest) -> ppb.ServiceResponse:
        kind = req.WhichOneof("kind")
        if kind == "metadata":
            return ppb.ServiceResponse(
                metadata=ppb.MetadataResponse(meta=self._meta()))
        if kind == "gen_proof":
            return await self._gen_proof(bytes(req.gen_proof.challenge))
        # unknown request kind: the node is newer than us — report error
        return ppb.ServiceResponse(gen_proof=ppb.GenProofResponse(
            status=ppb.GEN_PROOF_STATUS_ERROR))

    @staticmethod
    async def _scheduled(handle) -> tuple:
        """Await a runtime-scheduler prove job from the event loop; the
        result shape matches PostClient.proof's (the metadata half is
        unused by the stream answer)."""
        proof = await asyncio.wrap_future(handle.future)
        return proof, None

    def _meta(self) -> ppb.Metadata:
        info = self.client.info()
        meta = ppb.Metadata(
            node_id=info.node_id, commitment_atx_id=info.commitment,
            num_units=info.num_units, labels_per_unit=info.labels_per_unit)
        if info.vrf_nonce >= 0:
            meta.nonce = info.vrf_nonce
        return meta

    async def _gen_proof(self, challenge: bytes) -> ppb.ServiceResponse:
        job = self._job
        if job is not None and job.challenge != challenge:
            if not job.task.done():
                # one proof at a time per identity (reference post service
                # errors a second concurrent challenge)
                return ppb.ServiceResponse(gen_proof=ppb.GenProofResponse(
                    status=ppb.GEN_PROOF_STATUS_ERROR))
            self._job = job = None
        if job is None:
            job_id = ""
            if self.scheduler is not None:
                handle = self.client.submit_proof(self.scheduler,
                                                  self.tenant, challenge)
                job_id = handle.id
                task = asyncio.ensure_future(self._scheduled(handle))
            else:
                task = asyncio.ensure_future(
                    asyncio.to_thread(self.client.proof, challenge))
            self._job = job = _ProofJob(challenge, task,
                                        tenant=self.tenant, job_id=job_id)
        if not job.task.done():
            # still brewing: OK without proof, node will re-ask
            return ppb.ServiceResponse(gen_proof=ppb.GenProofResponse(
                status=ppb.GEN_PROOF_STATUS_OK))
        self._job = None  # consumed (success or failure)
        try:
            proof, _meta = job.task.result()  # spacecheck: ok=SC002 guarded by the task.done() early-return above — never blocks
        except Exception:
            return ppb.ServiceResponse(gen_proof=ppb.GenProofResponse(
                status=ppb.GEN_PROOF_STATUS_ERROR))
        return ppb.ServiceResponse(gen_proof=ppb.GenProofResponse(
            status=ppb.GEN_PROOF_STATUS_OK,
            proof=ppb.Proof(nonce=proof.nonce,
                            indices=pack_indices(proof.indices),
                            pow=proof.pow_nonce),
            metadata=ppb.ProofMetadata(challenge=challenge,
                                       meta=self._meta())))


class GrpcWorker:
    """All discovered identities, each with its own Register session.

    With ``scheduler`` (a runtime TenantScheduler) the worker is the
    multi-tenant service shape: every identity registers as a tenant
    and its proofs run as fair-share-scheduled jobs on the shared
    device instead of per-identity thread ownership.  The scheduler is
    borrowed, not owned — the embedder closes it; this worker only
    registers/unregisters its identities."""

    def __init__(self, service: PostService, node_address: str,
                 reconnect_backoff: float = 1.0, scheduler=None):
        self.service = service
        self.node_address = node_address
        self.backoff = reconnect_backoff
        self.scheduler = scheduler
        self.sessions: list[RegisterSession] = []
        self._tasks: list[asyncio.Task] = []
        self._tenants: list[str] = []

    async def start(self) -> None:
        for node_id in self.service.registered():
            client = self.service.client(node_id)
            s = RegisterSession(self.node_address, node_id, client,
                                reconnect_backoff=self.backoff,
                                scheduler=self.scheduler)
            if self.scheduler is not None:
                self.scheduler.register_tenant(s.tenant)
                self._tenants.append(s.tenant)
            self.sessions.append(s)
            self._tasks.append(asyncio.ensure_future(s.run()))

    async def wait_connected(self, timeout: float = 30.0) -> None:
        await asyncio.wait_for(
            asyncio.gather(*(s.connected.wait() for s in self.sessions)),
            timeout)

    async def stop(self) -> None:
        try:
            for s in self.sessions:
                s.stop()
            for t in self._tasks:
                t.cancel()
            await asyncio.gather(*self._tasks, return_exceptions=True)
        finally:
            for s in self.sessions:
                _ProofJob.forget_tenant(s.tenant)
            if self.scheduler is not None:
                for tenant in self._tenants:
                    self.scheduler.unregister_tenant(tenant)
                self._tenants.clear()
