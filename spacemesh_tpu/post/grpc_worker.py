"""Worker-side gRPC client: dial the node, Register, answer requests.

The reference post-service (a separate Rust binary) dials the node's
PostService and keeps a Register stream open per identity (reference
api/grpcserver/post_service.go:91 Register; activation/post_supervisor.go
spawns it with --address=<node grpc>).  This is the TPU worker's
equivalent: one `RegisterSession` per discovered identity, each a
bidirectional stream answering

  MetadataRequest  -> MetadataResponse (identity geometry)
  GenProofRequest  -> GenProofResponse (OK w/o proof while brewing — the
                      node re-asks every queryInterval, post_client.go:107)

Proving runs in a thread (scrypt recompute + nonce search); the stream
stays responsive while a proof is in flight.  Sessions reconnect with
backoff when the node restarts.
"""

from __future__ import annotations

import asyncio
import contextlib

import grpc

from ..api.gen import post_pb2 as ppb
from ..api.rpc import POST_REGISTER, pack_indices
from ..utils import metrics
from .service import PostClient, PostService


class _ProofJob:
    """One in-flight proving task per identity (the reference service
    rejects a second concurrent challenge per identity the same way).

    Tracks the session in ``post_prove_inflight`` so an operator can see
    how many identities are mid-prove on this worker (the node re-asks
    every queryInterval while a proof brews; post_client.go:107)."""

    def __init__(self, challenge: bytes, task: asyncio.Task):
        self.challenge = challenge
        self.task = task
        metrics.post_prove_inflight.set(_ProofJob.live + 1)
        _ProofJob.live += 1
        task.add_done_callback(self._done)

    live = 0

    @staticmethod
    def _done(_task) -> None:
        _ProofJob.live = max(_ProofJob.live - 1, 0)
        metrics.post_prove_inflight.set(_ProofJob.live)


class RegisterSession:
    """One identity's Register stream to the node."""

    def __init__(self, node_address: str, node_id: bytes, client: PostClient,
                 reconnect_backoff: float = 1.0):
        self.node_address = node_address
        self.node_id = node_id
        self.client = client
        self.backoff = reconnect_backoff
        self._job: _ProofJob | None = None
        self._stop = asyncio.Event()
        self.connected = asyncio.Event()  # true while a stream is live

    async def run(self) -> None:
        """Dial-register-serve loop; reconnects until stopped."""
        while not self._stop.is_set():
            try:
                await self._serve_once()
            except (grpc.aio.AioRpcError, ConnectionError, OSError):
                pass
            finally:
                self.connected.clear()
            if self._stop.is_set():
                return
            # node down or stream dropped: retry after backoff
            with contextlib.suppress(asyncio.TimeoutError):
                await asyncio.wait_for(self._stop.wait(), self.backoff)

    def stop(self) -> None:
        self._stop.set()

    async def _serve_once(self) -> None:
        async with grpc.aio.insecure_channel(self.node_address) as channel:
            stub = channel.stream_stream(
                POST_REGISTER,
                request_serializer=ppb.ServiceResponse.SerializeToString,
                response_deserializer=ppb.NodeRequest.FromString)
            call = stub()
            self.connected.set()
            try:
                while True:
                    req = await call.read()
                    if req == grpc.aio.EOF:
                        return
                    await call.write(await self._answer(req))
            finally:
                self.connected.clear()
                with contextlib.suppress(Exception):
                    call.cancel()

    async def _answer(self, req: ppb.NodeRequest) -> ppb.ServiceResponse:
        kind = req.WhichOneof("kind")
        if kind == "metadata":
            return ppb.ServiceResponse(
                metadata=ppb.MetadataResponse(meta=self._meta()))
        if kind == "gen_proof":
            return await self._gen_proof(bytes(req.gen_proof.challenge))
        # unknown request kind: the node is newer than us — report error
        return ppb.ServiceResponse(gen_proof=ppb.GenProofResponse(
            status=ppb.GEN_PROOF_STATUS_ERROR))

    def _meta(self) -> ppb.Metadata:
        info = self.client.info()
        meta = ppb.Metadata(
            node_id=info.node_id, commitment_atx_id=info.commitment,
            num_units=info.num_units, labels_per_unit=info.labels_per_unit)
        if info.vrf_nonce >= 0:
            meta.nonce = info.vrf_nonce
        return meta

    async def _gen_proof(self, challenge: bytes) -> ppb.ServiceResponse:
        job = self._job
        if job is not None and job.challenge != challenge:
            if not job.task.done():
                # one proof at a time per identity (reference post service
                # errors a second concurrent challenge)
                return ppb.ServiceResponse(gen_proof=ppb.GenProofResponse(
                    status=ppb.GEN_PROOF_STATUS_ERROR))
            self._job = job = None
        if job is None:
            task = asyncio.ensure_future(
                asyncio.to_thread(self.client.proof, challenge))
            self._job = job = _ProofJob(challenge, task)
        if not job.task.done():
            # still brewing: OK without proof, node will re-ask
            return ppb.ServiceResponse(gen_proof=ppb.GenProofResponse(
                status=ppb.GEN_PROOF_STATUS_OK))
        self._job = None  # consumed (success or failure)
        try:
            proof, _meta = job.task.result()
        except Exception:
            return ppb.ServiceResponse(gen_proof=ppb.GenProofResponse(
                status=ppb.GEN_PROOF_STATUS_ERROR))
        return ppb.ServiceResponse(gen_proof=ppb.GenProofResponse(
            status=ppb.GEN_PROOF_STATUS_OK,
            proof=ppb.Proof(nonce=proof.nonce,
                            indices=pack_indices(proof.indices),
                            pow=proof.pow_nonce),
            metadata=ppb.ProofMetadata(challenge=challenge,
                                       meta=self._meta())))


class GrpcWorker:
    """All discovered identities, each with its own Register session."""

    def __init__(self, service: PostService, node_address: str,
                 reconnect_backoff: float = 1.0):
        self.service = service
        self.node_address = node_address
        self.backoff = reconnect_backoff
        self.sessions: list[RegisterSession] = []
        self._tasks: list[asyncio.Task] = []

    async def start(self) -> None:
        for node_id in self.service.registered():
            client = self.service.client(node_id)
            s = RegisterSession(self.node_address, node_id, client,
                                reconnect_backoff=self.backoff)
            self.sessions.append(s)
            self._tasks.append(asyncio.ensure_future(s.run()))

    async def wait_connected(self, timeout: float = 30.0) -> None:
        await asyncio.wait_for(
            asyncio.gather(*(s.connected.wait() for s in self.sessions)),
            timeout)

    async def stop(self) -> None:
        for s in self.sessions:
            s.stop()
        for t in self._tasks:
            t.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
