"""POST initialization: fill the data directory with scrypt labels.

The PostSetupManager equivalent (reference activation/post.go:185-449 drives
CGo `initialization.Initialize`; here the labeler is the JAX kernel in
ops/scrypt.py). Design:

- the label space [0, total_labels) is processed in device-sized batches;
- dispatch is double-buffered: batch k+1 is enqueued on the accelerator
  before batch k's bytes are fetched to host and written to disk, so disk
  and TPU overlap;
- after every flushed batch the resume metadata is atomically rewritten
  (labels_written cursor + running VRF-nonce minimum), matching the
  reference's NumLabelsWritten resume semantics;
- the VRF nonce is the index of the numerically smallest label seen
  (little-endian u128 compare), tracked on the fly as post-rs does during
  init.

Progress/status mirrors the reference's state machine
(NotStarted/InProgress/Complete — activation/post.go:128-137).
"""

from __future__ import annotations

import dataclasses
import enum
import time
from pathlib import Path
from typing import Callable

import jax.numpy as jnp
import numpy as np

from ..ops import scrypt
from .data import LabelStore, PostMetadata

DEFAULT_BATCH = 1 << 13  # 8192 labels = 8 MiB ROMix scratch per 1k... tuned in bench


class Status(enum.Enum):
    NOT_STARTED = "not_started"
    IN_PROGRESS = "in_progress"
    COMPLETE = "complete"
    STOPPED = "stopped"
    ERROR = "error"


@dataclasses.dataclass
class InitResult:
    labels_written: int
    vrf_nonce: int
    elapsed_s: float
    labels_per_s: float


def _le128_min(labels: np.ndarray) -> tuple[int, tuple[int, int]]:
    """Index + (hi, lo) u64 pair of the numerically smallest LE-u128 label."""
    flat = np.ascontiguousarray(labels)
    lo = flat[:, :8].copy().view("<u8").ravel()
    hi = flat[:, 8:].copy().view("<u8").ravel()
    k = int(np.lexsort((lo, hi))[0])
    return k, (int(hi[k]), int(lo[k]))


class Initializer:
    """Fills (or resumes) one identity's POST data directory."""

    def __init__(self, data_dir: str | Path, meta: PostMetadata,
                 batch_size: int = DEFAULT_BATCH,
                 progress: Callable[[int, int], None] | None = None):
        self.store = LabelStore(data_dir, meta)
        self.meta = meta
        self.batch = batch_size
        self.progress = progress
        self.status = (Status.COMPLETE
                       if meta.labels_written >= meta.total_labels
                       else Status.NOT_STARTED)
        self._stop = False

    def stop(self) -> None:
        self._stop = True

    def run(self) -> InitResult:
        meta = self.meta
        commitment = bytes.fromhex(meta.commitment)
        total = meta.total_labels
        self.status = Status.IN_PROGRESS
        t0 = time.monotonic()
        written0 = meta.labels_written

        self._vrf = meta.vrf_nonce
        self._vrf_key = None
        if meta.vrf_nonce_value is not None:
            v = bytes.fromhex(meta.vrf_nonce_value)
            self._vrf_key = (int.from_bytes(v[8:], "little"),
                             int.from_bytes(v[:8], "little"))

        def batches():
            start = meta.labels_written
            while start < total:
                count = min(self.batch, total - start)
                idx = np.arange(start, start + count, dtype=np.uint64)
                lo, hi = scrypt.split_indices(idx)
                words = scrypt.scrypt_labels_jit(
                    jnp.asarray(scrypt.commitment_to_words(commitment)),
                    jnp.asarray(lo), jnp.asarray(hi), n=meta.scrypt_n)
                yield start, count, words
                start += count

        # double buffer: batch k+1 is already enqueued on the device while
        # batch k is fetched and written to disk
        pending = None
        for nxt in batches():
            if pending is not None:
                self._flush(pending)
            if self._stop:
                self.status = Status.STOPPED
                pending = None
                break
            pending = nxt
        if pending is not None:
            self._flush(pending)

        if meta.labels_written >= total:
            self.status = Status.COMPLETE
        elapsed = time.monotonic() - t0
        done = meta.labels_written - written0
        return InitResult(
            labels_written=meta.labels_written,
            vrf_nonce=self._vrf if self._vrf is not None else -1,
            elapsed_s=elapsed,
            labels_per_s=done / elapsed if elapsed > 0 else 0.0,
        )

    def _flush(self, item) -> None:
        start, count, words = item
        labels = np.frombuffer(scrypt.labels_to_bytes(words), dtype=np.uint8)
        labels = labels.reshape(count, scrypt.LABEL_BYTES)
        k, key = _le128_min(labels)
        if self._vrf_key is None or key < self._vrf_key:
            self._vrf = start + k
            self._vrf_key = key
        self.store.write_labels(start, labels.tobytes())
        self.meta.labels_written = start + count
        self.meta.vrf_nonce = self._vrf
        hi, lo = self._vrf_key
        self.meta.vrf_nonce_value = (
            lo.to_bytes(8, "little") + hi.to_bytes(8, "little")).hex()
        self.meta.save(self.store.dir)
        if self.progress:
            self.progress(self.meta.labels_written, self.meta.total_labels)


def initialize(data_dir: str | Path, *, node_id: bytes, commitment: bytes,
               num_units: int, labels_per_unit: int, scrypt_n: int = 8192,
               max_file_size: int = 64 * 1024 * 1024,
               batch_size: int = DEFAULT_BATCH,
               progress: Callable[[int, int], None] | None = None
               ) -> tuple[PostMetadata, InitResult]:
    """Create-or-resume an init session (the `PostSetupManager.StartSession`
    equivalent). Returns final metadata + timing."""
    dir_ = Path(data_dir)
    if (dir_ / "postdata_metadata.json").exists():
        meta = PostMetadata.load(dir_)
        if (meta.node_id != node_id.hex()
                or meta.commitment != commitment.hex()
                or meta.scrypt_n != scrypt_n
                or meta.labels_per_unit != labels_per_unit
                or meta.num_units != num_units
                or meta.max_file_size != max_file_size):
            raise ValueError(
                "existing POST data directory was initialized with different "
                "parameters; refusing to mix label sets")
    else:
        meta = PostMetadata(
            node_id=node_id.hex(), commitment=commitment.hex(),
            scrypt_n=scrypt_n, num_units=num_units,
            labels_per_unit=labels_per_unit, max_file_size=max_file_size)
    init = Initializer(dir_, meta, batch_size=batch_size, progress=progress)
    res = init.run()
    return meta, res
