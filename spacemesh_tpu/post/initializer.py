"""POST initialization: fill the data directory with scrypt labels.

The PostSetupManager equivalent (reference activation/post.go:185-449 drives
CGo `initialization.Initialize`; here the labeler is the JAX kernel in
ops/scrypt.py). The init loop is a streaming pipeline with three decoupled
stages (docs/POST_PIPELINE.md):

  dispatch  — enqueue up to K label batches on the accelerator, each chained
              to an on-device LE-u128 argmin that folds the batch into a
              donated running-minimum carry (the VRF-nonce scan; no host
              lexsort on the per-batch path);
  fetch     — pop the oldest in-flight batch, copy its bytes to host (this
              is the only per-batch device sync), per-shard when the batch
              was sharded over a device mesh;
  write     — hand the bytes to a bounded-queue background writer pool
              (post/data.py LabelWriter), so disk, PCIe and compute overlap.

The bounded-window dispatch/retire machinery itself lives in the shared
device-job runtime (spacemesh_tpu/runtime/engine.py Pipeline) — this
module only supplies the init-specific dispatch and retire callbacks;
the multi-tenant scheduler (runtime/scheduler.py) serves many
identities' inits through the same engine with cross-tenant lane
packing.

Resume metadata is rewritten on a time/label interval rather than per
batch, with one ordering rule: the persisted ``labels_written`` cursor is
the writer pool's *durable* cursor (contiguous bytes on disk), never the
dispatch frontier. The VRF scan may run ahead of the cursor — that is safe
because labels are deterministic: resume recomputes them and the min-merge
is idempotent.

When more than one device is visible, batches route through
parallel/mesh.py (data-parallel lane sharding) and each device shard's
bytes are striped to the writer pool independently.

Progress/status mirrors the reference's state machine
(NotStarted/InProgress/Complete — activation/post.go:128-137).
"""

from __future__ import annotations

import dataclasses
import enum
import errno
import os
import sys
import time
from pathlib import Path
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import scrypt
from ..runtime import engine
from ..utils import metrics, tracing
from .data import LabelStore, LabelWriter, PostMetadata

DEFAULT_BATCH = 1 << 13  # 8192 labels = 8 MiB ROMix scratch per 1k... tuned in bench
DEFAULT_INFLIGHT = 3     # device batches in flight before the oldest is fetched
DEFAULT_WRITERS = 2      # background writer threads
DEFAULT_WRITER_QUEUE = 8  # pending writes before dispatch backpressure
DEFAULT_META_INTERVAL_S = 5.0
DEFAULT_META_INTERVAL_LABELS = 1 << 20


class Status(enum.Enum):
    NOT_STARTED = "not_started"
    IN_PROGRESS = "in_progress"
    COMPLETE = "complete"
    STOPPED = "stopped"
    ERROR = "error"


@dataclasses.dataclass
class PipelineStats:
    """Host-side per-stage accounting for one run (tools/profiler.py
    --pipeline dumps this; the same numbers feed utils/metrics.py)."""

    batches: int = 0
    shards: int = 0
    dispatch_s: float = 0.0   # host time spent enqueueing device work
    fetch_s: float = 0.0      # blocked on device->host label copies
    write_stall_s: float = 0.0  # blocked on writer-pool backpressure
    write_s: float = 0.0      # filesystem time inside the writer pool
    save_s: float = 0.0       # metadata rewrites
    meta_saves: int = 0

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class InitResult:
    labels_written: int
    vrf_nonce: int
    elapsed_s: float
    labels_per_s: float
    stats: PipelineStats | None = None


class Initializer:
    """Fills (or resumes) one identity's POST data directory."""

    # ``progress(done, total)`` reports the FETCH frontier — labels whose
    # bytes reached the host and were handed to the writer pool. Up to the
    # writer queue may still be in flight to disk; the durable cursor is
    # what metadata persists (docs/POST_PIPELINE.md ordering rule).
    def __init__(self, data_dir: str | Path, meta: PostMetadata,
                 batch_size: int = DEFAULT_BATCH,
                 progress: Callable[[int, int], None] | None = None,
                 inflight: int | None = None,
                 writers: int | None = None,
                 writer_queue: int = DEFAULT_WRITER_QUEUE,
                 meta_interval_s: float = DEFAULT_META_INTERVAL_S,
                 meta_interval_labels: int = DEFAULT_META_INTERVAL_LABELS,
                 mesh="auto",
                 stall_deadline_s: float = 30.0,
                 tenant: str = "-",
                 fs=None,
                 enospc_retry_s: float = 0.5,
                 save_barrier: bool = False):
        self.tenant = tenant
        self.store = LabelStore(data_dir, meta, fs=fs)
        self.enospc_retry_s = enospc_retry_s
        # save_barrier drains the writer pool before every metadata
        # checkpoint: the op stream over the fs layer becomes a pure
        # function of the batch schedule (no writer-thread timing),
        # which is what makes faultfs plans replay-stable — the crash
        # sweep tests and the crash-recovery sim scenario set it; the
        # production default keeps disk/compute overlap through saves
        self.save_barrier = save_barrier
        self.meta = meta
        self.batch = batch_size
        self.progress = progress
        self.inflight = max(int(
            inflight if inflight is not None
            else os.environ.get("SPACEMESH_INFLIGHT", DEFAULT_INFLIGHT)), 1)
        self.writers = max(int(
            writers if writers is not None
            else os.environ.get("SPACEMESH_WRITERS", DEFAULT_WRITERS)), 1)
        self.writer_queue = writer_queue
        self.meta_interval_s = meta_interval_s
        self.meta_interval_labels = meta_interval_labels
        self.stall_deadline_s = stall_deadline_s
        self._fetched = meta.labels_written  # fetch frontier (watchdog)
        self._resume_at = meta.labels_written  # submit-frontier base
        self._mesh_arg = mesh
        self.status = (Status.COMPLETE
                       if meta.labels_written >= meta.total_labels
                       else Status.NOT_STARTED)
        self._stop = False

    def stop(self) -> None:
        """Request stop. The run loop checks this BEFORE dispatching the
        next batch, so stop latency is one fetch+drain, not a full batch
        compute; the durable cursor of already-flushed batches is always
        persisted on the way out."""
        self._stop = True

    # -- mesh routing -------------------------------------------------------

    def _resolve_plan(self, batch_hint: int):
        """-> (mesh | None, autotune.Decision) for this session.

        Real multi-device backends keep the historical behavior (shard
        over the whole mesh). On the CPU fallback the autotuned winner
        decides (ops/autotune.py mesh dimension): the op-dispatch-bound
        label kernel usually wins sharded over the virtual host devices,
        and the race has measured by how much on THIS host — zero
        configuration, SPACEMESH_MESH still forces either way."""
        from ..ops import autotune

        n = self.meta.scrypt_n
        if self._mesh_arg is None:
            return None, autotune.decide(n, batch_hint)
        if self._mesh_arg != "auto":
            mesh = self._mesh_arg if self._mesh_arg.size > 1 else None
            return mesh, autotune.decide(n, batch_hint)
        # ONE definition of the auto routing, shared with post/prover.py
        # (autotune.resolve_auto_mesh: tuned winner on the CPU fallback,
        # whole mesh on real hardware, SPACEMESH_MESH forces either way)
        devs, d = autotune.resolve_auto_mesh(n, batch_hint)
        if devs is None:
            return None, d
        from ..parallel import mesh as pmesh
        return pmesh.data_mesh(devs), d

    # -- the pipeline -------------------------------------------------------

    def run(self) -> InitResult:
        meta = self.meta
        commitment = bytes.fromhex(meta.commitment)
        total = meta.total_labels
        self.status = Status.IN_PROGRESS
        t0 = time.monotonic()
        written0 = meta.labels_written
        stats = PipelineStats()
        cw = scrypt.commitment_to_words(commitment)

        # resolve (and if needed race+persist) the kernel + mesh choice up
        # front so the session logs what it will run with and the first
        # dispatch doesn't absorb the calibration race silently
        # (ops/autotune.py). The decision is taken at the BUCKETED batch —
        # the executable shape every batch of this session (ragged tail
        # included) actually runs at (ops/scrypt.py shape_bucket).
        if total > written0:
            batch_hint = scrypt.shape_bucket(min(self.batch,
                                                 total - written0))
            mesh, decision = self._resolve_plan(batch_hint)
            print(f"romix kernel: impl={decision.impl} "
                  f"chunk={decision.chunk} devices={mesh.size if mesh else 1}"
                  f" (source={decision.source})", file=sys.stderr, flush=True)
            metrics.post_mesh_devices.set(mesh.size if mesh else 1)
        else:  # nothing to do: never pay a race/compile for a no-op resume
            from ..ops import autotune

            mesh, decision = None, autotune.default_decision(
                jax.default_backend(), self.meta.scrypt_n, self.batch)
        self._decision = decision

        # resumed (or fresh) running-minimum carry for the VRF scan
        resumed = None
        if meta.vrf_nonce_value is not None and meta.vrf_nonce is not None:
            v = bytes.fromhex(meta.vrf_nonce_value)
            resumed = (int.from_bytes(v[8:], "little"),
                       int.from_bytes(v[:8], "little"))
        carry_host = scrypt.vrf_carry_init(resumed, meta.vrf_nonce or 0)
        if mesh is not None:
            from ..parallel import mesh as pmesh
            carry = pmesh.replicate(mesh, carry_host)
        else:
            carry = jnp.asarray(carry_host)
        # last snapshot whose batch has been retired; valid for saves even
        # while the donated carry buffer keeps rotating on device
        self._snapshot = carry_host

        writer = self.store.start_writer(
            self.writers, self.writer_queue,
            enospc_retry_s=self.enospc_retry_s)
        self._last_save_t = time.monotonic()
        self._last_save_labels = written0
        # liveness (obs/health.py): the fetch frontier and the writer's
        # flushed/durable cursors must both keep advancing while the
        # session runs — a wedged device or disk flips /readyz instead
        # of hanging a silent init forever. post.store is the DEGRADED
        # probe: ENOSPC parks the pool and flips /readyz until space
        # returns (docs/CRASH_SAFETY.md), without killing the session.
        from ..obs import health as health_mod

        # an ENOSPC hold parks the writer pool and backpressure stalls
        # the fetch frontier — that is post.store's DEGRADED verdict,
        # not an init stall (docs/CRASH_SAFETY.md), and it must not
        # read as one: with a restart hook registered below, a
        # stall verdict would STOP a session that PR 13 promised
        # resumes unaided when space returns
        init_wd = health_mod.Watchdog(
            "post.init", progress=lambda: self._fetched,
            deadline_s=self.stall_deadline_s,
            active=lambda: (self.status == Status.IN_PROGRESS
                            and not writer.degraded()))
        writer_wd = health_mod.writer_watchdog(
            writer, deadline_s=self.stall_deadline_s)
        store_probe = health_mod.store_probe(writer)
        health_mod.HEALTH.register("post.init", init_wd.check)
        health_mod.HEALTH.register("post.writer", writer_wd.check)
        health_mod.HEALTH.register("post.store", store_probe)
        # recovery hooks beside the watchdogs (obs/remediate.py): a
        # stalled-init verdict STOPS the session — init is resumable
        # from the durable cursor, so a clean stop hands the restart to
        # the owning supervisor instead of hanging a wedged pipeline
        # forever (docs/SELF_HEALING.md)
        from ..obs import remediate as remediate_mod

        remediate_mod.ACTIONS.register("post.init", "restart_component",
                                       self.stop)
        remediate_mod.ACTIONS.register("post.writer", "restart_component",
                                       self.stop)
        session = tracing.span("init.run",
                               {"total": total, "resume_at": written0,
                                "batch": self.batch,
                                "devices": mesh.size if mesh else 1,
                                "impl": decision.impl,
                                "tenant": self.tenant}
                               if tracing.is_enabled() else None)
        session.__enter__()

        # the bounded dispatch->retire window is the shared runtime's
        # (runtime/engine.py); this module supplies only the callbacks.
        # The donated VRF carry is loop-carried state: the dispatch
        # callback rotates it through a one-slot cell.
        carry_cell = [carry]

        def batches():
            dispatched = written0
            while dispatched < total:
                count = min(self.batch, total - dispatched)
                yield dispatched, count
                dispatched += count

        def dispatch(item):
            start, count = item
            words, new_carry, snap = self._dispatch(
                mesh, cw, start, count, carry_cell[0])
            carry_cell[0] = new_carry
            metrics.post_pipeline_dispatched.inc()
            return start, count, words, snap

        def retire(ticket):
            self._retire(ticket, writer, stats)
            self._maybe_save(writer, stats)
            return None

        pipe = engine.Pipeline(
            kind="init", tenant=self.tenant, inflight=self.inflight,
            stop=lambda: self._stop, span="init",
            attrs=lambda item: {"start": item[0], "count": item[1]},
            on_inflight=metrics.post_pipeline_inflight.set)
        try:
            pipe.run(batches(), dispatch, retire)
            if pipe.stats.stopped:
                self.status = Status.STOPPED
            tw = time.perf_counter()
            with tracing.span("init.drain_stall"):
                writer.drain()
            stats.write_stall_s += time.perf_counter() - tw
            self._save_meta(writer, stats)
        finally:
            session.__exit__(None, None, None)
            stats.batches = pipe.stats.batches
            stats.dispatch_s = pipe.stats.dispatch_s
            stats.write_s = writer.write_seconds
            writer.close(drain=False)
            health_mod.HEALTH.unregister("post.init", init_wd.check)
            health_mod.HEALTH.unregister("post.writer", writer_wd.check)
            health_mod.HEALTH.unregister("post.store", store_probe)
            remediate_mod.ACTIONS.unregister(
                "post.init", "restart_component", self.stop)
            remediate_mod.ACTIONS.unregister(
                "post.writer", "restart_component", self.stop)
            # clears the degraded gauge only if THIS session's writer
            # set it — an unconditional zero would clobber another
            # session's live ENOSPC signal (the gauge is process-global)
            writer.clear_degraded()
            metrics.post_pipeline_inflight.set(0)
            metrics.post_pipeline_queue_depth.set(0)

        if meta.labels_written >= total:
            self.status = Status.COMPLETE
        elapsed = time.monotonic() - t0
        done = meta.labels_written - written0
        rate = done / elapsed if elapsed > 0 else 0.0
        metrics.post_pipeline_labels_per_sec.set(rate)
        for stage, secs in (("dispatch", stats.dispatch_s),
                            ("fetch", stats.fetch_s),
                            ("write", stats.write_s),
                            ("stall", stats.write_stall_s)):
            metrics.post_pipeline_stage_seconds.inc(secs, stage=stage)
        return InitResult(
            labels_written=meta.labels_written,
            vrf_nonce=meta.vrf_nonce if meta.vrf_nonce is not None else -1,
            elapsed_s=elapsed,
            labels_per_s=rate,
            stats=stats,
        )

    def _dispatch(self, mesh, cw, start: int, count: int, carry):
        """Enqueue one batch + min-scan on device; returns immediately."""
        n = self.meta.scrypt_n
        if mesh is not None:
            from ..parallel import mesh as pmesh
            # pad to the batch's shape bucket (and at least a multiple of
            # the mesh size) by repeating the last index — duplicates
            # cannot perturb the min scan (same value, first-occurrence
            # index wins) and the pad lanes are trimmed before the bytes
            # reach disk. Bucketing on host here; the sharded wrapper
            # skips its own pad (ops/scrypt.py shape_bucket)
            padded = scrypt.shape_bucket(count)
            if padded % mesh.size:
                padded = count + (-count) % mesh.size
            idx = np.arange(start, start + padded, dtype=np.uint64)
            idx[count:] = start + count - 1
            lo, hi = scrypt.split_indices(idx)
            # the raced mesh winner's layout rides along; an untuned mesh
            # (explicit mesh= arg, forced SPACEMESH_MESH with racing off)
            # keeps the pinned plain-XLA dispatch (impl=None)
            impl = self._decision.impl if self._decision.devices > 1 \
                else None
            return pmesh.labels_with_min_sharded(mesh, cw, lo, hi, carry,
                                                 n=n, impl=impl)
        idx = np.arange(start, start + count, dtype=np.uint64)
        lo, hi = scrypt.split_indices(idx)
        return scrypt.scrypt_labels_with_min(
            jnp.asarray(cw), jnp.asarray(lo), jnp.asarray(hi), carry, n=n)

    def _retire(self, item, writer: LabelWriter, stats: PipelineStats) -> None:
        """Fetch the oldest in-flight batch and hand it to the writers."""
        start, count, words, snap = item
        shards = []  # (global start, (4, lanes) ndarray, valid lane count)
        rsp = tracing.span("init.fetch", {"start": start, "count": count}
                           if tracing.is_enabled() else None)
        rsp.__enter__()
        tf = time.perf_counter()
        stall = 0.0
        shard_times: list[tuple[int, float]] = []  # (valid lanes, fetch s)
        try:
            if len(getattr(words.sharding, "device_set", ())) > 1:
                for shard in words.addressable_shards:
                    lane0 = shard.index[1].start or 0
                    if lane0 >= count:
                        continue  # pure padding shard
                    t0 = time.perf_counter()
                    arr = np.asarray(shard.data)
                    valid = min(count - lane0, arr.shape[1])
                    # the FIRST shard's copy blocks until the sharded
                    # program retires, so its time includes compute wait;
                    # later shards are (nearly) pure D2H. Both are what
                    # the operator experiences per shard.
                    shard_times.append((valid, time.perf_counter() - t0))
                    shards.append((start + lane0, arr, valid))
            else:
                shards.append((start, np.asarray(words), count))
            stats.shards += len(shards)
            if len(shard_times) > 1:
                secs = [s for _, s in shard_times]
                hi, lo_ = max(secs), min(secs)
                imbalance = (hi - lo_) / hi if hi > 0 else 0.0
                per_shard = [v / s for v, s in shard_times if s > 0]
                metrics.post_mesh_shard_imbalance.set(imbalance)
                if per_shard:
                    metrics.post_mesh_shard_labels_per_sec.set(
                        sum(per_shard) / len(per_shard))
                rsp.set(shards=len(shard_times),
                        shard_imbalance=round(imbalance, 4))
            for shard_start, arr, valid in shards:
                # byte conversion is host fetch-side work; only the
                # submit() wait is writer backpressure
                data = scrypt.labels_to_bytes(arr)[:valid
                                                   * scrypt.LABEL_BYTES]
                ts = time.perf_counter()
                with tracing.span("init.write_stall"):
                    writer.submit(shard_start, data)
                stall += time.perf_counter() - ts
        finally:
            rsp.__exit__(None, None, None)
        stats.fetch_s += time.perf_counter() - tf - stall
        stats.write_stall_s += stall
        if stall > 0:
            metrics.post_pipeline_stall_seconds.inc(stall)
        metrics.post_pipeline_queue_depth.set(writer.queue_depth())
        metrics.post_pipeline_labels.inc(count)
        self._fetched = start + count
        self._snapshot = snap
        if self.progress:
            self.progress(start + count, self.meta.total_labels)

    # -- metadata durability -------------------------------------------------

    def _maybe_save(self, writer: LabelWriter, stats: PipelineStats) -> None:
        now = time.monotonic()
        # the label trigger fires on the SUBMIT frontier (deterministic
        # per batch schedule), not the flushed cursor (writer-thread
        # timing) — so checkpoint op sequences replay bit-identically
        # under a fault plan
        frontier = self._resume_at + writer.labels_submitted
        if (now - self._last_save_t < self.meta_interval_s
                and frontier - self._last_save_labels
                < self.meta_interval_labels):
            return
        self._save_meta(writer, stats)

    def _save_meta(self, writer: LabelWriter, stats: PipelineStats) -> None:
        """Persist resume metadata. Ordering rule: the cursor is the
        writer's durable (contiguous-FSYNCED) label count — never the
        dispatch or fetch frontier, and never bytes merely handed to
        the OS. ``checkpoint()`` fsyncs the dirty label files first and
        hands back the interval CRC the recovery path verifies on
        reopen (docs/CRASH_SAFETY.md)."""
        meta = self.meta
        t0 = time.perf_counter()
        if self.save_barrier:
            writer.drain()
        # ENOSPC on the checkpoint fsync or the metadata save degrades
        # exactly like an ENOSPC label write: the save path parks (the
        # post.store probe flips, /readyz shows degraded), retries on
        # the writer's interval/kick, and the session survives
        while True:
            try:
                durable, crc = writer.checkpoint()
                break
            except OSError as e:
                if e.errno != errno.ENOSPC or not writer.enospc_wait:
                    raise
                writer.wait_for_space("label-file fsync")
        decoded = scrypt.vrf_carry_decode(self._snapshot)
        meta.labels_written = durable
        prev_end = meta.intervals[-1][0] if meta.intervals else 0
        if durable > prev_end:
            meta.intervals.append([durable, crc])
        if decoded is not None:
            idx, (hi, lo) = decoded
            meta.vrf_nonce = idx
            meta.vrf_nonce_value = (
                lo.to_bytes(8, "little") + hi.to_bytes(8, "little")).hex()
        with tracing.span("init.save_meta", {"durable": durable}
                          if tracing.is_enabled() else None):
            while True:
                try:
                    meta.save(self.store.dir, fs=self.store.fs)
                    break
                except OSError as e:
                    if e.errno != errno.ENOSPC or not writer.enospc_wait:
                        raise
                    writer.wait_for_space("metadata save")
        writer.clear_degraded()
        stats.meta_saves += 1
        stats.save_s += time.perf_counter() - t0
        metrics.post_pipeline_meta_saves.inc()
        self._last_save_t = time.monotonic()
        # record the SAME frontier the trigger compares against: with
        # the durable cursor here, a writer backlog >= the interval
        # would re-trip the label trigger on every retire (a checkpoint
        # storm — fsync + durable metadata write per batch)
        self._last_save_labels = self._resume_at + writer.labels_submitted


def open_or_create_meta(data_dir: Path, *, node_id: bytes,
                        commitment: bytes, num_units: int,
                        labels_per_unit: int, scrypt_n: int = 8192,
                        max_file_size: int = 64 * 1024 * 1024,
                        fs=None) -> PostMetadata:
    """Load (and parameter-check) or create one identity's metadata —
    the create-or-resume gate shared by :func:`initialize` and the
    multi-tenant scheduler's packed init path (runtime/scheduler.py).

    Every reopen runs crash recovery (post/data.py recover_store):
    tail-interval CRC verification, truncation of torn/un-fsynced
    bytes back to the last verified checkpoint, and stray staging-file
    cleanup — so a resumed init always starts from a state the
    durability ledger can vouch for."""
    from .data import recover_store

    dir_ = Path(data_dir)
    if (dir_ / "postdata_metadata.json").exists():
        meta = PostMetadata.load(dir_, fs=fs)
        if (meta.node_id != node_id.hex()
                or meta.commitment != commitment.hex()
                or meta.scrypt_n != scrypt_n
                or meta.labels_per_unit != labels_per_unit
                or meta.num_units != num_units
                or meta.max_file_size != max_file_size):
            raise ValueError(
                "existing POST data directory was initialized with different "
                "parameters; refusing to mix label sets")
        recover_store(dir_, meta, fs=fs)
        return meta
    meta = PostMetadata(
        node_id=node_id.hex(), commitment=commitment.hex(),
        scrypt_n=scrypt_n, num_units=num_units,
        labels_per_unit=labels_per_unit, max_file_size=max_file_size)
    if any(dir_.glob("postdata_*.bin")):
        # a crash before the first metadata save: label bytes with no
        # durable claim behind them — recovery wipes them so the fresh
        # init cannot build on un-fsynced (possibly torn) data
        recover_store(dir_, meta, fs=fs)
    return meta


def initialize(data_dir: str | Path, *, node_id: bytes, commitment: bytes,
               num_units: int, labels_per_unit: int, scrypt_n: int = 8192,
               max_file_size: int = 64 * 1024 * 1024,
               batch_size: int = DEFAULT_BATCH,
               progress: Callable[[int, int], None] | None = None,
               fs=None,
               **pipeline_opts) -> tuple[PostMetadata, InitResult]:
    """Create-or-resume an init session (the `PostSetupManager.StartSession`
    equivalent). Returns final metadata + timing. ``pipeline_opts`` pass
    through to Initializer (inflight, writers, mesh, meta intervals);
    ``fs`` is the injectable I/O layer (post/faultfs.py fault plans)."""
    from ..utils import accel

    accel.enable_persistent_cache()
    dir_ = Path(data_dir)
    meta = open_or_create_meta(
        dir_, node_id=node_id, commitment=commitment, num_units=num_units,
        labels_per_unit=labels_per_unit, scrypt_n=scrypt_n,
        max_file_size=max_file_size, fs=fs)
    init = Initializer(dir_, meta, batch_size=batch_size, progress=progress,
                       fs=fs, **pipeline_opts)
    res = init.run()
    return meta, res
