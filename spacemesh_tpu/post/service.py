"""The PostService seam: node <-> TPU-worker contract.

Mirrors the reference's process boundary (reference
api/grpcserver/post_service.go:24-174: the external post-service registers
per node_id and the node requests proofs/info over the stream;
activation/post_supervisor.go babysits the worker process). Here the
contract is a small Python interface with an in-proc implementation; the
gRPC transport wraps the same interface when the worker runs out-of-process
so the node side is identical either way.
"""

from __future__ import annotations

import dataclasses
import threading
from pathlib import Path

from .data import PostMetadata
from .prover import Proof, ProofParams, Prover


@dataclasses.dataclass
class PostInfo:
    node_id: bytes
    commitment: bytes
    num_units: int
    labels_per_unit: int
    scrypt_n: int
    vrf_nonce: int
    # durable labels on disk; < num_units * labels_per_unit while a
    # streaming init is still in flight (interval metadata saves mean this
    # advances during init, not just at the end)
    labels_written: int = 0


class PostClient:
    """What the node sees for one registered identity (reference
    api/grpcserver/post_client.go:69 `Proof()` / `Info()`).

    ``prove_opts`` pass through to the Prover — the streaming-pipeline
    knobs (pipelined, window_groups, inflight, readers, reader_queue,
    use_pallas, mesh; post/prover.py). Unset knobs fall back to the
    ``SPACEMESH_PROVE_*`` env overrides, then the platform defaults.
    """

    def __init__(self, data_dir: str | Path, params: ProofParams | None = None,
                 batch_labels: int = 1 << 14, **prove_opts):
        self.data_dir = Path(data_dir)
        self.params = params or ProofParams()
        self._batch = batch_labels
        self._prove_opts = prove_opts
        self._lock = threading.Lock()

    def info(self) -> PostInfo:
        meta = PostMetadata.load(self.data_dir)
        return PostInfo(
            node_id=bytes.fromhex(meta.node_id),
            commitment=bytes.fromhex(meta.commitment),
            num_units=meta.num_units,
            labels_per_unit=meta.labels_per_unit,
            scrypt_n=meta.scrypt_n,
            vrf_nonce=meta.vrf_nonce if meta.vrf_nonce is not None else -1,
            labels_written=meta.labels_written,
        )

    def proof(self, challenge: bytes) -> tuple[Proof, PostMetadata]:
        with self._lock:  # one proving session per identity at a time
            prover = Prover(self.data_dir, self.params,
                            batch_labels=self._batch, **self._prove_opts)
            return prover.prove(challenge), prover.meta

    def submit_proof(self, scheduler, tenant: str, challenge: bytes):
        """Route this identity's prove through the multi-tenant runtime
        scheduler instead of owning a thread: returns the JobHandle
        (per-identity job id; fair-share + gang-scheduled windows —
        runtime/scheduler.py). The one-session-per-identity contract is
        the scheduler's per-tenant FIFO here, not the thread lock."""
        return scheduler.submit_prove(tenant, self.data_dir, challenge,
                                      self.params,
                                      batch_labels=self._batch,
                                      **self._prove_opts)


class PostService:
    """Worker-side registry of identities -> clients (the `Register`
    stream equivalent). The node looks clients up by node_id."""

    def __init__(self) -> None:
        self._clients: dict[bytes, PostClient] = {}
        self._lock = threading.Lock()

    def register(self, node_id: bytes, client: PostClient) -> None:
        with self._lock:
            self._clients[node_id] = client

    def deregister(self, node_id: bytes) -> None:
        with self._lock:
            self._clients.pop(node_id, None)

    def client(self, node_id: bytes) -> PostClient | None:
        with self._lock:
            return self._clients.get(node_id)

    def registered(self) -> list[bytes]:
        with self._lock:
            return list(self._clients)
