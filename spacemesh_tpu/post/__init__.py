"""POST worker: initialization (disk fill), proving, verification.

The TPU-native replacement for the reference's post-rs initializer +
post-service prover + CGo verifier (SURVEY.md §2.2-2.3). The node talks to
this worker through the PostService seam (post/service.py), mirroring the
process boundary at reference api/grpcserver/post_service.go.
"""
