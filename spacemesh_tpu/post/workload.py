"""Shared reduced-parameter prove workload — ONE copy of the fixture that
bench.py's `post_prove_labels_per_sec` line and `tools/profiler.py --prove`
both measure (the prove-side analogue of verify/workload.py).

Reduced parameters (k1=64 > k2=16, the regime the repo's e2e tests use) and
a trivial k2pow, so the measured quantity is the label scan, not the pow
search. Node id, commitment, challenge and store geometry are fixed: the
winning nonce — and both provers' full proofs — are deterministic, and
``compare_serial_vs_pipelined`` refuses to report a number unless the two
paths produced bit-identical proofs and the verifier accepts them.
"""

from __future__ import annotations

import hashlib
import time
from pathlib import Path

from . import initializer, verifier
from .prover import Proof, ProofParams, Prover

NODE = hashlib.sha256(b"bench-prove-node").digest()
COMMITMENT = hashlib.sha256(b"bench-prove-commit").digest()
CHALLENGE = hashlib.sha256(b"bench-prove-challenge").digest()
PARAMS = ProofParams(k1=64, k2=16, k3=8, pow_difficulty=bytes([255]) * 32)


def build(data_dir: str | Path, labels: int, batch: int,
          **prover_opts) -> Prover:
    """Init the fixed store under ``data_dir`` and return a Prover over it."""
    initializer.initialize(
        data_dir, node_id=NODE, commitment=COMMITMENT, num_units=1,
        labels_per_unit=labels, scrypt_n=2,
        max_file_size=64 * 1024 * 1024, batch_size=min(batch * 2, 8192))
    return Prover(data_dir, PARAMS, batch_labels=batch, **prover_opts)


def verify_proof(proof: Proof, total_labels: int) -> bool:
    return verifier.verify(verifier.VerifyItem(
        proof=proof, challenge=CHALLENGE, node_id=NODE,
        commitment=COMMITMENT, scrypt_n=2, total_labels=total_labels),
        PARAMS)


def compare_serial_vs_pipelined(prover: Prover, reps: int = 3) -> dict:
    """Best-of-``reps`` seconds for each path over the same store, with the
    proof-identity and verifier gates applied before any number escapes."""
    pow_nonce = prover._pow(CHALLENGE)

    def best_of(fn):
        fn()  # warm: compile + page cache
        t = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            proof = fn()
            t = min(t, time.perf_counter() - t0)
        return proof, t

    try:
        serial_proof, serial_s = best_of(
            lambda: prover._prove_serial(CHALLENGE, pow_nonce))
        pipe_proof, pipe_s = best_of(
            lambda: prover._prove_pipelined(CHALLENGE, pow_nonce))
    finally:
        # the internal entry points skip prove()'s per-session fd cleanup
        prover.store.close()
    if pipe_proof != serial_proof:
        raise RuntimeError(
            f"pipelined proof diverged from serial: "
            f"nonce {pipe_proof.nonce} vs {serial_proof.nonce}")
    if not verify_proof(pipe_proof, prover.meta.total_labels):
        raise RuntimeError("verifier rejected the pipelined proof")
    return {
        "proof": pipe_proof,
        "serial_s": serial_s,
        "pipelined_s": pipe_s,
        "speedup": serial_s / pipe_s if pipe_s > 0 else None,
        "stats": prover.last_stats.as_dict() if prover.last_stats else {},
    }
