"""POST verification: recompute-and-check, batched across proofs.

The PostVerifier equivalent (reference activation/post_verifier.go:122-405
runs a CGo worker pool; validation semantics activation/validation.go:182).
TPU-first design: verification of MANY proofs is one batched label
recompute — all (proof, index) pairs are flattened into a single scrypt
batch, then a single proving-hash batch — instead of a per-proof worker
pool. The K3 spot-check subset (reference validation.go:206 PostSubset)
subsamples each proof's indices deterministically from a verifier seed.

Also verifies the k2pow witness (ops/pow.py replaces RandomX behind the
same seam).
"""

from __future__ import annotations

import dataclasses
import hashlib

import numpy as np

from ..ops import autotune
from ..ops import pow as k2pow
from ..ops import proving, scrypt
from .prover import Proof, ProofParams


@dataclasses.dataclass
class VerifyItem:
    """One proof plus the identity/geometry it claims to cover."""

    proof: Proof
    challenge: bytes
    node_id: bytes
    commitment: bytes
    scrypt_n: int
    total_labels: int


def _k3_subset(item: VerifyItem, k3: int, seed: bytes) -> list[int]:
    """K3-subsample of the proof's indices, keyed by the VERIFIER's seed.

    The seed must be unpredictable to the prover (reference
    validation.go:206 seeds PostSubset by the verifying node's id): a
    prover who can predict the sampled positions could stuff the k2-k3
    unsampled slots with garbage indices.
    """
    idx = item.proof.indices
    if k3 >= len(idx):
        return list(idx)
    h = hashlib.sha256(seed + item.challenge + item.node_id).digest()
    rng = np.random.default_rng(np.frombuffer(h[:8], dtype=np.uint64)[0])
    pick = rng.choice(len(idx), size=k3, replace=False)
    return [idx[i] for i in sorted(pick)]


def verify_many(items: list[VerifyItem], params: ProofParams | None = None,
                seed: bytes | None = None) -> list[bool]:
    """Verify a batch of proofs; returns per-proof validity.

    One scrypt recompute + one proving-hash pass over the union of all
    spot-checked indices — the TPU replacement for the reference's
    worker-pool verify (proofs are lanes, not queue items).

    ``seed`` keys the K3 spot-check subset; by default a fresh random seed
    is drawn per call so provers cannot predict which indices get checked.
    Pass an explicit seed only for reproducible verification (tests,
    deterministic replay).
    """
    import os

    p = params or ProofParams()
    if seed is None:
        seed = os.urandom(32)
    results = [True] * len(items)

    # 1) structural + pow checks (host, cheap)
    flat_idx: list[int] = []
    flat_owner: list[int] = []
    for i, it in enumerate(items):
        pr = it.proof
        if (len(pr.indices) < p.k2
                or len(set(pr.indices)) != len(pr.indices)
                or any(not (0 <= j < it.total_labels) for j in pr.indices)
                or not k2pow.verify(it.challenge, it.node_id,
                                    p.pow_difficulty, pr.pow_nonce)):
            results[i] = False
            continue
        for j in _k3_subset(it, p.k3, seed):
            flat_idx.append(j)
            flat_owner.append(i)
    if not flat_idx:
        return results

    # 2) one batched label recompute + proving-hash pass over ALL proofs.
    # scrypt_n must be uniform per compiled program; group by n (usually 1).
    import jax.numpy as jnp

    owners = np.array(flat_owner)
    idx = np.array(flat_idx, dtype=np.uint64)
    commits = np.stack([
        np.frombuffer(items[o].commitment, dtype=np.uint8) for o in flat_owner])
    chals = np.stack([
        np.frombuffer(items[o].challenge, dtype="<u4").astype(np.uint32)
        for o in flat_owner]).T  # (8, B)
    nonces = np.array([items[o].proof.nonce for o in flat_owner], dtype=np.uint32)
    values = np.empty(len(idx), dtype=np.uint32)
    for n in sorted({items[o].scrypt_n for o in flat_owner}):
        sel = np.array([items[o].scrypt_n == n for o in flat_owner])
        # pad the flat batch to its power-of-two shape bucket (repeat
        # lane 0, trim after): an unbucketed pass would compile one
        # executable per DISTINCT spot-check count — farm batches at
        # varying occupancy turned every new flat count into a fresh
        # XLA compile
        b = int(sel.sum())
        bb = scrypt.shape_bucket(b)
        pad = bb - b

        def _pad(a, axis=0):
            reps = np.take(a, [0], axis=axis)
            return np.concatenate(
                [a, np.repeat(reps, pad, axis=axis)], axis=axis)

        lo, hi = scrypt.split_indices(idx[sel])
        # the shared tuned mesh routing (SPACEMESH_MESH forces; CPU
        # consults the raced winner) — the verify farm's batch recompute
        # is a label batch like any other, so it shards like one
        devs, d = autotune.resolve_auto_mesh(n, bb)
        if devs is not None and len(devs) > 1 and bb % len(devs) == 0:
            from ..parallel import mesh as pmesh

            # mesh callers pre-bucket on host (ops/scrypt.py _tunable):
            # pad BEFORE the label recompute so one sharded executable
            # serves every occupancy at this bucket
            cw8 = commits[sel].view(">u4").astype(np.uint32).T  # (8, b)
            chal_b, nonce_b = chals[:, sel], nonces[sel]
            if pad:
                cw8, chal_b = _pad(cw8, axis=1), _pad(chal_b, axis=1)
                nonce_b, lo, hi = _pad(nonce_b), _pad(lo), _pad(hi)
            mesh = pmesh.data_mesh(devs)
            # sharded label words feed the sharded proving hash directly
            # — no host bytes round-trip between the two programs. The
            # label pipeline emits BE word groups; the proving hash eats
            # LE (what labels_to_bytes->labels_to_words round-trips on
            # the single-device path), so swap on device.
            lw_dev = pmesh.words_to_le(pmesh.scrypt_labels_sharded(
                mesh, cw8, lo, hi, n=n, impl=d.impl))
            lay = pmesh.topology.get().layouts_for(mesh)
            vals = np.asarray(proving.proving_hash_jit(
                lay.put_lane(chal_b), lay.put_batch(nonce_b),
                lay.put_batch(lo), lay.put_batch(hi), lw_dev))[:b]
        else:
            labels = scrypt.scrypt_labels_multi(commits[sel], idx[sel], n=n)
            lw = scrypt.labels_to_words(labels)
            if pad:
                chal_b = _pad(chals[:, sel], axis=1)
                nonce_b = _pad(nonces[sel])
                lo, hi = _pad(lo), _pad(hi)
                lw = _pad(lw, axis=1)
            else:
                chal_b, nonce_b = chals[:, sel], nonces[sel]
            vals = np.asarray(proving.proving_hash_jit(
                jnp.asarray(chal_b), jnp.asarray(nonce_b),
                jnp.asarray(lo), jnp.asarray(hi), jnp.asarray(lw)))[:b]
        values[sel] = vals

    # 3) threshold check per item
    thr = np.array([proving.threshold_u32(p.k1, items[o].total_labels)
                    for o in flat_owner], dtype=np.uint64)
    bad_owners = set(owners[values >= thr].tolist())
    for o in bad_owners:
        results[o] = False
    return results


def verify(item: VerifyItem, params: ProofParams | None = None,
           seed: bytes | None = None) -> bool:
    return verify_many([item], params, seed)[0]
