"""Transaction + account queries (reference sql/transactions, sql/accounts)."""

from __future__ import annotations

from ..core.types import Transaction, TransactionResult
from .db import Database


def add_tx(db: Database, tx: Transaction, principal: bytes | None = None,
           nonce: int | None = None) -> None:
    db.exec(
        "INSERT OR IGNORE INTO transactions (id, raw, principal, nonce)"
        " VALUES (?,?,?,?)", (tx.id, tx.raw, principal, nonce))


def get_tx(db: Database, tx_id: bytes) -> Transaction | None:
    row = db.one("SELECT raw FROM transactions WHERE id=?", (tx_id,))
    return Transaction(raw=row["raw"]) if row else None


def has_tx(db: Database, tx_id: bytes) -> bool:
    return db.one("SELECT 1 FROM transactions WHERE id=?", (tx_id,)) is not None


def set_result(db: Database, tx_id: bytes, layer: int, block: bytes,
               result: TransactionResult) -> None:
    db.exec(
        "UPDATE transactions SET layer=?, block=?, result=? WHERE id=?",
        (layer, block, result.to_bytes(), tx_id))


def result(db: Database, tx_id: bytes) -> TransactionResult | None:
    row = db.one("SELECT result FROM transactions WHERE id=?", (tx_id,))
    return (TransactionResult.from_bytes(row["result"])
            if row and row["result"] else None)


def pending_by_principal(db: Database, principal: bytes) -> list[Transaction]:
    return [Transaction(raw=r["raw"]) for r in
            db.all("SELECT raw FROM transactions WHERE principal=? AND layer"
                   " IS NULL ORDER BY nonce", (principal,))]


# --- accounts (layered snapshots; latest row wins) ------------------------


def update_account(db: Database, address: bytes, layer: int, balance: int,
                   next_nonce: int, template: bytes | None = None,
                   state: bytes | None = None) -> None:
    db.exec(
        "INSERT OR REPLACE INTO accounts (address, layer, balance, next_nonce,"
        " template, state) VALUES (?,?,?,?,?,?)",
        (address, layer, balance, next_nonce, template, state))


def account(db: Database, address: bytes, at_layer: int | None = None):
    q = ("SELECT * FROM accounts WHERE address=?"
         + ("" if at_layer is None else " AND layer<=?")
         + " ORDER BY layer DESC LIMIT 1")
    params = (address,) if at_layer is None else (address, at_layer)
    return db.one(q, params)


def revert_accounts_above(db: Database, layer: int) -> None:
    db.exec("DELETE FROM accounts WHERE layer>?", (layer,))


def all_current_accounts(db: Database):
    return db.all(
        "SELECT a.* FROM accounts a JOIN (SELECT address, MAX(layer) m FROM"
        " accounts GROUP BY address) b ON a.address=b.address AND a.layer=b.m")
