"""Ballot queries (reference sql/ballots)."""

from __future__ import annotations

from ..core.types import Ballot
from .db import Database


def add(db: Database, ballot: Ballot) -> None:
    db.exec(
        "INSERT OR IGNORE INTO ballots (id, layer, atx_id, node_id, data)"
        " VALUES (?,?,?,?,?)",
        (ballot.id, ballot.layer, ballot.atx_id, ballot.node_id,
         ballot.to_bytes()))


def get(db: Database, ballot_id: bytes) -> Ballot | None:
    row = db.one("SELECT data FROM ballots WHERE id=?", (ballot_id,))
    return Ballot.from_bytes(row["data"]) if row else None


def resolve_epoch_data(db: Database, ballot: Ballot,
                       layers_per_epoch: int | None = None):
    """The ballot's own EpochData, else its ref ballot's — accepted only
    from the same owner AND the same ATX (reference
    eligibility_validator.go validateSecondary: a ballot must share its
    atx with its reference ballot; it must not inherit another
    identity's epoch declaration either), and — when the caller passes
    ``layers_per_epoch`` — only from a ref ballot in the SAME epoch.
    The reference rejects a cross-epoch ref explicitly; relying on an
    ATX id resolving for a single target epoch covers this only
    incidentally (ADVICE r5). ONE definition shared by live ingest
    (miner.ingest_ballot) and restart recovery (Tortoise.recover): the
    two paths must derive identical beacons and eligibility counts, or
    a restart changes ballot weights and bad-beacon flags
    (code-review r5)."""
    if ballot.epoch_data is not None:
        return ballot.epoch_data
    ref = get(db, ballot.ref_ballot)
    if ref is not None and ref.epoch_data is not None \
            and ref.node_id == ballot.node_id \
            and ref.atx_id == ballot.atx_id \
            and (layers_per_epoch is None
                 or ref.layer // layers_per_epoch
                 == ballot.layer // layers_per_epoch):
        return ref.epoch_data
    return None


def has(db: Database, ballot_id: bytes) -> bool:
    return db.one("SELECT 1 FROM ballots WHERE id=?", (ballot_id,)) is not None


def in_layer(db: Database, layer: int) -> list[Ballot]:
    return [Ballot.from_bytes(r["data"]) for r in
            db.all("SELECT data FROM ballots WHERE layer=?", (layer,))]


def ids_in_layer(db: Database, layer: int) -> list[bytes]:
    return [r["id"] for r in
            db.all("SELECT id FROM ballots WHERE layer=?", (layer,))]


def by_node_in_layer(db: Database, node_id: bytes, layer: int) -> list[Ballot]:
    return [Ballot.from_bytes(r["data"]) for r in
            db.all("SELECT data FROM ballots WHERE node_id=? AND layer=?",
                   (node_id, layer))]


def refballot(db: Database, node_id: bytes, epoch_start: int, epoch_end: int
              ) -> Ballot | None:
    """First ballot of the node within [epoch_start, epoch_end) that carries
    epoch data (the epoch's reference ballot)."""
    for r in db.all(
            "SELECT data FROM ballots WHERE node_id=? AND layer>=? AND layer<?"
            " ORDER BY layer", (node_id, epoch_start, epoch_end)):
        b = Ballot.from_bytes(r["data"])
        if b.epoch_data is not None:
            return b
    return None


def refballot_by_atx(db: Database, atx_id: bytes, epoch_start: int,
                     epoch_end: int) -> Ballot | None:
    """First epoch-data ballot built on ``atx_id`` in the epoch (reference
    sql/ballots FirstInEpoch, keyed by ATX for active-set recovery)."""
    for r in db.all(
            "SELECT data FROM ballots WHERE atx_id=? AND layer>=? AND layer<?"
            " ORDER BY layer", (atx_id, epoch_start, epoch_end)):
        b = Ballot.from_bytes(r["data"])
        if b.epoch_data is not None:
            return b
    return None
