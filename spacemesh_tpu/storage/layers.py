"""Per-layer bookkeeping (reference sql/layers)."""

from __future__ import annotations

from .db import Database


def set_processed(db: Database, layer: int) -> None:
    db.exec("INSERT INTO layers (id, processed) VALUES (?,1)"
            " ON CONFLICT(id) DO UPDATE SET processed=1", (layer,))


def processed(db: Database) -> int:
    row = db.one("SELECT MAX(id) m FROM layers WHERE processed=1")
    return row["m"] if row and row["m"] is not None else -1


def set_applied(db: Database, layer: int, block_id: bytes,
                state_hash: bytes) -> None:
    db.exec(
        "INSERT INTO layers (id, applied_block, state_hash) VALUES (?,?,?)"
        " ON CONFLICT(id) DO UPDATE SET applied_block=excluded.applied_block,"
        " state_hash=excluded.state_hash", (layer, block_id, state_hash))


def applied_block(db: Database, layer: int) -> bytes | None:
    row = db.one("SELECT applied_block FROM layers WHERE id=?", (layer,))
    return row["applied_block"] if row else None


def state_hash(db: Database, layer: int) -> bytes | None:
    row = db.one("SELECT state_hash FROM layers WHERE id=?", (layer,))
    return row["state_hash"] if row else None


def last_applied(db: Database) -> int:
    row = db.one("SELECT MAX(id) m FROM layers WHERE applied_block IS NOT NULL")
    return row["m"] if row and row["m"] is not None else -1


def set_aggregated_hash(db: Database, layer: int, h: bytes) -> None:
    db.exec(
        "INSERT INTO layers (id, aggregated_hash) VALUES (?,?)"
        " ON CONFLICT(id) DO UPDATE SET aggregated_hash=excluded.aggregated_hash",
        (layer, h))


def aggregated_hash(db: Database, layer: int) -> bytes | None:
    row = db.one("SELECT aggregated_hash FROM layers WHERE id=?", (layer,))
    return row["aggregated_hash"] if row else None
