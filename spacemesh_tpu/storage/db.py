"""SQLite wrapper: connections, transactions, versioned migrations.

Reference parity (reference sql/database.go:244 Open, :562 Database
interface; migrations sql/migrations.go with versioned .sql files; schema
drift check sql/schema.go): migrations are ordered Python-side DDL lists,
the applied version lives in ``PRAGMA user_version``, and opening verifies
the schema version matches the code. In-memory databases (``:memory:``)
give every test real persistence semantics — the reference's
statesql.InMemory pattern (SURVEY.md §4.2).

sqlite3 is used in autocommit mode with explicit BEGIN IMMEDIATE
transactions; WAL journaling for file databases.
"""

from __future__ import annotations

import contextlib
import queue
import sqlite3
import threading
import time
from pathlib import Path


class Database:
    """One writer sqlite handle plus an optional read-only pool.

    The control plane is asyncio/single-threaded per subsystem; the lock
    makes cross-thread use (post worker callbacks, API server) safe.

    ``read_pool`` (reference sql/database.go: a pooled connection set so
    API reads don't serialize behind the writer) opens that many extra
    read-only connections for file databases in WAL mode — WAL readers
    see a consistent snapshot and never block the writer or each other.
    ``one``/``all`` borrow from the pool except when the CALLING thread
    holds an open transaction (its uncommitted writes are only visible
    on the writer handle). In-memory databases cannot pool (each sqlite
    connection to ":memory:" is a distinct database) and keep the
    single-handle behavior.

    Every query records its latency in the global metrics registry
    (reference sql/metrics.go) under ``sql_<name>_query_seconds``.
    """

    def __init__(self, path: str | Path, migrations: list[str],
                 name: str = "db", read_pool: int = 0):
        self.path = str(path)
        self.name = name
        self._conn = sqlite3.connect(
            self.path, isolation_level=None, check_same_thread=False)
        self._conn.row_factory = sqlite3.Row
        self._lock = threading.RLock()
        self._tx_owner: int | None = None
        if self.path != ":memory:":
            # incremental auto-vacuum: maybe_vacuum reclaims free pages
            # in bounded chunks instead of a full-database VACUUM that
            # would hold the writer lock for minutes on a mainnet-shape
            # db (code-review r5). MUST precede journal_mode=WAL — the
            # WAL switch initializes page 1, after which the pragma is a
            # silent no-op. Pre-existing dbs without it never reclaim
            # (retrofitting needs a full offline VACUUM).
            self._conn.execute("PRAGMA auto_vacuum=INCREMENTAL")
            self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute("PRAGMA foreign_keys=ON")
        from ..utils import metrics as _metrics
        # per-role instrument names ("state"/"local"): a bounded set the
        # registry get-or-creates with IDENTICAL buckets on every
        # construction (bucket drift raises since PR 7)
        # spacecheck: ok=SC005 bounded per-db-role names, identical buckets on re-create
        self._latency = _metrics.REGISTRY.histogram(
            f"sql_{name}_query_seconds",
            f"{name} db query latency",
            buckets=(0.0005, 0.005, 0.05, 0.5, 5.0, float("inf")))
        # spacecheck: ok=SC005 bounded per-db-role names, get-or-create by design
        self._queries = _metrics.REGISTRY.counter(
            f"sql_{name}_queries", f"{name} db queries executed")
        self._readers: queue.SimpleQueue | None = None
        self._pool_closed = False
        self._migrate(migrations)
        if read_pool > 0 and self.path != ":memory:":
            self._readers = queue.SimpleQueue()
            for _ in range(read_pool):
                rc = sqlite3.connect(self.path, isolation_level=None,
                                     check_same_thread=False)
                rc.row_factory = sqlite3.Row
                rc.execute("PRAGMA query_only=ON")
                self._readers.put(rc)

    def _migrate(self, migrations: list) -> None:
        # NOTE: executescript() implicitly commits any open transaction, so
        # migrations run outside tx(); each script is itself atomic enough
        # (DDL) and user_version advances only after a script completes.
        # A migration may also be a Python callable(conn) — data rewrites
        # (blob re-encoding) that SQL can't express (the reference's coded
        # migrations, sql/migrations.go).
        with self._lock:
            version = self._conn.execute("PRAGMA user_version").fetchone()[0]
            if version > len(migrations):
                raise RuntimeError(
                    f"{self.name}: database schema version {version} is newer "
                    f"than this build supports ({len(migrations)})")
            for i in range(version, len(migrations)):
                if callable(migrations[i]):
                    # data rewrites must be atomic WITH the version bump:
                    # autocommit would persist a half-rewritten state on
                    # a crash, and a rerun over partial output can
                    # mis-detect what it is repairing (code-review r5 on
                    # 0005's boundary scan)
                    self._conn.execute("BEGIN IMMEDIATE")
                    try:
                        migrations[i](self._conn)
                        self._conn.execute(f"PRAGMA user_version={i + 1}")
                    except BaseException:
                        self._conn.execute("ROLLBACK")
                        raise
                    self._conn.execute("COMMIT")
                else:
                    self._conn.executescript(migrations[i])
                    self._conn.execute(f"PRAGMA user_version={i + 1}")

    @contextlib.contextmanager
    def tx(self):
        """BEGIN IMMEDIATE transaction; commits on success, rolls back on
        error. Reentrant (nested use joins the outer transaction)."""
        with self._lock:
            if self._conn.in_transaction:
                yield self._conn
                return
            self._conn.execute("BEGIN IMMEDIATE")
            self._tx_owner = threading.get_ident()
            try:
                yield self._conn
            except BaseException:
                self._conn.execute("ROLLBACK")
                raise
            else:
                self._conn.execute("COMMIT")
            finally:
                self._tx_owner = None

    @contextlib.contextmanager
    def _timed(self):
        start = time.perf_counter()
        try:
            yield
        finally:
            self._latency.observe(time.perf_counter() - start)
            self._queries.inc()

    @contextlib.contextmanager
    def _read_conn(self):
        """A connection for a read: a pooled read-only handle when one
        exists and the calling thread is not inside tx() (uncommitted
        writes are only visible on the writer handle)."""
        if self._readers is None \
                or self._tx_owner == threading.get_ident():
            with self._lock:
                yield self._conn
            return
        if self._pool_closed:
            # close() drained the pool — blocking on get() here would
            # hang the caller forever; fail the way sqlite3 does
            raise sqlite3.ProgrammingError(
                f"{self.name}: cannot read from a closed database")
        rc = self._readers.get()
        try:
            yield rc
        finally:
            if self._pool_closed:
                rc.close()
            else:
                self._readers.put(rc)

    def exec(self, sql: str, params=()) -> sqlite3.Cursor:
        with self._timed(), self._lock:
            return self._conn.execute(sql, params)

    def one(self, sql: str, params=()):
        with self._timed(), self._read_conn() as conn:
            return conn.execute(sql, params).fetchone()

    def all(self, sql: str, params=()):
        with self._timed(), self._read_conn() as conn:
            return conn.execute(sql, params).fetchall()

    def close(self) -> None:
        # the queue object stays — a reader borrowed by another thread
        # returns through _read_conn's finally, which checks this flag
        # and closes it instead of re-pooling (code-review r5: nulling
        # the queue raced the in-flight return)
        self._pool_closed = True
        if self._readers is not None:
            while True:
                try:
                    self._readers.get_nowait().close()
                except queue.Empty:
                    break
        with self._lock:
            self._conn.close()

    def vacuum(self) -> None:
        with self._lock:
            self._conn.execute("VACUUM")

    def maybe_vacuum(self, min_free_fraction: float = 0.2,
                     max_pages: int = 512) -> bool:
        """Reclaim free pages when the freelist says it is worth it
        (reference sql/vacuum.go: scheduled maintenance, not per-write).
        Uses ``PRAGMA incremental_vacuum`` bounded to ``max_pages`` per
        call so the writer lock is held for a bounded slice, never a
        full-database rewrite; the pruner's next tick continues the
        reclaim. Returns True if pages were reclaimed. Falls back to a
        full VACUUM only where incremental mode is unavailable
        (pre-existing dbs created without auto_vacuum)."""
        with self._lock:
            pages = self._conn.execute("PRAGMA page_count").fetchone()[0]
            free = self._conn.execute("PRAGMA freelist_count").fetchone()[0]
            if pages == 0 or free / pages < min_free_fraction:
                return False
            mode = self._conn.execute("PRAGMA auto_vacuum").fetchone()[0]
            if mode != 2:
                # a full VACUUM here would hold the writer lock for the
                # whole database rewrite — exactly the stall this method
                # exists to avoid. Databases created before incremental
                # mode keep their freelist; the operator can run
                # vacuum() offline (code-review r5).
                return False
            # streaming pragma: each cursor step frees one page — the
            # cursor must be drained or only a single page is reclaimed
            self._conn.execute(
                f"PRAGMA incremental_vacuum({max_pages})").fetchall()
            return True


# --- state database (replicated consensus data) ---------------------------

STATE_MIGRATIONS = [
    # 0001: core mesh entities
    """
    CREATE TABLE atxs (
        id BLOB PRIMARY KEY,
        node_id BLOB NOT NULL,
        publish_epoch INT NOT NULL,
        num_units INT NOT NULL,
        tick_height INT NOT NULL DEFAULT 0,
        vrf_nonce INT NOT NULL DEFAULT 0,
        coinbase BLOB,
        received INT NOT NULL DEFAULT 0,
        data BLOB NOT NULL
    );
    CREATE INDEX atxs_by_epoch ON atxs (publish_epoch);
    CREATE INDEX atxs_by_node ON atxs (node_id, publish_epoch);

    CREATE TABLE ballots (
        id BLOB PRIMARY KEY,
        layer INT NOT NULL,
        atx_id BLOB NOT NULL,
        node_id BLOB NOT NULL,
        data BLOB NOT NULL
    );
    CREATE INDEX ballots_by_layer ON ballots (layer);
    CREATE INDEX ballots_by_node_layer ON ballots (node_id, layer);

    CREATE TABLE blocks (
        id BLOB PRIMARY KEY,
        layer INT NOT NULL,
        validity INT NOT NULL DEFAULT 0,  -- 0 undecided, 1 valid, -1 invalid
        data BLOB NOT NULL
    );
    CREATE INDEX blocks_by_layer ON blocks (layer);

    CREATE TABLE layers (
        id INT PRIMARY KEY,
        processed INT NOT NULL DEFAULT 0,
        applied_block BLOB,
        state_hash BLOB,
        aggregated_hash BLOB
    );

    CREATE TABLE certificates (
        layer INT NOT NULL,
        block_id BLOB NOT NULL,
        cert BLOB,
        valid INT NOT NULL DEFAULT 1,
        PRIMARY KEY (layer, block_id)
    );

    CREATE TABLE beacons (
        epoch INT PRIMARY KEY,
        beacon BLOB NOT NULL
    );

    CREATE TABLE identities (
        node_id BLOB PRIMARY KEY,
        proof BLOB,
        received INT NOT NULL DEFAULT 0,
        marriage_atx BLOB
    );

    CREATE TABLE transactions (
        id BLOB PRIMARY KEY,
        raw BLOB NOT NULL,
        principal BLOB,
        nonce INT,
        layer INT,
        block BLOB,
        result BLOB
    );
    CREATE INDEX txs_by_principal ON transactions (principal, nonce);

    CREATE TABLE accounts (
        address BLOB NOT NULL,
        layer INT NOT NULL,
        balance INT NOT NULL DEFAULT 0,
        next_nonce INT NOT NULL DEFAULT 0,
        template BLOB,
        state BLOB,
        PRIMARY KEY (address, layer)
    );

    CREATE TABLE rewards (
        coinbase BLOB NOT NULL,
        layer INT NOT NULL,
        total_reward INT NOT NULL,
        layer_reward INT NOT NULL,
        PRIMARY KEY (coinbase, layer)
    );

    CREATE TABLE poet_proofs (
        ref BLOB PRIMARY KEY,
        poet_id BLOB NOT NULL,
        round_id TEXT NOT NULL,
        ticks INT NOT NULL,
        data BLOB NOT NULL
    );

    CREATE TABLE active_sets (
        id BLOB PRIMARY KEY,
        epoch INT NOT NULL,
        data BLOB NOT NULL
    );
    """,
    # 0002: beacon provenance — protocol-decided beacons are final, fallback/
    # synced ones may be superseded by a later majority (ADVICE r1: a single
    # peer must not poison a late joiner's beacon permanently). Existing rows
    # default to FALLBACK(1): pre-migration rows may have been adopted from a
    # single peer; protocol-decided values are network-identical, so leaving
    # them supersedable is harmless.
    """
    ALTER TABLE beacons ADD COLUMN source INT NOT NULL DEFAULT 1;
    """,
    # 0003: ATX wire version — v2 (merged/multi-identity) rows store the
    # shared envelope blob once per covered identity under synthetic ids
    """
    ALTER TABLE atxs ADD COLUMN version INT NOT NULL DEFAULT 1;
    """,
]


def _migrate_0004_reward_atx(conn) -> None:
    """Reward gained a leading atx_id field (reference AnyReward carries
    the ATXID; needed for active-set-from-first-block recovery). Re-encode
    every stored block blob from the 2-field layout; unknown provenance
    gets the zero ATX id. Block ids are content hashes, so the id column
    is rewritten too and dependent tables (layers.applied_block,
    certificates.block_id) follow."""
    import io

    from ..core import codec as _codec
    from ..core import types as _types

    legacy_reward = _codec.Codec(
        enc=None,
        dec=lambda r: (_types.ADDRESS.dec(r), _types.u64.dec(r)))
    legacy_block = _codec.Codec(
        enc=None,
        dec=lambda r: {
            "layer": _types.u32.dec(r),
            "tick_height": _types.u64.dec(r),
            "rewards": _codec.vec(legacy_reward, 1 << 12).dec(r),
            "tx_ids": _codec.vec(_types.HASH32, 1 << 16).dec(r),
        })
    rows = conn.execute("SELECT id, data FROM blocks").fetchall()
    for row in rows:
        old_id, data = row[0], row[1]
        try:
            reader = io.BytesIO(data)
            raw = legacy_block.dec(reader)
            if reader.read(1):
                continue  # trailing bytes: not the legacy layout
        except Exception:
            continue  # already new-format (fresh db mid-transition)
        block = _types.Block(
            layer=raw["layer"], tick_height=raw["tick_height"],
            rewards=[_types.Reward(atx_id=bytes(32), coinbase=cb, weight=w)
                     for cb, w in raw["rewards"]],
            tx_ids=raw["tx_ids"])
        conn.execute("UPDATE blocks SET id=?, data=? WHERE id=?",
                     (block.id, block.to_bytes(), old_id))
        conn.execute("UPDATE layers SET applied_block=?"
                     " WHERE applied_block=?", (block.id, old_id))
        conn.execute("UPDATE certificates SET block_id=? WHERE block_id=?",
                     (block.id, old_id))


STATE_MIGRATIONS.append(_migrate_0004_reward_atx)


def _migrate_0005_rewrite_fixups(conn) -> None:
    """The 0004 block-id rewrite invalidated derived data it did not fix
    (ADVICE r4) — and 0004 itself cannot be amended (databases already at
    user_version 4 would never re-run it), so the repair is a separate
    migration that DETECTS whether a rewrite ever happened: it recomputes
    the chained aggregated layer hashes agg(L) = H(agg(L-1) || applied)
    (mesh.py _aggregate) and compares with the stored chain. A mismatch
    can only mean the stored chain predates the id rewrite, in which
    case:
      - the chain is replaced with the recomputed one (fork-finder
        comparisons against freshly syncing peers must match);
      - hare certificates are dropped — their blobs embed the old block
        id under a signature that cannot be re-issued;
      - the top layer is recorded as a boundary mark; Tortoise.recover
        replays ballots strictly after it (their signed vote lists name
        pre-rewrite ids that would all resolve as against). Persisted
        per-block validity verdicts cover the fenced-off layers."""
    from ..core.hashing import sum256

    conn.execute("CREATE TABLE IF NOT EXISTS migration_marks ("
                 " key TEXT PRIMARY KEY, value INT NOT NULL)")
    rows = conn.execute(
        "SELECT id, applied_block, aggregated_hash FROM layers"
        " WHERE aggregated_hash IS NOT NULL ORDER BY id").fetchall()
    # The rewrite point is localizable with the STEP relation over stored
    # values: stored_agg(L) == H(stored_agg(L-1) || applied(L)) holds for
    # layers chained after the id rewrite and fails for layers whose
    # applied_block was rewritten under them (0004 changed the column but
    # not the hash). A node that kept running on the v4 build for weeks
    # has thousands of perfectly valid post-rewrite layers — fencing and
    # cert-dropping must stop at the true boundary, not the top
    # (code-review r5). Residual: trailing EMPTY pre-rewrite layers are
    # step-consistent (their input bytes(32) never changed), so a ballot
    # in one of those few layers may still be replayed; its unresolved
    # supports default to against within an already-fenced window.
    boundary = -1
    stored = {lr[0]: lr[2] for lr in rows}
    for lr in rows:
        layer, applied = lr[0], lr[1] or bytes(32)
        prev = stored.get(layer - 1, bytes(32))
        if sum256(prev, applied) != lr[2]:
            boundary = layer
    if boundary < 0:
        return
    # full-chain recompute from genesis: post-boundary layers are
    # step-consistent but chain over a pre-rewrite PREFIX, so their
    # absolute values still differ from what a freshly syncing peer
    # computes over the rewritten ids
    agg: dict[int, bytes] = {}
    for lr in rows:
        layer, applied = lr[0], lr[1] or bytes(32)
        agg[layer] = sum256(agg.get(layer - 1, bytes(32)), applied)
        conn.execute("UPDATE layers SET aggregated_hash=? WHERE id=?",
                     (agg[layer], layer))
    conn.execute("DELETE FROM certificates WHERE layer<=?", (boundary,))
    conn.execute("INSERT OR REPLACE INTO migration_marks VALUES"
                 " ('block_id_rewrite_boundary', ?)", (boundary,))


STATE_MIGRATIONS.append(_migrate_0005_rewrite_fixups)

# --- local database (node-private progress) -------------------------------

LOCAL_MIGRATIONS = [
    """
    CREATE TABLE nipost_state (
        node_id BLOB PRIMARY KEY,
        phase INT NOT NULL DEFAULT 0,
        challenge BLOB,
        poet_ref BLOB,
        nipost BLOB,
        updated INT NOT NULL DEFAULT 0
    );

    CREATE TABLE poet_registrations (
        node_id BLOB NOT NULL,
        poet_id BLOB NOT NULL,
        round_id TEXT NOT NULL,
        challenge BLOB NOT NULL,
        round_end INT NOT NULL,
        PRIMARY KEY (node_id, poet_id, round_id)
    );

    CREATE TABLE initial_post (
        node_id BLOB PRIMARY KEY,
        post BLOB NOT NULL,
        commitment_atx BLOB NOT NULL
    );

    CREATE TABLE atx_sync_state (
        epoch INT PRIMARY KEY,
        downloaded INT NOT NULL DEFAULT 0,
        total INT NOT NULL DEFAULT 0
    );

    CREATE TABLE prepared_activeset (
        kind INT NOT NULL,
        epoch INT NOT NULL,
        id BLOB NOT NULL,
        weight INT NOT NULL,
        data BLOB NOT NULL,
        PRIMARY KEY (kind, epoch)
    );
    """,
]


def open_state(path: str | Path = ":memory:",
               read_pool: int = 0) -> Database:
    """The replicated consensus database (reference sql/statesql)."""
    return Database(path, STATE_MIGRATIONS, name="state",
                    read_pool=read_pool)


def open_local(path: str | Path = ":memory:") -> Database:
    """The node-private database (reference sql/localsql)."""
    return Database(path, LOCAL_MIGRATIONS, name="local")
