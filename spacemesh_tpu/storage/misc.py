"""Small per-entity query modules bundled: beacons, certificates,
identities (malfeasance), rewards, poet proofs, active sets
(reference sql/beacons, sql/certificates, sql/identities, sql/rewards,
sql/poets, sql/activesets)."""

from __future__ import annotations

from ..core.types import Certificate, MalfeasanceProof, PoetProof
from .db import Database


# --- beacons ---------------------------------------------------------------


BEACON_PROTOCOL = 0  # decided by running the beacon protocol (final)
BEACON_FALLBACK = 1  # adopted from sync/bootstrap/checkpoint (supersedable)
BEACON_GUESS = 2     # OUR OWN timeout-guess (an early get() fell back to
                     # the local bootstrap derivation before the protocol
                     # ran) — supersedable by anything, and the ONLY
                     # source run_epoch may overwrite by running the
                     # protocol: a network-adopted FALLBACK value can be
                     # bit-identical to the local derivation, so
                     # provenance must be recorded, not inferred
                     # (code-review r3)


def set_beacon(db: Database, epoch: int, beacon: bytes,
               source: int = BEACON_PROTOCOL) -> None:
    db.exec(
        "INSERT OR REPLACE INTO beacons (epoch, beacon, source) VALUES (?,?,?)",
        (epoch, beacon, source))


def get_beacon(db: Database, epoch: int) -> bytes | None:
    row = db.one("SELECT beacon FROM beacons WHERE epoch=?", (epoch,))
    return row["beacon"] if row else None


def beacon_source(db: Database, epoch: int) -> int | None:
    row = db.one("SELECT source FROM beacons WHERE epoch=?", (epoch,))
    return row["source"] if row else None


# --- migration marks -------------------------------------------------------


def migration_boundary(db: Database) -> int:
    """Highest layer whose signed artifacts (ballot vote lists, hare
    certificates) predate the 0004 block-id rewrite; -1 when the database
    never held legacy-format blocks. Tortoise.recover replays ballots only
    strictly after this layer (their support votes name pre-rewrite ids
    that no longer resolve; persisted per-block validity covers the rest).
    """
    import sqlite3
    try:
        row = db.one("SELECT value FROM migration_marks"
                     " WHERE key='block_id_rewrite_boundary'")
    except sqlite3.OperationalError:
        return -1  # db migrated before the mark table existed
    return row["value"] if row else -1


# --- certificates ----------------------------------------------------------


def add_certificate(db: Database, layer: int, cert: Certificate) -> None:
    db.exec(
        "INSERT OR REPLACE INTO certificates (layer, block_id, cert, valid)"
        " VALUES (?,?,?,1)", (layer, cert.block_id, cert.to_bytes()))


def certificate(db: Database, layer: int) -> Certificate | None:
    row = db.one(
        "SELECT cert FROM certificates WHERE layer=? AND valid=1", (layer,))
    return Certificate.from_bytes(row["cert"]) if row and row["cert"] else None


def certified_block(db: Database, layer: int) -> bytes | None:
    row = db.one(
        "SELECT block_id FROM certificates WHERE layer=? AND valid=1", (layer,))
    return row["block_id"] if row else None


# --- identities (malfeasance) ---------------------------------------------


def set_malicious(db: Database, node_id: bytes, proof: MalfeasanceProof,
                  received: int = 0) -> None:
    # identities rows also carry marriages: upsert, first proof wins
    db.exec(
        "INSERT INTO identities (node_id, proof, received) VALUES (?,?,?)"
        " ON CONFLICT(node_id) DO UPDATE SET"
        " proof=COALESCE(identities.proof, excluded.proof)",
        (node_id, proof.to_bytes(), received))


def is_malicious(db: Database, node_id: bytes) -> bool:
    row = db.one("SELECT proof FROM identities WHERE node_id=?", (node_id,))
    return row is not None and row["proof"] is not None


def malfeasance_proof(db: Database, node_id: bytes) -> MalfeasanceProof | None:
    row = db.one("SELECT proof FROM identities WHERE node_id=?", (node_id,))
    return MalfeasanceProof.from_bytes(row["proof"]) if row and row["proof"] else None


def all_malicious(db: Database) -> list[bytes]:
    return [r["node_id"] for r in
            db.all("SELECT node_id FROM identities WHERE proof IS NOT NULL")]


# --- marriages (equivocation sets; reference sql/marriage) -----------------


def set_marriage(db: Database, node_id: bytes, marriage_atx: bytes) -> None:
    db.exec(
        "INSERT INTO identities (node_id, marriage_atx) VALUES (?,?)"
        " ON CONFLICT(node_id) DO UPDATE SET"
        " marriage_atx=COALESCE(identities.marriage_atx,"
        " excluded.marriage_atx)", (node_id, marriage_atx))


def marriage_of(db: Database, node_id: bytes) -> bytes | None:
    row = db.one("SELECT marriage_atx FROM identities WHERE node_id=?",
                 (node_id,))
    return row["marriage_atx"] if row else None


def married_set(db: Database, marriage_atx: bytes) -> list[bytes]:
    return [r["node_id"] for r in
            db.all("SELECT node_id FROM identities WHERE marriage_atx=?",
                   (marriage_atx,))]


# --- rewards ---------------------------------------------------------------


def add_reward(db: Database, coinbase: bytes, layer: int, total: int,
               layer_reward: int) -> None:
    db.exec(
        "INSERT OR REPLACE INTO rewards (coinbase, layer, total_reward,"
        " layer_reward) VALUES (?,?,?,?)", (coinbase, layer, total, layer_reward))


def rewards_for(db: Database, coinbase: bytes) -> list[tuple[int, int]]:
    return [(r["layer"], r["total_reward"]) for r in
            db.all("SELECT layer, total_reward FROM rewards WHERE coinbase=?"
                   " ORDER BY layer", (coinbase,))]


def list_rewards(db: Database, *, limit: int, offset: int = 0,
                 coinbase: bytes | None = None,
                 start_layer: int = 0) -> list:
    """Paginated reward listing (reference v2alpha1 RewardService.List)."""
    where, args = ["layer >= ?"], [start_layer]
    if coinbase is not None:
        where.append("coinbase=?")
        args.append(coinbase)
    return db.all(
        "SELECT coinbase, layer, total_reward, layer_reward FROM rewards"
        f" WHERE {' AND '.join(where)} ORDER BY layer, coinbase"
        " LIMIT ? OFFSET ?", (*args, limit, offset))


# --- poet proofs -----------------------------------------------------------


def add_poet_proof(db: Database, proof: PoetProof) -> None:
    db.exec(
        "INSERT OR IGNORE INTO poet_proofs (ref, poet_id, round_id, ticks,"
        " data) VALUES (?,?,?,?,?)",
        (proof.id, proof.poet_id, proof.round_id, proof.ticks,
         proof.to_bytes()))


def poet_proof(db: Database, ref: bytes) -> PoetProof | None:
    row = db.one("SELECT data FROM poet_proofs WHERE ref=?", (ref,))
    return PoetProof.from_bytes(row["data"]) if row else None


def poet_proof_for_round(db: Database, poet_id: bytes, round_id: str
                         ) -> PoetProof | None:
    row = db.one(
        "SELECT data FROM poet_proofs WHERE poet_id=? AND round_id=?",
        (poet_id, round_id))
    return PoetProof.from_bytes(row["data"]) if row else None


# --- active sets -----------------------------------------------------------


def add_active_set(db: Database, set_id: bytes, epoch: int,
                   atx_ids: list[bytes]) -> None:
    db.exec("INSERT OR IGNORE INTO active_sets (id, epoch, data) VALUES (?,?,?)",
            (set_id, epoch, b"".join(atx_ids)))


def active_set(db: Database, set_id: bytes) -> list[bytes] | None:
    row = db.one("SELECT data FROM active_sets WHERE id=?", (set_id,))
    if row is None:
        return None
    data = row["data"]
    return [data[i:i + 32] for i in range(0, len(data), 32)]
