"""Block queries (reference sql/blocks)."""

from __future__ import annotations

from ..core.types import Block
from .db import Database

UNDECIDED, VALID, INVALID = 0, 1, -1


def add(db: Database, block: Block) -> None:
    db.exec("INSERT OR IGNORE INTO blocks (id, layer, data) VALUES (?,?,?)",
            (block.id, block.layer, block.to_bytes()))


def get(db: Database, block_id: bytes) -> Block | None:
    row = db.one("SELECT data FROM blocks WHERE id=?", (block_id,))
    return Block.from_bytes(row["data"]) if row else None


def has(db: Database, block_id: bytes) -> bool:
    return db.one("SELECT 1 FROM blocks WHERE id=?", (block_id,)) is not None


def in_layer(db: Database, layer: int) -> list[Block]:
    return [Block.from_bytes(r["data"]) for r in
            db.all("SELECT data FROM blocks WHERE layer=?", (layer,))]


def ids_in_layer(db: Database, layer: int) -> list[bytes]:
    return [r["id"] for r in
            db.all("SELECT id FROM blocks WHERE layer=? ORDER BY id", (layer,))]


def set_valid(db: Database, block_id: bytes) -> None:
    db.exec("UPDATE blocks SET validity=? WHERE id=?", (VALID, block_id))


def set_invalid(db: Database, block_id: bytes) -> None:
    db.exec("UPDATE blocks SET validity=? WHERE id=?", (INVALID, block_id))


def validity(db: Database, block_id: bytes) -> int | None:
    row = db.one("SELECT validity FROM blocks WHERE id=?", (block_id,))
    return row["validity"] if row else None


def contextually_valid(db: Database, layer: int) -> list[bytes]:
    return [r["id"] for r in
            db.all("SELECT id FROM blocks WHERE layer=? AND validity=?"
                   " ORDER BY id", (layer, VALID))]
