"""In-RAM ATX cache for hot consensus paths (reference atxsdata/data.go:
per-epoch maps of ATX weight/height/nonce/malicious, fed on ATX ingestion,
evicted per epoch; used by tortoise, eligibility oracle, and the miner
without touching SQLite)."""

from __future__ import annotations

import dataclasses
import threading


@dataclasses.dataclass
class AtxInfo:
    node_id: bytes
    weight: int           # num_units * tick_count
    base_height: int
    height: int
    num_units: int
    vrf_nonce: int
    vrf_public_key: bytes = b""
    malicious: bool = False


class AtxCache:
    def __init__(self) -> None:
        self._epochs: dict[int, dict[bytes, AtxInfo]] = {}
        self._malicious: set[bytes] = set()
        self._lock = threading.RLock()

    def add(self, target_epoch: int, atx_id: bytes, info: AtxInfo) -> None:
        with self._lock:
            info.malicious = info.malicious or info.node_id in self._malicious
            self._epochs.setdefault(target_epoch, {})[atx_id] = info

    def get(self, target_epoch: int, atx_id: bytes) -> AtxInfo | None:
        with self._lock:
            return self._epochs.get(target_epoch, {}).get(atx_id)

    def iter_epoch(self, target_epoch: int):
        with self._lock:
            return list(self._epochs.get(target_epoch, {}).items())

    def epoch_weight(self, target_epoch: int) -> int:
        with self._lock:
            return sum(i.weight for i in
                       self._epochs.get(target_epoch, {}).values()
                       if not i.malicious)

    def epoch_count(self, target_epoch: int) -> int:
        """Number of non-malicious ATXs targeting the epoch."""
        with self._lock:
            return sum(1 for i in self._epochs.get(target_epoch, {}).values()
                       if not i.malicious)

    def weight_for_set(self, target_epoch: int, atx_ids: list[bytes]) -> int:
        with self._lock:
            e = self._epochs.get(target_epoch, {})
            return sum(e[a].weight for a in atx_ids if a in e)

    def set_malicious(self, node_id: bytes) -> None:
        with self._lock:
            self._malicious.add(node_id)
            for epoch in self._epochs.values():
                for info in epoch.values():
                    if info.node_id == node_id:
                        info.malicious = True

    def is_malicious(self, node_id: bytes) -> bool:
        with self._lock:
            return node_id in self._malicious

    def evict(self, before_epoch: int) -> None:
        with self._lock:
            for e in [e for e in self._epochs if e < before_epoch]:
                del self._epochs[e]
