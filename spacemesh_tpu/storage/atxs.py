"""ATX queries (reference sql/atxs). V2 (merged) ATXs store the shared
envelope blob once per covered identity under per-identity synthetic ids;
readers return a uniform per-identity `AtxView`."""

from __future__ import annotations

import dataclasses

from ..core.types import EMPTY32, ActivationTx, ActivationTxV2
from .db import Database


@dataclasses.dataclass
class AtxView:
    """Per-identity view over a v1 or v2 ATX row — the fields every
    consumer (cache warmup, builder chaining, double-publish checks)
    needs, version-independent."""

    id: bytes
    node_id: bytes
    publish_epoch: int
    prev_atx: bytes
    num_units: int
    vrf_nonce: int
    vrf_public_key: bytes
    version: int

    def target_epoch(self) -> int:
        return self.publish_epoch + 1


def _view(row) -> AtxView | None:
    version = row["version"] if "version" in row.keys() else 1
    if version == 1:
        atx = ActivationTx.from_bytes(row["data"])
        return AtxView(id=atx.id, node_id=atx.node_id,
                       publish_epoch=atx.publish_epoch,
                       prev_atx=atx.prev_atx, num_units=atx.num_units,
                       vrf_nonce=atx.vrf_nonce,
                       vrf_public_key=atx.vrf_public_key, version=1)
    atx2 = ActivationTxV2.from_bytes(row["data"])
    for sp in atx2.subposts:
        if sp.node_id == row["node_id"]:
            return AtxView(id=atx2.identity_atx_id(sp.node_id),
                           node_id=sp.node_id,
                           publish_epoch=atx2.publish_epoch,
                           prev_atx=sp.prev_atx, num_units=sp.num_units,
                           vrf_nonce=sp.vrf_nonce,
                           vrf_public_key=sp.node_id, version=2)
    return None


def add(db: Database, atx: ActivationTx, *, tick_height: int = 0,
        received: int = 0) -> None:
    db.exec(
        "INSERT OR IGNORE INTO atxs (id, node_id, publish_epoch, num_units,"
        " tick_height, vrf_nonce, coinbase, received, data, version)"
        " VALUES (?,?,?,?,?,?,?,?,?,1)",
        (atx.id, atx.node_id, atx.publish_epoch, atx.num_units, tick_height,
         atx.vrf_nonce, atx.coinbase, received, atx.to_bytes()))


def add_v2(db: Database, atx2: ActivationTxV2, *, tick_heights: dict,
           received: int = 0) -> None:
    """One row per covered identity, all sharing the envelope blob."""
    blob = atx2.to_bytes()
    for sp in atx2.subposts:
        db.exec(
            "INSERT OR IGNORE INTO atxs (id, node_id, publish_epoch,"
            " num_units, tick_height, vrf_nonce, coinbase, received, data,"
            " version) VALUES (?,?,?,?,?,?,?,?,?,2)",
            (atx2.identity_atx_id(sp.node_id), sp.node_id,
             atx2.publish_epoch, sp.num_units,
             tick_heights.get(sp.node_id, 0), sp.vrf_nonce, atx2.coinbase,
             received, blob))


def get(db: Database, atx_id: bytes) -> ActivationTx | None:
    row = db.one("SELECT data FROM atxs WHERE id=? AND version=1",
                 (atx_id,))
    return ActivationTx.from_bytes(row["data"]) if row else None


def get_blob(db: Database, atx_id: bytes) -> bytes | None:
    """Raw wire blob under the id (v1 ATX bytes or v2 envelope)."""
    row = db.one("SELECT data FROM atxs WHERE id=?", (atx_id,))
    return row["data"] if row else None


def view(db: Database, atx_id: bytes) -> AtxView | None:
    row = db.one("SELECT node_id, data, version FROM atxs WHERE id=?",
                 (atx_id,))
    return _view(row) if row else None


def has(db: Database, atx_id: bytes) -> bool:
    return db.one("SELECT 1 FROM atxs WHERE id=?", (atx_id,)) is not None


def list_rows(db: Database, *, limit: int, offset: int = 0,
              epoch: int | None = None, smesher: bytes | None = None,
              coinbase: bytes | None = None) -> list:
    """Paginated ATX listing (reference v2alpha1 ActivationService.List:
    sql builder ops over epoch/smesher/coinbase, LIMIT capped by the
    service)."""
    where, args = [], []
    if epoch is not None:
        where.append("publish_epoch=?")
        args.append(epoch)
    if smesher is not None:
        where.append("node_id=?")
        args.append(smesher)
    if coinbase is not None:
        where.append("coinbase=?")
        args.append(coinbase)
    clause = (" WHERE " + " AND ".join(where)) if where else ""
    return db.all(
        f"SELECT * FROM atxs{clause} ORDER BY publish_epoch, id"
        " LIMIT ? OFFSET ?", (*args, limit, offset))


def count(db: Database, *, epoch: int | None = None) -> int:
    if epoch is None:
        row = db.one("SELECT COUNT(*) AS n FROM atxs", ())
    else:
        row = db.one("SELECT COUNT(*) AS n FROM atxs WHERE publish_epoch=?",
                     (epoch,))
    return row["n"] if row else 0


def tick_height(db: Database, atx_id: bytes) -> int | None:
    row = db.one("SELECT tick_height FROM atxs WHERE id=?", (atx_id,))
    return row["tick_height"] if row else None


def by_node_in_epoch(db: Database, node_id: bytes, epoch: int
                     ) -> AtxView | None:
    row = db.one(
        "SELECT node_id, data, version FROM atxs WHERE node_id=?"
        " AND publish_epoch=?", (node_id, epoch))
    return _view(row) if row else None


def latest_by_node(db: Database, node_id: bytes) -> AtxView | None:
    row = db.one(
        "SELECT node_id, data, version FROM atxs WHERE node_id=?"
        " ORDER BY publish_epoch DESC LIMIT 1", (node_id,))
    return _view(row) if row else None


def ids_in_epoch(db: Database, epoch: int) -> list[bytes]:
    return [r["id"] for r in
            db.all("SELECT id FROM atxs WHERE publish_epoch=?", (epoch,))]


def all_in_epoch(db: Database, epoch: int) -> list[AtxView]:
    return [v for r in
            db.all("SELECT node_id, data, version FROM atxs"
                   " WHERE publish_epoch=?", (epoch,))
            if (v := _view(r)) is not None]


def all_rows(db: Database):
    """(id, tick_height, prev tick lookup support) for cache warmup."""
    return db.all("SELECT id, node_id, publish_epoch, num_units,"
                  " tick_height, data, version FROM atxs"
                  " ORDER BY publish_epoch")


def count_in_epoch(db: Database, epoch: int) -> int:
    return db.one("SELECT COUNT(*) c FROM atxs WHERE publish_epoch=?",
                  (epoch,))["c"]


def coinbase_of(db: Database, atx_id: bytes) -> bytes | None:
    """Reward coinbase for any ATX version (the column is populated for
    both v1 rows and v2 per-identity rows)."""
    row = db.one("SELECT coinbase FROM atxs WHERE id=?", (atx_id,))
    return row["coinbase"] if row else None


def rows_for_grading(db: Database, publish_epoch: int):
    """(id, received, proof_received) for ATXs published in the epoch,
    joined with any malfeasance-proof receipt time (reference sql/atxs
    IterateForGrading)."""
    return db.all(
        "SELECT a.id id, a.received received,"
        " (SELECT i.received FROM identities i"
        "   WHERE i.node_id=a.node_id AND i.proof IS NOT NULL)"
        " proof_received"
        " FROM atxs a WHERE a.publish_epoch=?", (publish_epoch,))
