"""ATX queries (reference sql/atxs)."""

from __future__ import annotations

from ..core.types import ActivationTx
from .db import Database


def add(db: Database, atx: ActivationTx, *, tick_height: int = 0,
        received: int = 0) -> None:
    db.exec(
        "INSERT OR IGNORE INTO atxs (id, node_id, publish_epoch, num_units,"
        " tick_height, vrf_nonce, coinbase, received, data)"
        " VALUES (?,?,?,?,?,?,?,?,?)",
        (atx.id, atx.node_id, atx.publish_epoch, atx.num_units, tick_height,
         atx.vrf_nonce, atx.coinbase, received, atx.to_bytes()))


def get(db: Database, atx_id: bytes) -> ActivationTx | None:
    row = db.one("SELECT data FROM atxs WHERE id=?", (atx_id,))
    return ActivationTx.from_bytes(row["data"]) if row else None


def has(db: Database, atx_id: bytes) -> bool:
    return db.one("SELECT 1 FROM atxs WHERE id=?", (atx_id,)) is not None


def tick_height(db: Database, atx_id: bytes) -> int | None:
    row = db.one("SELECT tick_height FROM atxs WHERE id=?", (atx_id,))
    return row["tick_height"] if row else None


def by_node_in_epoch(db: Database, node_id: bytes, epoch: int
                     ) -> ActivationTx | None:
    row = db.one(
        "SELECT data FROM atxs WHERE node_id=? AND publish_epoch=?",
        (node_id, epoch))
    return ActivationTx.from_bytes(row["data"]) if row else None


def latest_by_node(db: Database, node_id: bytes) -> ActivationTx | None:
    row = db.one(
        "SELECT data FROM atxs WHERE node_id=? ORDER BY publish_epoch DESC"
        " LIMIT 1", (node_id,))
    return ActivationTx.from_bytes(row["data"]) if row else None


def ids_in_epoch(db: Database, epoch: int) -> list[bytes]:
    return [r["id"] for r in
            db.all("SELECT id FROM atxs WHERE publish_epoch=?", (epoch,))]


def all_in_epoch(db: Database, epoch: int) -> list[ActivationTx]:
    return [ActivationTx.from_bytes(r["data"]) for r in
            db.all("SELECT data FROM atxs WHERE publish_epoch=?", (epoch,))]


def all_rows(db: Database):
    """(id, tick_height, prev tick lookup support) for cache warmup."""
    return db.all("SELECT id, node_id, publish_epoch, num_units, tick_height,"
                  " data FROM atxs ORDER BY publish_epoch")


def count_in_epoch(db: Database, epoch: int) -> int:
    return db.one("SELECT COUNT(*) c FROM atxs WHERE publish_epoch=?",
                  (epoch,))["c"]
