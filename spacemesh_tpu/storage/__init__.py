"""Persistence: SQLite state/local databases + in-RAM caches.

Mirrors the reference sql/ layer (reference sql/database.go, two databases:
``state.db`` for consensus data replicated across the network and
``local.db`` for node-private progress — sql/statesql, sql/localsql), with
per-entity query modules (reference sql/atxs, sql/ballots, ...) and the
lock-free in-RAM ATX cache used by hot paths (reference atxsdata/data.go).
"""

from .db import Database, open_local, open_state  # noqa: F401
