"""Mixed verification workloads: one builder for bench.py, the
profiler's --verify-farm view, and tests/test_verify_farm.py.

A workload is a list of farm requests (signatures, VRF proofs, POST
proofs, poet memberships, k2pow witnesses) with a controlled
invalid/malformed fraction,
plus the inline oracle that verifies each request exactly the way the
pre-farm handlers did — the parity target the farm must match
bit-for-bit (ISSUE 2 acceptance).
"""

from __future__ import annotations

import dataclasses
import hashlib
import random

from ..core.signing import Domain, EdSigner, EdVerifier, VrfVerifier
from ..post import verifier as post_verifier
from ..post.prover import Proof as PostProof, ProofParams, Prover
from .farm import (
    MembershipRequest,
    PostRequest,
    PowRequest,
    SigRequest,
    VrfRequest,
)

# tiny-but-real POST geometry (profiler.verify_benchmark uses the same):
# scrypt N=2 keeps the label recompute sub-second on CPU while running
# the full batched verify path
POST_PARAMS = ProofParams(k1=64, k2=16, k3=8,
                          pow_difficulty=bytes([32]) + bytes([255]) * 31)
POST_SCRYPT_N = 2
POST_LABELS = 512
POST_UNITS = 2


@dataclasses.dataclass
class Workload:
    requests: list
    ed: EdVerifier
    vrf: VrfVerifier
    post_params: ProofParams
    post_seed: bytes  # fixed K3 seed: serial and farm must sample alike

    def inline_verify(self, req) -> bool:
        """The pre-farm serial path: one inline verifier call per item."""
        if isinstance(req, SigRequest):
            return self.ed.verify(req.domain, req.public_key, req.msg,
                                  req.signature)
        if isinstance(req, VrfRequest):
            return self.vrf.verify(req.public_key, req.alpha, req.proof)
        if isinstance(req, MembershipRequest):
            from ..consensus.poet import verify_membership

            return verify_membership(req.member, req.proof, req.root,
                                     req.leaf_count)
        if isinstance(req, PostRequest):
            return post_verifier.verify(req.item, self.post_params,
                                        seed=self.post_seed)
        if isinstance(req, PowRequest):
            from ..ops import pow as k2pow

            return k2pow.verify(req.challenge, req.node_id,
                                req.difficulty, req.nonce)
        raise TypeError(f"unknown request {type(req).__name__}")

    def inline_all(self) -> list[bool]:
        return [self.inline_verify(r) for r in self.requests]


def _corrupt(data: bytes, pos: int) -> bytes:
    return data[:pos] + bytes([data[pos] ^ 0x5A]) + data[pos + 1:]


def build(post_dir: str, *, sigs: int = 64, vrfs: int = 8, posts: int = 16,
          memberships: int = 8, pows: int = 0, post_challenges: int = 4,
          invalid_frac: float = 0.125, rng_seed: int = 7) -> Workload:
    """Build a deterministic mixed workload.

    ``post_dir`` must be an empty (or reusable) directory: a tiny real
    POST unit is initialized there once and proofs are generated against
    ``post_challenges`` distinct challenges; ``posts`` requests replicate
    them (replicated proofs are farm dedup fodder — exactly the gossip
    re-delivery pattern). Roughly ``invalid_frac`` of every kind is made
    invalid, including structurally malformed items (wrong-length keys,
    out-of-range POST indices), which must reject on both paths.
    """
    from ..post import initializer

    rng = random.Random(rng_seed)
    every = max(int(round(1 / invalid_frac)), 2) if invalid_frac > 0 else 0

    def bad(i: int) -> bool:
        return bool(every) and i % every == 0

    ed = EdVerifier()
    vrf = VrfVerifier()
    requests: list = []

    # --- ed25519 signatures ------------------------------------------
    signers = [EdSigner(seed=hashlib.sha256(
        b"wl-signer" + k.to_bytes(4, "little")).digest()) for k in range(4)]
    for i in range(sigs):
        s = signers[i % len(signers)]
        msg = b"workload-msg-" + i.to_bytes(4, "little")
        sig = s.sign(Domain.BALLOT, msg)
        if bad(i):
            mode = i % 3
            if mode == 0:
                sig = _corrupt(sig, rng.randrange(len(sig)))
            elif mode == 1:
                sig = sig[:17]  # malformed: wrong length
            else:
                msg = msg + b"!"  # signature over different bytes
        requests.append(SigRequest(int(Domain.BALLOT), s.public_key, msg,
                                   sig))

    # --- VRF proofs ---------------------------------------------------
    vrf_signers = [s.vrf_signer() for s in signers[:2]]
    for i in range(vrfs):
        vs = vrf_signers[i % len(vrf_signers)]
        alpha = b"workload-alpha-" + i.to_bytes(4, "little")
        proof = vs.prove(alpha)
        key = vs.public_key
        if bad(i):
            mode = i % 3
            if mode == 0:
                proof = _corrupt(proof, rng.randrange(len(proof)))
            elif mode == 1:
                proof = proof[:31]  # malformed: wrong length
            else:
                key = bytes(32)  # not a curve point's honest owner
        requests.append(VrfRequest(key, alpha, proof))

    # --- poet membership ---------------------------------------------
    from ..consensus.poet import merkle_path, merkle_root

    members = [b"member-" + k.to_bytes(4, "little") for k in range(16)]
    root = merkle_root(members)
    for i in range(memberships):
        idx = i % len(members)
        member = members[idx]
        proof = merkle_path(members, idx)
        if bad(i):
            if i % 2:
                member = b"not-a-member-" + i.to_bytes(4, "little")
            else:
                proof = dataclasses.replace(
                    proof, nodes=[_corrupt(n, 0) for n in proof.nodes])
        requests.append(MembershipRequest(member, proof, root,
                                          len(members)))

    # --- k2pow witnesses ---------------------------------------------
    if pows > 0:
        from ..ops import pow as k2pow

        pow_challenge = hashlib.sha256(b"wl-pow-challenge").digest()
        pow_node = hashlib.sha256(b"wl-pow-node").digest()
        # easy difficulty so honest witnesses are found in a few hashes
        difficulty = bytes([0x20]) + bytes([0xFF]) * 31
        nonce, found = 0, []
        while len(found) < max(pows // 2, 2):
            if k2pow.verify(pow_challenge, pow_node, difficulty, nonce):
                found.append(nonce)
            nonce += 1
        for i in range(pows):
            chall, node, diff = pow_challenge, pow_node, difficulty
            witness = found[i % len(found)]
            if bad(i):
                mode = i % 3
                if mode == 0:
                    witness = witness + 1  # walk to a guaranteed miss
                    while k2pow.verify(chall, node, diff, witness):
                        witness += 1
                elif mode == 1:
                    chall = _corrupt(chall, 0)  # wrong prefix
                else:
                    diff = bytes(32)  # impossible difficulty
            requests.append(PowRequest(chall, node, diff, witness))

    # --- POST proofs --------------------------------------------------
    if posts > 0:
        node = hashlib.sha256(b"wl-post-node").digest()
        commit = hashlib.sha256(b"wl-post-commit").digest()
        meta, _ = initializer.initialize(
            post_dir, node_id=node, commitment=commit,
            num_units=POST_UNITS, labels_per_unit=POST_LABELS,
            scrypt_n=POST_SCRYPT_N, max_file_size=4096, batch_size=256)
        prover = Prover(post_dir, POST_PARAMS, batch_labels=512)
        proofs = []
        for c in range(max(post_challenges, 1)):
            challenge = hashlib.sha256(
                b"wl-challenge" + c.to_bytes(4, "little")).digest()
            proofs.append((challenge, prover.prove(challenge)))
        for i in range(posts):
            challenge, proof = proofs[i % len(proofs)]
            indices = list(proof.indices)
            if bad(i):
                mode = i % 3
                if mode == 0:
                    # in-range but wrong label: fails the device recompute
                    indices[i % len(indices)] = \
                        (indices[i % len(indices)] + 1) \
                        % meta.total_labels
                elif mode == 1:
                    indices[0] = meta.total_labels + 17  # out of range
                else:
                    indices = indices[:1]  # too few indices (< k2)
            requests.append(PostRequest(post_verifier.VerifyItem(
                proof=PostProof(nonce=proof.nonce, indices=indices,
                                pow_nonce=proof.pow_nonce,
                                k2=POST_PARAMS.k2),
                challenge=challenge, node_id=node, commitment=commit,
                scrypt_n=POST_SCRYPT_N,
                total_labels=meta.total_labels)))

    rng.shuffle(requests)
    return Workload(requests=requests, ed=ed, vrf=vrf,
                    post_params=POST_PARAMS,
                    post_seed=hashlib.sha256(b"wl-k3-seed").digest())


def compare_serial_vs_farm(w: Workload) -> dict:
    """One workload through the inline serial path and a fresh farm.

    The shared harness behind bench.py's verify metrics and the
    profiler's --verify-farm view — the warm-up rules and cache clears
    are correctness-sensitive (neither path may ride the other's warm
    ed25519 verdict cache, and per-shape XLA compiles are a
    once-per-machine cost, not throughput), so they live in ONE place.
    Raises if the farm's decisions diverge from the serial path's.
    Returned stats cover the timed farm phase only.
    """
    import asyncio
    import time

    from ..core.signing import clear_verify_cache
    from .farm import VerificationFarm

    reqs = w.requests
    warm = next((r for r in reqs if isinstance(r, PostRequest)), None)
    if warm is not None:
        w.inline_verify(warm)  # pay the serial path's compile once
    clear_verify_cache()
    t0 = time.perf_counter()
    expected = w.inline_all()
    serial_s = time.perf_counter() - t0
    clear_verify_cache()

    async def run():
        farm = VerificationFarm(
            ed_verifier=w.ed, vrf_verifier=w.vrf,
            post_params=w.post_params, post_seed=w.post_seed)
        post_reqs = [r for r in reqs if isinstance(r, PostRequest)]
        await asyncio.gather(*(farm.submit(r) for r in post_reqs))
        base = {k: v for k, v in farm.stats.items()
                if isinstance(v, (int, float))}
        farm.stats["max_occupancy"] = 0  # warm-up burst must not leak
        t0 = time.perf_counter()
        got = await asyncio.gather(*(farm.submit(r) for r in reqs))
        dt = time.perf_counter() - t0
        stats = {k: (farm.stats[k] - base[k]
                     if k in base and k != "max_occupancy"
                     else farm.stats[k])
                 for k in farm.stats}
        await farm.aclose()
        return got, dt, stats

    got, batched_s, stats = asyncio.run(run())
    if got != expected:
        raise RuntimeError("farm decisions diverged from serial path")
    return {
        "items": len(reqs),
        "rejected": len(reqs) - sum(expected),
        "serial_s": serial_s,
        "batched_s": batched_s,
        "speedup": round(serial_s / batched_s, 2) if batched_s else None,
        "stats": stats,
    }
