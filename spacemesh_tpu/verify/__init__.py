"""Batched verification farm: micro-batching admission for crypto checks.

The continuous-batching pattern from inference serving applied to
verification: callers submit one signature / VRF proof / POST proof /
poet-membership check and await the verdict; a per-kind scheduler
coalesces pending requests into device-wide batches (docs/VERIFY_FARM.md).
"""

from .farm import (  # noqa: F401
    FarmClosed,
    Lane,
    MembershipRequest,
    PostRequest,
    SigRequest,
    VerificationFarm,
    VrfRequest,
)
