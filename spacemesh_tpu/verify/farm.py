"""Dynamic micro-batching verification farm.

The reference node verifies every incoming ATX/ballot/proposal serially
at ingest (reference activation/handler.go, proposals/handler.go: one
item per gossip callback). That shape wastes exactly the throughput a
batched backend earns: post/verifier.py verifies MANY proofs in one
device pass, and ed25519/ECVRF checks amortize across a worker pool —
but only when someone coalesces the work.

This module is that someone: the continuous-batching pattern from
inference serving applied to crypto verification.

* Callers submit one :class:`VerifyRequest` (ed25519 signature, VRF
  proof, POST proof, poet membership, k2pow witness) on a priority lane
  and await a future with the boolean verdict.
* A per-kind scheduler coalesces pending requests and dispatches a
  batch when it reaches ``max_batch``, when the oldest request's
  lane-latency deadline (2-10 ms) expires, or immediately when the
  backend is idle — so a lone request never waits out the coalescing
  window (the window only pays off under load, which is also the only
  time it fills).
* Three lanes — BLOCK (block-critical: certificates, hare-adjacent) >
  GOSSIP > SYNC (backfill) — with per-lane queue bounds. A saturated
  sync lane backpressures its *submitters*; batch composition always
  drains higher-priority lanes first, and a pending BLOCK request
  bypasses the in-flight dispatch cap, so sync floods cannot delay
  block-critical dispatch beyond its deadline.
* Identical in-flight requests deduplicate onto one future (gossip
  storms re-deliver the same ATX from many peers).

Verdicts are decision-identical to the inline verifiers: the farm calls
the same ``EdVerifier.verify`` / ``VrfVerifier.verify`` /
``post_verifier.verify_many`` / ``verify_membership`` code, only
batched. Embedders without an event loop (unit tests, CLI tools) simply
pass ``farm=None`` to the handlers and keep the synchronous path — the
sync-fallback contract (docs/VERIFY_FARM.md).
"""

from __future__ import annotations

import asyncio
import dataclasses
import enum
import os
import time

from typing import Optional

from ..core.signing import EdVerifier, VrfVerifier
from ..post import verifier as post_verifier
from ..post.prover import ProofParams
from ..runtime.queue import KindLanes, LaneGroup, QueueClosed
from ..utils import metrics, sanitize, tracing


class FarmClosed(QueueClosed):
    """The farm was shut down while (or before) the request was pending."""


class Lane(enum.IntEnum):
    """Priority lanes, drained in ascending order."""

    BLOCK = 0   # block-critical: certificates, consensus-blocking checks
    GOSSIP = 1  # live gossip ingest
    SYNC = 2    # backfill / historical sync


KIND_SIG = "sig"
KIND_VRF = "vrf"
KIND_POST = "post"
KIND_MEMBERSHIP = "membership"
KIND_POW = "pow"
KINDS = (KIND_SIG, KIND_VRF, KIND_POST, KIND_MEMBERSHIP, KIND_POW)


@dataclasses.dataclass(frozen=True)
class SigRequest:
    """ed25519 signature check (EdVerifier semantics, domain-separated)."""

    domain: int
    public_key: bytes
    msg: bytes
    signature: bytes

    kind = KIND_SIG

    def key(self) -> tuple:
        return (KIND_SIG, self.domain, self.public_key, self.msg,
                self.signature)


@dataclasses.dataclass(frozen=True)
class VrfRequest:
    """ECVRF proof check (VrfVerifier semantics)."""

    public_key: bytes
    alpha: bytes
    proof: bytes

    kind = KIND_VRF

    def key(self) -> tuple:
        return (KIND_VRF, self.public_key, self.alpha, self.proof)


@dataclasses.dataclass(frozen=True)
class MembershipRequest:
    """PoET merkle-membership check (consensus.poet.verify_membership)."""

    member: bytes
    proof: object  # core.types.MerkleProof
    root: bytes
    leaf_count: int

    kind = KIND_MEMBERSHIP

    def key(self) -> tuple:
        return (KIND_MEMBERSHIP, self.member, self.root, self.leaf_count,
                self.proof.leaf_index, tuple(self.proof.nodes))


@dataclasses.dataclass(frozen=True)
class PostRequest:
    """POST proof check (post.verifier.VerifyItem)."""

    item: post_verifier.VerifyItem

    kind = KIND_POST

    def key(self) -> tuple:
        it = self.item
        return (KIND_POST, it.challenge, it.node_id, it.commitment,
                it.scrypt_n, it.total_labels, it.proof.nonce,
                it.proof.pow_nonce, tuple(it.proof.indices))


@dataclasses.dataclass(frozen=True)
class PowRequest:
    """k2pow witness check (ops/pow.py verify semantics): the
    verification half of the proof-gating proof-of-work, batched across
    items with per-item prefixes and difficulties (verifyd routes remote
    nodes' witness checks here)."""

    challenge: bytes
    node_id: bytes
    difficulty: bytes
    nonce: int

    kind = KIND_POW

    def key(self) -> tuple:
        return (KIND_POW, self.challenge, self.node_id, self.difficulty,
                self.nonce)


class _Pending:
    __slots__ = ("req", "lane", "future", "enqueued", "deadline", "span")

    def __init__(self, req, lane: Lane, future: asyncio.Future,
                 enqueued: float, deadline: float):
        self.req = req
        self.lane = lane
        self.future = future
        self.enqueued = enqueued
        self.deadline = deadline
        self.span = tracing._NOP  # the submitter's request span


class _KindState:
    """Per-kind scheduler state: the runtime's per-lane deques
    (runtime/queue.py KindLanes) + arrival signal + in-flight tasks."""

    def __init__(self, group: LaneGroup) -> None:
        self.lanes = KindLanes(group)
        self.arrived = asyncio.Event()
        self.inflight: set[asyncio.Task] = set()
        self.worker: Optional[asyncio.Task] = None


# default coalescing windows per lane (the ISSUE's 2-10 ms band): block
# work dispatches almost immediately, backfill may wait longest for a
# fuller batch
DEFAULT_MAX_WAIT_S = {Lane.BLOCK: 0.002, Lane.GOSSIP: 0.005,
                      Lane.SYNC: 0.010}
DEFAULT_LANE_BOUNDS = {Lane.BLOCK: 4096, Lane.GOSSIP: 8192,
                       Lane.SYNC: 16384}


class VerificationFarm:
    """Micro-batching admission service for verification work.

    One farm per node (node/app.py). ``submit`` may only be called from
    a running event loop; workers start lazily on first submit and
    rebind automatically if the embedder runs multiple event loops over
    the farm's lifetime (tests that asyncio.run() twice).
    """

    def __init__(self, *, ed_verifier: EdVerifier | None = None,
                 vrf_verifier: VrfVerifier | None = None,
                 post_params: ProofParams | None = None,
                 post_seed: bytes | None = None,
                 max_batch: int = 256,
                 max_inflight: int = 4,
                 max_wait_s: dict[Lane, float] | None = None,
                 lane_bounds: dict[Lane, int] | None = None,
                 sig_threads: int | None = None,
                 stall_deadline_s: float = 30.0,
                 tuner=None):
        self.ed_verifier = ed_verifier or EdVerifier()
        self.vrf_verifier = vrf_verifier or VrfVerifier()
        self.post_params = post_params or ProofParams()
        # deterministic K3 seed for reproducible verification (tests,
        # benches); None = fresh random seed per dispatch, exactly like
        # the inline verify_many default
        self.post_seed = post_seed
        self.max_batch = max(int(max_batch), 1)
        self.max_inflight = max(int(max_inflight), 1)
        self.max_wait_s = dict(DEFAULT_MAX_WAIT_S)
        if max_wait_s:
            self.max_wait_s.update(max_wait_s)
        self.lane_bounds = dict(DEFAULT_LANE_BOUNDS)
        if lane_bounds:
            self.lane_bounds.update(lane_bounds)
        self._sig_threads = sig_threads
        # optional speculative batch-sizing policy (verifyd/batchtune.py
        # BatchTuner, or anything with note_arrival/observe/target_batch/
        # dispatch_now): sizes batches from MEASURED per-kind device
        # rates and dispatches a partially-full batch as soon as the
        # marginal wait for more items exceeds the predicted throughput
        # gain. None keeps the static max_batch + deadline policy.
        self._tuner = tuner
        self._pool = None  # lazy ThreadPoolExecutor for sig/vrf fan-out
        self._loop: asyncio.AbstractEventLoop | None = None
        self.stats = {
            "requests": 0, "dedup_hits": 0, "batches": 0, "items": 0,
            "max_occupancy": 0, "dispatch_s": 0.0, "rejected": 0,
            "queue_peak": {lane.name.lower(): 0 for lane in Lane},
        }
        # stats are mutated on the LOOP only (backend threads return
        # results; the loop-side finally block does the accounting) —
        # owner-write is the runtime twin of that loop-only contract
        self._shared_stats = sanitize.SharedField("verify.farm.stats",
                                                  mode="owner-write")
        # lane accounting (bounds, backpressure waiters with the slot
        # handoff, dedup) is the shared runtime's (runtime/queue.py);
        # this farm keeps only the coalescing policy and the backends
        self._group = LaneGroup(Lane, self.lane_bounds,
                                make_exc=lambda: FarmClosed("farm closed"),
                                on_depth=self._on_depth)
        self._kinds: dict[str, _KindState] = {}
        self._closed = False
        # liveness contract (obs/health.py): while ANY lane holds queued
        # requests, the dispatched-item counter must advance within the
        # deadline — a wedged backend thread or a dead worker task shows
        # up on /readyz instead of as silently-hanging submitters
        from ..obs import health as health_mod
        from ..obs import remediate as remediate_mod

        self._watchdog = health_mod.Watchdog(
            "verify.farm",
            progress=lambda: self.stats["items"],
            active=lambda: self._group.total() > 0,
            deadline_s=stall_deadline_s)
        health_mod.HEALTH.register("verify.farm", self._watchdog.check)
        # per-kind backend breakers (obs/remediate.py): a device backend
        # that keeps raising stops being re-paid per batch — its batches
        # fail FAST with a typed BreakerOpen until a half-open probe
        # batch finds it recovered. Sized generously: only a sustained
        # failure run trips (a lone flaky batch never opens it).
        self._breakers: dict[str, remediate_mod.CircuitBreaker] = {}
        self._breaker_cfg = {"failure_budget": 5, "window_s": 30.0,
                             "cooldown_s": 5.0, "cooldown_cap_s": 60.0}
        # the farm's recovery hook: a stalled-farm verdict resets lanes
        # (fails wedged waiters typed, restarts workers) instead of
        # waiting for an operator (docs/SELF_HEALING.md)
        remediate_mod.ACTIONS.register("verify.farm", "reset_farm_lanes",
                                       self.reset_lanes)

    def _on_depth(self, lane: Lane, depth: int) -> None:
        lname = lane.name.lower()
        metrics.verify_farm_queue_depth.set(depth, lane=lname)
        if depth > self.stats["queue_peak"][lname]:
            self.stats["queue_peak"][lname] = depth

    # --- lifecycle ----------------------------------------------------

    def _bind(self) -> None:
        """Bind scheduler state to the CURRENT running loop; a farm that
        outlives an asyncio.run() rebinds on the next submit (pending
        work from the dead loop is unrecoverable and dropped)."""
        loop = asyncio.get_running_loop()
        if not self._group.bind(loop):
            return
        self._loop = loop
        self._kinds = {kind: _KindState(self._group) for kind in KINDS}

    def _ensure_worker(self, kind: str) -> None:
        st = self._kinds[kind]
        if st.worker is None or st.worker.done():
            st.worker = self._loop.create_task(self._worker(kind))

    def _fail_pending(self) -> None:
        """Fail every queued request and backpressure waiter with
        FarmClosed (the bound loop must still be alive)."""
        for st in self._kinds.values():
            st.arrived.set()
            for p in st.lanes.drain_all():
                if not p.future.done():
                    p.future.set_exception(FarmClosed("farm closed"))
        self._group.fail_waiters()

    async def aclose(self) -> None:
        """Stop workers and fail pending requests with FarmClosed."""
        self._closed = True
        self._group.closed = True
        workers = [st.worker for st in self._kinds.values()
                   if st.worker is not None]
        for w in workers:
            w.cancel()
        self._fail_pending()
        await asyncio.gather(*workers, return_exceptions=True)
        inflight = [t for st in self._kinds.values() for t in st.inflight]
        await asyncio.gather(*inflight, return_exceptions=True)
        self.shutdown()

    def shutdown(self) -> None:
        """Synchronous teardown: drop scheduler state and the worker
        pool. Safe to call twice. Normally App.close runs this after
        the loop exits, but error-path teardown can reach it with the
        loop still alive — then pending futures and backpressure
        waiters must fail with FarmClosed, or handler coroutines
        awaiting submit() hang forever (only aclose() would otherwise
        resolve them)."""
        self._closed = True
        self._group.closed = True
        for st in self._kinds.values():
            if st.worker is not None:
                try:
                    st.worker.cancel()
                except RuntimeError:  # task's loop already torn down
                    pass
        if self._loop is not None and not self._loop.is_closed():
            self._fail_pending()
        if self._pool is not None:
            self._pool.shutdown(wait=False, cancel_futures=True)
            self._pool = None
        from ..obs import health as health_mod
        from ..obs import remediate as remediate_mod

        health_mod.HEALTH.unregister("verify.farm", self._watchdog.check)
        remediate_mod.ACTIONS.unregister("verify.farm",
                                         "reset_farm_lanes",
                                         self.reset_lanes)
        for br in self._breakers.values():
            remediate_mod.BREAKERS.unregister(br)
        self._breakers.clear()

    def reset_lanes(self) -> None:
        """The remediation engine's ``reset_farm_lanes`` action: fail
        every queued request and backpressure waiter with a typed
        FarmClosed and restart the workers — a wedged lane recovers to
        an empty, serving farm instead of pinning its submitters until
        process restart. Pending verdicts are LOST (their callers see
        the typed error and re-submit); in-flight backend batches
        resolve normally."""
        if self._closed or self._loop is None or self._loop.is_closed():
            return
        reset_exc = FarmClosed("farm lanes reset by remediation")
        for st in self._kinds.values():
            st.arrived.set()
            for p in st.lanes.drain_all():
                # unlike the close path, the farm keeps serving: every
                # drained entry's lane slot must be released or the
                # lanes stay "full" forever
                self._group.release(p.lane)
                if self._group.dedup.get(p.req.key()) is p:
                    del self._group.dedup[p.req.key()]
                if not p.future.done():
                    p.future.set_exception(reset_exc)
            if st.worker is not None and not st.worker.done():
                st.worker.cancel()
                st.worker = None
        self._group.fail_waiters()

    def _breaker(self, kind: str):
        br = self._breakers.get(kind)
        if br is None:
            from ..obs import remediate as remediate_mod

            br = self._breakers[kind] = remediate_mod.BREAKERS.register(
                remediate_mod.CircuitBreaker(
                    f"verify.farm.{kind}",
                    time_source=self._loop.time,
                    **self._breaker_cfg))
        return br

    # --- submission ---------------------------------------------------

    async def submit(self, req, lane: Lane = Lane.GOSSIP) -> bool:
        """Queue one verification and await its verdict."""
        if self._closed:
            raise FarmClosed("farm closed")
        self._bind()
        lane = Lane(lane)
        self._shared_stats.touch()
        self.stats["requests"] += 1
        metrics.verify_farm_requests.inc(kind=req.kind,
                                         lane=lane.name.lower())
        if self._tuner is not None:
            # arrival-rate EWMA feeds the speculative dispatch decision
            self._tuner.note_arrival(req.kind, self._loop.time())
        key = req.key()
        ent = self._group.dedup.get(key)
        if ent is not None and not ent.future.done():
            self.stats["dedup_hits"] += 1
            metrics.verify_farm_dedup_hits.inc()
            if lane < ent.lane:
                # a higher-priority caller must not inherit the queued
                # twin's lane position (a block-critical check stuck
                # behind a sync backlog would defeat the lane contract)
                self._promote(ent, lane)
            # the twin's request span owns the lifecycle; this caller's
            # span just records that it coalesced onto it
            async with tracing.span(
                    "farm.request",
                    {"kind": req.kind, "lane": lane.name.lower(),
                     "dedup": True, "twin": ent.span.id}
                    if tracing.is_enabled() else None):
                return await self._await(ent.future)
        sp = tracing.span("farm.request",
                          {"kind": req.kind, "lane": lane.name.lower()}
                          if tracing.is_enabled() else None)
        with sp:
            # backpressure: a full lane blocks ITS OWN submitters only
            # (the waiter/slot-handoff semantics live in
            # runtime/queue.py LaneGroup.acquire — the ONE copy)
            if self._group.count(lane) >= self.lane_bounds[lane]:
                async with tracing.span("farm.lane_wait",
                                        {"lane": lane.name.lower()}
                                        if tracing.is_enabled() else None):
                    await self._group.acquire(lane)
            now = self._loop.time()
            pend = _Pending(req, lane, self._loop.create_future(), now,
                            now + self.max_wait_s[lane])
            pend.span = sp
            st = self._kinds[req.kind]
            st.lanes.append(pend)
            self._group.dedup[key] = pend
            self._ensure_worker(req.kind)
            st.arrived.set()
            return await self._await(pend.future)

    @staticmethod
    async def _await(fut: asyncio.Future) -> bool:
        # shield: dedup can hand one future to many awaiters — a caller
        # cancelling its own await must not cancel everyone's verdict
        try:
            return await asyncio.shield(fut)
        except asyncio.CancelledError:
            if fut.cancelled():
                raise FarmClosed("farm closed") from None
            raise

    # --- scheduler ----------------------------------------------------

    async def _worker(self, kind: str) -> None:
        st = self._kinds[kind]
        try:
            while not self._closed:
                st.arrived.clear()
                if st.lanes.count() == 0:
                    await st.arrived.wait()
                    continue
                # one loop turn so same-tick submitters (gather bursts)
                # land in this batch
                await asyncio.sleep(0)
                await self._coalesce(kind, st)
                if self._closed:
                    break
                # take() is NOT capped at the tuned target: the target
                # is the occupancy worth WAITING for, and a deeper
                # backlog dispatching as one batch both amortizes
                # better and feeds the tuner observations above the
                # target — capping at the target would lock a
                # collapsed model in place (it could never measure a
                # fuller batch again)
                batch = st.lanes.take(self.max_batch)
                if not batch:
                    continue
                self._on_taken(batch)
                task = self._loop.create_task(self._dispatch(kind, batch))
                st.inflight.add(task)
                task.add_done_callback(st.inflight.discard)
        except asyncio.CancelledError:
            pass

    def _batch_limit(self, kind: str) -> int:
        """Per-kind batch-size cap: the tuner's measured-rate target when
        one is attached (capped by max_batch — the device/memory bound),
        else max_batch."""
        if self._tuner is not None:
            target = self._tuner.target_batch(kind)
            if target:
                return max(1, min(int(target), self.max_batch))
        return self.max_batch

    def _tuner_go(self, kind: str, st: _KindState, n: int,
                  now: float) -> bool:
        """Speculative early dispatch: the tuner predicts (from measured
        per-kind rates + the arrival EWMA) that waiting for a fuller
        batch costs more than it gains. Never extends the lane deadline
        — it can only dispatch EARLIER than the 2-10 ms window."""
        if self._tuner is None:
            return False
        oldest = min((q[0].enqueued for q in st.lanes.lanes.values()
                      if q), default=now)
        return bool(self._tuner.dispatch_now(kind, n,
                                             max(now - oldest, 0.0)))

    async def _coalesce(self, kind: str, st: _KindState) -> None:
        """Hold the batch open until it is worth dispatching.

        Dispatch NOW when: the batch is full (the per-kind tuned target
        when a batch tuner is attached); the backend is idle (a lone
        request must not wait out the coalescing window); the oldest
        pending deadline has passed and an in-flight slot is free; or
        the tuner's speculative model says the marginal wait for more
        items exceeds the predicted throughput gain. The in-flight cap
        throttles small-batch churn under load — but a pending BLOCK
        request bypasses the cap, so a saturated sync lane can never
        delay block-critical dispatch beyond its deadline."""
        while not self._closed:
            n = st.lanes.count()
            if n == 0:
                return
            # the in-flight cap gates EVERY dispatch (a full batch too:
            # spawning the whole backlog at once would flood the worker
            # pool and anything submitted later — block-critical work
            # included — would queue behind sleeping threads). Only a
            # pending BLOCK request bypasses the cap.
            can_go = (len(st.inflight) < self.max_inflight
                      or bool(st.lanes.lanes[Lane.BLOCK]))
            now = self._loop.time()
            if self._tuner is None:
                # static policy: full batch, idle fast-path, deadline
                go = (n >= self.max_batch
                      or not st.inflight
                      or st.lanes.earliest_deadline() <= now)
            else:
                # tuned policy: the idle fast-path routes through the
                # speculative model too — under service load an idle
                # backend must not slice a filling batch into
                # fragments, and with no model yet (or arrivals gone
                # quiet) dispatch_now returns the fast-path answer
                go = (n >= self._batch_limit(kind)
                      or st.lanes.earliest_deadline() <= now
                      or self._tuner_go(kind, st, n, now))
            if can_go and go:
                return
            st.arrived.clear()
            arr = self._loop.create_task(st.arrived.wait())
            waits = {arr} | set(st.inflight)
            # dispatch-eligible: sleep at most until the deadline;
            # capped: sleep until a slot frees or something arrives
            timeout = max(st.lanes.earliest_deadline() - self._loop.time(),
                          0.0005) if can_go else None
            await asyncio.wait(waits, timeout=timeout,
                               return_when=asyncio.FIRST_COMPLETED)
            arr.cancel()

    def _promote(self, ent: _Pending, lane: Lane) -> None:
        """Move a still-queued pending entry to a higher-priority lane
        (dedup hit from that lane); no-op once it is in a dispatch."""
        st = self._kinds[ent.req.kind]
        if not st.lanes.remove(ent):
            return  # already taken into a batch
        ent.lane = lane
        ent.deadline = min(ent.deadline,
                           self._loop.time() + self.max_wait_s[lane])
        st.lanes.append(ent)
        st.arrived.set()

    def _on_taken(self, batch: list[_Pending]) -> None:
        now = self._loop.time()
        for p in batch:
            self._group.release(p.lane)
            wait = max(now - p.enqueued, 0.0)
            metrics.verify_farm_queue_wait_seconds.observe(
                wait, kind=p.req.kind)
            p.span.set(queue_wait_ms=round(wait * 1e3, 3))

    async def _dispatch(self, kind: str, batch: list[_Pending]) -> None:
        # the batch span is the hub of the capture: its args carry the
        # member request-span ids, and each member span records the
        # batch id back — so in a Perfetto export a request's wall time
        # decomposes into lane wait vs its batch's backend dispatch
        bsp = tracing.span("farm.batch",
                           {"kind": kind, "n": len(batch),
                            "members": [p.span.id for p in batch]}
                           if tracing.is_enabled() else None)
        for p in batch:
            p.span.set(batch=bsp.id)
        t0 = time.perf_counter()
        br = self._breaker(kind)
        try:
            with bsp:
                if not br.allow():
                    # the kind's backend is known-dead: fail the batch
                    # fast with the typed breaker error instead of
                    # re-paying the failing dispatch (a half-open probe
                    # batch goes through once the cooldown elapses)
                    from ..obs.remediate import BreakerOpen

                    raise BreakerOpen(br.component, br.retry_in())
                results = await asyncio.to_thread(
                    self._run_backend, kind, [p.req for p in batch])
        except Exception as exc:  # noqa: BLE001 — fail the batch, not the farm
            from ..obs.remediate import BreakerOpen

            if not isinstance(exc, BreakerOpen):
                br.record_failure()
            for p in batch:
                if not p.future.done():
                    p.future.set_exception(exc)
        else:
            br.record_success()
            for p, ok in zip(batch, results):
                if not p.future.done():
                    p.future.set_result(bool(ok))
                if not bool(ok):
                    self.stats["rejected"] += 1
            if self._tuner is not None:
                # successful batches only refine the tuner's model — a
                # backend that RAISED in milliseconds must not record a
                # phantom items/s rate
                self._tuner.observe(kind, len(batch),
                                    time.perf_counter() - t0)
        finally:
            dt = time.perf_counter() - t0
            for p in batch:
                if self._group.dedup.get(p.req.key()) is p:
                    del self._group.dedup[p.req.key()]
            self._shared_stats.touch()
            self.stats["batches"] += 1
            self.stats["items"] += len(batch)
            if len(batch) > self.stats["max_occupancy"]:
                self.stats["max_occupancy"] = len(batch)
            self.stats["dispatch_s"] += dt
            metrics.verify_farm_batches.inc(kind=kind)
            metrics.verify_farm_batch_occupancy.observe(len(batch))
            metrics.verify_farm_dispatch_seconds.observe(dt, kind=kind)

    # --- backends (run in a worker thread) ----------------------------

    def _run_backend(self, kind: str, reqs: list) -> list[bool]:
        if kind == KIND_SIG:
            from ..core import signing

            if signing._HAVE_CRYPTOGRAPHY:
                # OpenSSL per-item releases the GIL: thread fan-out wins
                return self._fanout(self._verify_sig, reqs)
            # pure-Python fallback: one random-linear-combination batch
            # check (Pippenger MSM) beats N independent ladders
            return self.ed_verifier.verify_many(
                [(r.domain, r.public_key, r.msg, r.signature)
                 for r in reqs])
        if kind == KIND_VRF:
            return self._fanout(self._verify_vrf, reqs)
        if kind == KIND_MEMBERSHIP:
            from ..consensus.poet import verify_membership

            return [verify_membership(r.member, r.proof, r.root,
                                      r.leaf_count) for r in reqs]
        if kind == KIND_POST:
            return self._verify_posts(reqs)
        if kind == KIND_POW:
            from ..ops import pow as k2pow

            return k2pow.verify_many(
                [(r.challenge, r.node_id, r.difficulty, r.nonce)
                 for r in reqs])
        raise ValueError(f"unknown verify kind {kind!r}")

    def _verify_sig(self, r: SigRequest) -> bool:
        return self.ed_verifier.verify(r.domain, r.public_key, r.msg,
                                       r.signature)

    def _verify_vrf(self, r: VrfRequest) -> bool:
        return self.vrf_verifier.verify(r.public_key, r.alpha, r.proof)

    def _fanout(self, fn, reqs: list) -> list[bool]:
        """Chunk a big batch across the worker pool: OpenSSL ed25519 and
        the native ECVRF library both release the GIL, so wide batches
        verify on every core."""
        threads = self._sig_threads
        if threads is None:
            threads = min(8, os.cpu_count() or 1)
        if threads <= 1 or len(reqs) < 2 * threads:
            return [fn(r) for r in reqs]
        if self._pool is None:
            import concurrent.futures

            self._pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=threads,
                thread_name_prefix="verify-farm")
        chunk = (len(reqs) + threads - 1) // threads
        parts = [reqs[i:i + chunk] for i in range(0, len(reqs), chunk)]
        futs = [self._pool.submit(lambda part=part: [fn(r) for r in part])
                for part in parts]
        out: list[bool] = []
        for f in futs:
            out.extend(f.result())
        return out

    def _verify_posts(self, reqs: list[PostRequest]) -> list[bool]:
        items = [r.item for r in reqs]
        n = len(items)
        # pad to a power-of-two item count so the flattened device shapes
        # recur across occupancies (each new shape is an XLA compile);
        # duplicated lanes are free relative to a recompile
        pad = 1 << (n - 1).bit_length()
        if pad > n and pad <= self.max_batch:
            items = items + [items[0]] * (pad - n)
        return post_verifier.verify_many(
            items, self.post_params, seed=self.post_seed)[:n]
