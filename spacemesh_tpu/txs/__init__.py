"""Conservative state / mempool (reference txs/)."""

from .conservative_state import ConservativeState  # noqa: F401
