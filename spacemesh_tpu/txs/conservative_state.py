"""Mempool with per-account nonce/balance projection.

Mirrors the reference's conservative state (reference
txs/conservative_state.go:53: a tx cache projecting each account's
nonce/balance as if pending txs applied in order; txs/mempool_iterator.go
orders candidates by fee; SelectProposalTXs picks for a proposal). A tx is
admitted only if its nonce continues the account's projected chain and the
projected balance covers fee + amount (conservative: never propose a tx
that cannot apply).
"""

from __future__ import annotations

import dataclasses
import threading

from ..core.types import Transaction
from ..storage import transactions as txstore
from ..storage.db import Database
from ..vm.vm import Method, SpendPayload, TxBody, TxValidity, VM


@dataclasses.dataclass
class _Pending:
    tx: Transaction
    body: TxBody
    fee: int
    spend: int


class ConservativeState:
    def __init__(self, db: Database, vm: VM):
        self.db = db
        self.vm = vm
        self._lock = threading.RLock()
        # principal -> list of pending txs ordered by nonce
        self._pool: dict[bytes, list[_Pending]] = {}

    # --- admission ----------------------------------------------------

    def add(self, tx: Transaction) -> TxValidity:
        """Validate + admit a gossip/API transaction into the pool."""
        body = self.vm.parse(tx)
        if body is None:
            return TxValidity.MALFORMED
        with self._lock:
            validity = self._admissible(body)
            if validity != TxValidity.VALID:
                return validity
            fee = self.vm.gas(body) * body.gas_price
            spend = 0
            if body.method == Method.SPEND:
                spend = SpendPayload.from_bytes(body.payload).amount
            self._pool.setdefault(body.principal, []).append(
                _Pending(tx=tx, body=body, fee=fee, spend=spend))
            txstore.add_tx(self.db, tx, principal=body.principal,
                           nonce=body.nonce)
            return TxValidity.VALID

    def _admissible(self, body: TxBody) -> TxValidity:
        # signature/structure against current state
        validity = self.vm.validate(body, check_sig=True)
        if validity == TxValidity.INVALID_NONCE:
            pass  # maybe continues the projected chain; checked below
        elif validity == TxValidity.NOT_SPAWNED:
            # allowed if a pending spawn for this principal exists
            if not any(p.body.method == Method.SPAWN
                       for p in self._pool.get(body.principal, ())):
                return TxValidity.NOT_SPAWNED
        elif validity != TxValidity.VALID:
            return validity

        nonce, balance = self._projection(body.principal)
        if body.nonce != nonce:
            return TxValidity.INVALID_NONCE
        fee = self.vm.gas(body) * body.gas_price
        spend = 0
        if body.method == Method.SPEND:
            try:
                spend = SpendPayload.from_bytes(body.payload).amount
            except Exception:
                return TxValidity.MALFORMED
        if balance < fee + spend:
            return TxValidity.INSUFFICIENT_FUNDS
        return TxValidity.VALID

    def _projection(self, principal: bytes) -> tuple[int, int]:
        row = txstore.account(self.db, principal)
        nonce = row["next_nonce"] if row else 0
        balance = row["balance"] if row else 0
        for p in self._pool.get(principal, ()):
            nonce = max(nonce, p.body.nonce + 1)
            balance -= p.fee + p.spend
        return nonce, balance

    def projected(self, principal: bytes) -> tuple[int, int]:
        with self._lock:
            return self._projection(principal)

    # --- selection ----------------------------------------------------

    def select_proposal_txs(self, max_txs: int) -> list[bytes]:
        """Pick tx ids for a proposal: per-account nonce order, accounts
        interleaved by fee (reference SelectProposalTXs + mempool
        iterator)."""
        with self._lock:
            heads = {p: list(txs) for p, txs in self._pool.items() if txs}
            out: list[bytes] = []
            while heads and len(out) < max_txs:
                best = max(heads, key=lambda p: heads[p][0].fee)
                out.append(heads[best][0].tx.id)
                heads[best].pop(0)
                if not heads[best]:
                    del heads[best]
            return out

    def get(self, tx_id: bytes) -> Transaction | None:
        with self._lock:
            for txs in self._pool.values():
                for p in txs:
                    if p.tx.id == tx_id:
                        return p.tx
        return txstore.get_tx(self.db, tx_id)

    def pending_count(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._pool.values())

    # --- post-application maintenance ---------------------------------

    def on_applied(self) -> None:
        """Drop pool entries the chain has caught up with (nonce below the
        account's persisted next_nonce) or that became unpayable under the
        account's NEW balance — otherwise a drained account's spends would
        be re-proposed and fail layer after layer."""
        with self._lock:
            for principal in list(self._pool):
                row = txstore.account(self.db, principal)
                next_nonce = row["next_nonce"] if row else 0
                balance = row["balance"] if row else 0
                kept = []
                for p in self._pool[principal]:
                    if p.body.nonce < next_nonce:
                        continue
                    if balance < p.fee + p.spend:
                        break  # nonce chain broken from here on
                    balance -= p.fee + p.spend
                    kept.append(p)
                if kept:
                    self._pool[principal] = kept
                else:
                    del self._pool[principal]
