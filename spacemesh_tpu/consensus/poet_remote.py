"""External poet service: remote client, server CLI, and multi-poet.

The reference talks HTTP to external poet servers, registers every
identity's challenge at ALL of them before the round, then picks the
best proof by tick count (reference activation/poet.go client,
activation/nipost.go:349 submitPoetChallenges / getBestProof;
activation/poetdb.go stores+validates proofs). This module is that
capability for the TPU framework, using the same length-prefixed JSON
transport as the POST worker (one framing for every auxiliary service):

  RemotePoetClient   — PoetService surface over TCP (register /
                       execute_round / result + membership fetch)
  PoetServerDaemon   — wraps an in-proc PoetService behind a listener
                       (`python -m spacemesh_tpu.tools.poet_server`)
  MultiPoet          — fan-out registration to several poets; the round
                       result is the BEST proof by ticks among the poets
                       that included our challenge (a dead poet costs
                       nothing; reference nipost.go multi-poet phase 0)
"""

from __future__ import annotations

import asyncio
import json
import socket
import struct
from typing import Optional

from ..core.types import MerkleProof, PoetProof
from .poet import PoetService, RoundResult

MAX_MSG = 16 << 20


def _send_msg(sock: socket.socket, obj: dict) -> None:
    data = json.dumps(obj).encode()
    sock.sendall(struct.pack("<I", len(data)) + data)


def _recv_msg(sock: socket.socket) -> dict:
    head = b""
    while len(head) < 4:
        chunk = sock.recv(4 - len(head))
        if not chunk:
            raise ConnectionError("connection closed")
        head += chunk
    (length,) = struct.unpack("<I", head)
    if length > MAX_MSG:
        raise ConnectionError("oversized message")
    buf = b""
    while len(buf) < length:
        chunk = sock.recv(length - len(buf))
        if not chunk:
            raise ConnectionError("connection closed")
        buf += chunk
    return json.loads(buf)


def _result_to_dict(result: RoundResult) -> dict:
    return {
        "proof": {
            "poet_id": result.proof.poet_id.hex(),
            "round_id": result.proof.round_id,
            "root": result.proof.root.hex(),
            "ticks": result.proof.ticks,
        },
        "members": [m.hex() for m in result.members],
    }


def _result_from_dict(d: dict) -> RoundResult:
    p = d["proof"]
    return RoundResult(
        proof=PoetProof(poet_id=bytes.fromhex(p["poet_id"]),
                        round_id=p["round_id"],
                        root=bytes.fromhex(p["root"]),
                        ticks=p["ticks"]),
        members=[bytes.fromhex(m) for m in d["members"]])


class PoetServerDaemon:
    """Serves one in-proc PoetService over TCP."""

    def __init__(self, service: PoetService, listen: str = "127.0.0.1:0"):
        self.service = service
        self.listen = listen
        self.address: tuple[str, int] | None = None
        self._server: asyncio.AbstractServer | None = None

    async def start(self) -> tuple[str, int]:
        host, _, port = self.listen.rpartition(":")
        self._server = await asyncio.start_server(
            self._client, host or "127.0.0.1", int(port or 0))
        self.address = self._server.sockets[0].getsockname()[:2]
        return self.address

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    async def _client(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                head = await reader.readexactly(4)
                (length,) = struct.unpack("<I", head)
                if length > MAX_MSG:
                    break
                req = json.loads(await reader.readexactly(length))
                resp = await self._dispatch(req)
                data = json.dumps(resp).encode()
                writer.write(struct.pack("<I", len(data)) + data)
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        finally:
            writer.close()

    async def _dispatch(self, req: dict) -> dict:
        try:
            method = req.get("method")
            if method == "info":
                return {"ok": True,
                        "poet_id": self.service.poet_id.hex(),
                        "ticks": self.service.ticks}
            if method == "register":
                cert = None
                if req.get("cert") is not None:
                    from .certifier import PoetCert

                    cert = PoetCert.from_dict(req["cert"])
                await self.service.register(
                    req["round_id"], bytes.fromhex(req["challenge"]),
                    node_id=(bytes.fromhex(req["node_id"])
                             if req.get("node_id") else None),
                    signature=(bytes.fromhex(req["signature"])
                               if req.get("signature") else None),
                    cert=cert)
                return {"ok": True}
            if method == "execute_round":
                result = await self.service.execute_round(req["round_id"])
                return {"ok": True, "result": _result_to_dict(result)}
            if method == "result":
                result = self.service.result(req["round_id"])
                if result is None:
                    return {"ok": True, "result": None}
                return {"ok": True, "result": _result_to_dict(result)}
            return {"ok": False, "error": f"unknown method {method!r}"}
        except Exception as e:  # noqa: BLE001
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}


class RemotePoetClient:
    """PoetService surface backed by a remote poet daemon. Registrations
    are remembered locally so a crashed node can resubmit idempotently
    (the daemon dedups; reference localsql poet_registrations)."""

    def __init__(self, address: tuple[str, int], timeout: float = 120.0):
        self.address = tuple(address)
        self.timeout = timeout
        self._info_cache: dict | None = None

    @property
    def poet_id(self) -> bytes:
        """Lazy: a node must be able to START while its poet daemon is
        momentarily down — id resolves (and caches) on first contact."""
        try:
            return self._info()["poet_id_bytes"]
        except (OSError, RuntimeError):
            return bytes(32)

    @property
    def ticks(self) -> int:
        try:
            return self._info()["ticks"]
        except (OSError, RuntimeError):
            return 0

    def _call(self, req: dict) -> dict:
        with socket.create_connection(self.address,
                                      timeout=self.timeout) as s:
            _send_msg(s, req)
            resp = _recv_msg(s)
        if not resp.get("ok"):
            raise RuntimeError(f"poet: {resp.get('error')}")
        return resp

    def _info(self) -> dict:
        if self._info_cache is None:
            d = self._call({"method": "info"})
            self._info_cache = {"poet_id_bytes": bytes.fromhex(d["poet_id"]),
                                "ticks": d["ticks"]}
        return self._info_cache

    async def register(self, round_id: str, challenge: bytes,
                       node_id: bytes | None = None,
                       signature: bytes | None = None,
                       cert=None) -> None:
        req = {"method": "register", "round_id": round_id,
               "challenge": challenge.hex()}
        if cert is not None:
            req["cert"] = cert.to_dict()
        if node_id is not None:
            req["node_id"] = node_id.hex()
        if signature is not None:
            req["signature"] = signature.hex()
        await asyncio.to_thread(self._call, req)

    async def execute_round(self, round_id: str) -> RoundResult:
        d = await asyncio.to_thread(
            self._call, {"method": "execute_round", "round_id": round_id})
        return _result_from_dict(d["result"])

    def result(self, round_id: str) -> Optional[RoundResult]:
        try:
            d = self._call({"method": "result", "round_id": round_id})
        except (OSError, RuntimeError):
            return None
        if d.get("result") is None:
            return None
        return _result_from_dict(d["result"])


class MultiPoet:
    """Register everywhere, take the best proof by ticks (reference
    nipost.go getBestProof). Implements the PoetService seam the ATX
    Builder uses, so multi-poet is transparent to the pipeline."""

    def __init__(self, poets: list):
        if not poets:
            raise ValueError("need at least one poet")
        self.poets = poets
        self.poet_id = poets[0].poet_id  # nominal; results carry their own

    async def register(self, round_id: str, challenge: bytes,
                       node_id: bytes | None = None,
                       signature: bytes | None = None,
                       cert=None) -> None:
        results = await asyncio.gather(
            *(p.register(round_id, challenge, node_id=node_id,
                         signature=signature, cert=cert)
              for p in self.poets),
            return_exceptions=True)
        if all(isinstance(r, Exception) for r in results):
            raise RuntimeError(f"all poets failed: {results[0]}")

    async def execute_round(self, round_id: str) -> RoundResult:
        results = await asyncio.gather(
            *(p.execute_round(round_id) for p in self.poets),
            return_exceptions=True)
        ok = [r for r in results if isinstance(r, RoundResult)]
        if not ok:
            raise RuntimeError(f"all poets failed: {results[0]}")
        return max(ok, key=lambda r: r.proof.ticks)

    def result(self, round_id: str) -> Optional[RoundResult]:
        best: RoundResult | None = None
        for p in self.poets:
            r = p.result(round_id)
            if r is not None and (best is None
                                  or r.proof.ticks > best.proof.ticks):
                best = r
        return best
