"""ATX v2: merged multi-identity ATXs, marriages, equivocation sets.

Mirrors the reference's v2 activation pipeline (reference
activation/wire/wire_v2.go:17 ActivationTxV2 w/ NiPosts + Marriages;
activation/handler_v2.go:75 processATX, :379 validateMarriages; married
identities form ONE equivocation set — sql/marriage — so malfeasance by
any member condemns all of them).

Design notes (TPU framework, not a wire copy):
- One envelope, signed by the primary identity, carries a SubPost per
  covered identity. Every covered identity must be the primary or
  married to it (a certificate inside this ATX or a recorded marriage).
- Marriage certificates are the PARTNER's signature over
  "marry" || primary_id — consent, not mere association.
- Each identity keeps its own synthetic ATX id
  (ActivationTxV2.identity_atx_id) so eligibility/cache/tortoise weight
  stays per-identity.
- POST verification runs as ONE batched pass across all subposts (the
  vmapped verifier, post/verifier.py) — a merged ATX is a batch, which
  is exactly the TPU-native win.
"""

from __future__ import annotations

import asyncio
import dataclasses
from typing import Optional

from ..core import codec
from ..core.signing import Domain, EdSigner, EdVerifier
from ..core.types import (
    EMPTY32,
    ActivationTxV2,
    MarriageCert,
    NIPost,
    Post,
    PostMetadataWire,
    SubPostV2,
)
from ..post import verifier as post_verifier
from ..post.prover import Proof as PostProof, ProofParams
from ..storage import atxs as atxstore
from ..storage import misc as miscstore
from ..storage.cache import AtxCache, AtxInfo
from ..storage.db import Database
from ..verify.farm import Lane, MembershipRequest, PostRequest, SigRequest
from .activation import commitment_of, nipost_challenge, post_challenge
from .poet import verify_membership

TOPIC_ATX_V2 = "ax2"


class HandlerV2:
    """Gossip/sync ingestion of merged ATXs."""

    def __init__(self, *, db: Database, cache: AtxCache,
                 verifier: EdVerifier, golden_atx: bytes,
                 post_params: ProofParams, labels_per_unit: int,
                 scrypt_n: int, pubsub=None, on_atx=None, now=None,
                 farm=None):
        import time as _time

        self.now = now or _time.time
        self.db = db
        self.cache = cache
        self.verifier = verifier
        self.golden_atx = golden_atx
        self.post_params = post_params
        self.labels_per_unit = labels_per_unit
        self.scrypt_n = scrypt_n
        self.on_atx = on_atx
        # verification farm (verify/farm.py); None = inline verification
        self.farm = farm
        if pubsub is not None:
            pubsub.register(TOPIC_ATX_V2, self._gossip)

    async def _gossip(self, peer: bytes, data: bytes) -> bool:
        try:
            atx2 = ActivationTxV2.from_bytes(data)
        except (codec.DecodeError, ValueError):
            return False
        return await self.process_async(atx2, lane=Lane.GOSSIP)

    def _married_to_primary(self, atx2: ActivationTxV2) -> set[bytes]:
        """Identities allowed inside this envelope: the primary, partners
        certified IN this ATX, and previously recorded marriages."""
        allowed = {atx2.node_id}
        for cert in atx2.marriages:
            allowed.add(cert.partner_id)
        recorded = miscstore.marriage_of(self.db, atx2.node_id)
        if recorded is not None:
            allowed.update(miscstore.married_set(self.db, recorded))
        return allowed

    # NOTE: process() and process_async() are the same validation
    # sequence — sync/inline vs farm-batched (the per-subpost structure
    # is shared via _subpost_prepare). tests/test_atx_v2.py::
    # test_process_async_parity_with_inline pins their decisions to
    # each other; edit them together.

    def _equivocates(self, sp, atx2: ActivationTxV2) -> bool:
        """Per-identity double-publish guard (marks malicious on hit)."""
        existing = atxstore.by_node_in_epoch(self.db, sp.node_id,
                                             atx2.publish_epoch)
        if existing is not None and \
                existing.id != atx2.identity_atx_id(sp.node_id):
            self.cache.set_malicious(sp.node_id)
            return True
        return False

    def _subpost_prepare(self, sp, atx2: ActivationTxV2):
        """Structural per-subpost validation shared by both paths:
        double-publish guard, poet lookup, VerifyItem + height math.
        Returns (poet, challenge, item, prev_height) or None to reject.
        Membership + POST verification stay with the caller (inline vs
        farm-batched)."""
        if self._equivocates(sp, atx2):
            return None
        poet = miscstore.poet_proof(self.db,
                                    sp.nipost.post_metadata.challenge)
        if poet is None:
            return None
        challenge = nipost_challenge(sp.prev_atx, atx2.publish_epoch)
        item = post_verifier.VerifyItem(
            proof=PostProof(nonce=sp.nipost.post.nonce,
                            indices=list(sp.nipost.post.indices),
                            pow_nonce=sp.nipost.post.pow_nonce,
                            k2=self.post_params.k2),
            challenge=post_challenge(poet.root, challenge),
            node_id=sp.node_id,
            commitment=commitment_of(sp.node_id, self.golden_atx),
            scrypt_n=self.scrypt_n,
            total_labels=sp.num_units * self.labels_per_unit)
        prev_height = 0
        if sp.prev_atx != EMPTY32:
            prev_height = atxstore.tick_height(self.db, sp.prev_atx) or 0
        return poet, challenge, item, prev_height

    def process(self, atx2: ActivationTxV2) -> bool:
        if not atx2.subposts:
            return False
        if atxstore.has(self.db,
                        atx2.identity_atx_id(atx2.subposts[0].node_id)):
            return True
        # envelope signature by the primary
        if not self.verifier.verify(Domain.ATX, atx2.node_id,
                                    atx2.signed_bytes(), atx2.signature):
            return False
        # marriage certificates: partner consent over "marry"||primary
        for cert in atx2.marriages:
            if not self.verifier.verify(
                    Domain.ATX, cert.partner_id,
                    MarriageCert.message(atx2.node_id), cert.signature):
                return False
        allowed = self._married_to_primary(atx2)
        seen_ids: set[bytes] = set()
        items: list[post_verifier.VerifyItem] = []
        ticks: dict[bytes, int] = {}
        heights: dict[bytes, tuple[int, int]] = {}
        for sp in atx2.subposts:
            if sp.node_id not in allowed or sp.node_id in seen_ids:
                return False
            seen_ids.add(sp.node_id)
            prep = self._subpost_prepare(sp, atx2)
            if prep is None:
                return False
            poet, challenge, item, prev_height = prep
            if not verify_membership(challenge, sp.nipost.membership,
                                     poet.root,
                                     leaf_count=self._leaf_count(poet)):
                return False
            items.append(item)
            ticks[sp.node_id] = prev_height + poet.ticks
            heights[sp.node_id] = (prev_height, poet.ticks)
        # ONE batched POST verification across every covered identity
        if not all(post_verifier.verify_many(items, self.post_params)):
            return False
        self._store(atx2, ticks, heights)
        return True

    async def process_async(self, atx2: ActivationTxV2,
                            lane: Lane = Lane.GOSSIP) -> bool:
        """process(), with every crypto check routed through the farm —
        a merged ATX's subposts batch not just with each other but with
        every OTHER in-flight ATX's proofs. Falls back to the inline
        path when no farm runs."""
        if self.farm is None:
            return self.process(atx2)
        if not atx2.subposts:
            return False
        if atxstore.has(self.db,
                        atx2.identity_atx_id(atx2.subposts[0].node_id)):
            return True
        if not await self.farm.submit(
                SigRequest(int(Domain.ATX), atx2.node_id,
                           atx2.signed_bytes(), atx2.signature), lane=lane):
            return False
        for cert in atx2.marriages:
            if not await self.farm.submit(
                    SigRequest(int(Domain.ATX), cert.partner_id,
                               MarriageCert.message(atx2.node_id),
                               cert.signature), lane=lane):
                return False
        allowed = self._married_to_primary(atx2)
        seen_ids: set[bytes] = set()
        items: list[post_verifier.VerifyItem] = []
        ticks: dict[bytes, int] = {}
        heights: dict[bytes, tuple[int, int]] = {}
        for sp in atx2.subposts:
            if sp.node_id not in allowed or sp.node_id in seen_ids:
                return False
            seen_ids.add(sp.node_id)
            prep = self._subpost_prepare(sp, atx2)
            if prep is None:
                return False
            poet, challenge, item, prev_height = prep
            if not await self.farm.submit(
                    MembershipRequest(challenge, sp.nipost.membership,
                                      poet.root, self._leaf_count(poet)),
                    lane=lane):
                return False
            items.append(item)
            ticks[sp.node_id] = prev_height + poet.ticks
            heights[sp.node_id] = (prev_height, poet.ticks)
        verdicts = await asyncio.gather(
            *(self.farm.submit(PostRequest(it), lane=lane)
              for it in items))
        if not all(verdicts):
            return False
        # re-run the double-publish guard with NO awaits before the
        # store: a conflicting envelope may have landed while the crypto
        # checks above coalesced in the farm (the sync path can't
        # interleave, so only this path needs the recheck)
        for sp in atx2.subposts:
            if self._equivocates(sp, atx2):
                return False
        self._store(atx2, ticks, heights)
        return True

    def _leaf_count(self, poet) -> int:
        from .activation import poet_leaf_count

        return poet_leaf_count(self.db, poet)

    def _store(self, atx2: ActivationTxV2, ticks: dict,
               heights: dict) -> None:
        with self.db.tx():
            atxstore.add_v2(self.db, atx2, tick_heights=ticks,
                            received=self.now())
            # record the equivocation set: everyone in the envelope is
            # married to everyone else via this ATX
            if atx2.marriages:
                for sp in atx2.subposts:
                    miscstore.set_marriage(self.db, sp.node_id, atx2.id)
                miscstore.set_marriage(self.db, atx2.node_id, atx2.id)
        for sp in atx2.subposts:
            prev_height, tick_delta = heights[sp.node_id]
            self.cache.add(
                atx2.target_epoch(), atx2.identity_atx_id(sp.node_id),
                AtxInfo(node_id=sp.node_id,
                        weight=sp.num_units * tick_delta,
                        base_height=prev_height,
                        height=ticks[sp.node_id],
                        num_units=sp.num_units, vrf_nonce=sp.vrf_nonce,
                        vrf_public_key=sp.node_id))
        if self.on_atx:
            self.on_atx(atx2)


def build_marriage_cert(partner: EdSigner, primary_id: bytes) -> MarriageCert:
    return MarriageCert(
        partner_id=partner.node_id,
        signature=partner.sign(Domain.ATX, MarriageCert.message(primary_id)))


async def build_merged_atx(*, primary: EdSigner, partners: list[EdSigner],
                           db: Database, poet, post_clients: dict,
                           golden_atx: bytes, coinbase: bytes,
                           publish_epoch: int,
                           execute_round: bool = False) -> ActivationTxV2:
    """Build one merged ATX covering primary + partners (reference
    activation.Builder v2 path): every identity registers its challenge,
    one poet round serves all, every identity proves POST over the same
    statement, partners sign marriage certificates."""
    import asyncio

    signers = [primary] + partners
    round_id = str(publish_epoch)
    challenges = {}
    for s in signers:
        prev = atxstore.latest_by_node(db, s.node_id)
        prev_id = prev.id if prev is not None else EMPTY32
        ch = nipost_challenge(prev_id, publish_epoch)
        challenges[s.node_id] = (prev_id, ch)
        await poet.register(round_id, ch)
    if execute_round:
        result = await poet.execute_round(round_id)
    else:
        while (result := await asyncio.to_thread(poet.result,
                                                 round_id)) is None:
            # spacecheck: ok=SC001 off-loop poll pacing, not a protocol delay; elapses instantly in virtual time
            await asyncio.sleep(0.05)

    from .activation import store_poet_blob
    from .poet import PoetBlob

    store_poet_blob(db, PoetBlob(proof=result.proof,
                                 member_count=len(result.members)))

    subposts = []
    for s in signers:
        prev_id, ch = challenges[s.node_id]
        membership = result.membership(ch)
        if membership is None:
            raise RuntimeError("challenge missing from poet round")
        client = post_clients[s.node_id]
        proof, meta = await asyncio.to_thread(
            client.proof, post_challenge(result.proof.root, ch))
        info = await asyncio.to_thread(client.info)
        subposts.append(SubPostV2(
            node_id=s.node_id, prev_atx=prev_id,
            num_units=info.num_units, vrf_nonce=info.vrf_nonce,
            nipost=NIPost(
                membership=membership,
                post=Post(nonce=proof.nonce, indices=proof.indices,
                          pow_nonce=proof.pow_nonce),
                post_metadata=PostMetadataWire(
                    challenge=result.proof.id,
                    labels_per_unit=info.labels_per_unit))))

    atx2 = ActivationTxV2(
        publish_epoch=publish_epoch, pos_atx=golden_atx, coinbase=coinbase,
        marriages=[build_marriage_cert(p, primary.node_id)
                   for p in partners],
        subposts=subposts, node_id=primary.node_id, signature=bytes(64))
    return dataclasses.replace(
        atx2, signature=primary.sign(Domain.ATX, atx2.signed_bytes()))
