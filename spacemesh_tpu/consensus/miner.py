"""Miner: the proposal builder.

Mirrors reference miner/proposal_builder.go: on each layer tick, for each
registered signer, compute the VRF eligibility slots landing in this layer
(:482 initSignerData), select txs from the conservative state, encode
tortoise votes, assemble + sign + publish the Proposal (:549 build). The
first ballot of an epoch carries EpochData (beacon + active-set root);
later ballots reference it.

Also the proposal gossip handler (reference proposals/handler.go):
validates incoming ballots (signature, slot eligibility via the oracle),
stores the proposal, and feeds the ballot to the tortoise with its
eligibility weight.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ..utils import logging as slog

from ..core import codec
from ..core.hashing import sum256
from ..core.signing import Domain, EdSigner, EdVerifier
from ..core.types import (
    EMPTY32,
    Ballot,
    EpochData,
    Proposal,
    VotingEligibility,
)
from ..p2p.pubsub import TOPIC_PROPOSAL, PubSub
from ..storage import atxs as atxstore
from ..storage import ballots as ballotstore
from ..storage.cache import AtxCache
from ..storage.db import Database
from ..txs import ConservativeState
from .eligibility import Oracle
from .mesh import ProposalStore
from .tortoise import Tortoise

_log = slog.get("miner")

MAX_TXS_PER_PROPOSAL = 700


class _BadBeacon(str):
    """Truthy sentinel: ballot ingested but its beacon mismatches ours."""


BAD_BEACON = _BadBeacon("bad-beacon")


# single definition of the set-commitment hash (consensus/activeset.py);
# re-exported under the historical name
from .activeset import active_set_hash as active_set_root  # noqa: E402


class ProposalBuilder:
    def __init__(self, *, signer: EdSigner, db: Database, cache: AtxCache,
                 oracle: Oracle, tortoise: Tortoise,
                 cstate: ConservativeState, pubsub: PubSub,
                 layers_per_epoch: int, beacon_getter,
                 activeset_gen=None):
        self.signer = signer
        self.db = db
        self.cache = cache
        self.oracle = oracle
        self.tortoise = tortoise
        self.cstate = cstate
        self.pubsub = pubsub
        self.layers_per_epoch = layers_per_epoch
        self.beacon_getter = beacon_getter
        # graded three-path generator (consensus/activeset.py); falls back
        # to the full atxsdata view when it can't produce a set yet
        self.activeset_gen = activeset_gen

    def own_atx(self, epoch: int) -> Optional[bytes]:
        for atx_id, info in self.cache.iter_epoch(epoch):
            if info.node_id == self.signer.node_id:
                return atx_id
        return None

    async def build(self, layer: int) -> Optional[Proposal]:
        epoch = layer // self.layers_per_epoch
        atx_id = self.own_atx(epoch)
        if atx_id is None:
            return None
        # never double-mine a layer: a second (different) ballot in the
        # same layer is self-equivocation (reference proposal builder
        # skips layers it already built for; guards restarts and clock
        # anomalies like --genesis-now replays)
        if ballotstore.by_node_in_layer(self.db, self.signer.node_id, layer):
            return None
        beacon = await self.beacon_getter(epoch)
        vrf = self.signer.vrf_signer()

        # resolve the active set this ballot DECLARES first — slot counts
        # must be computed against that set's weight, matching what
        # validators recompute (activeset.declared_set_weight); otherwise
        # a builder whose local ATX view runs ahead of its declared set
        # would claim slot indices validators reject
        epoch_start = epoch * self.layers_per_epoch
        ref = ballotstore.refballot(self.db, self.signer.node_id,
                                    epoch_start, epoch_start + self.layers_per_epoch)
        epoch_data = None
        ref_id = EMPTY32
        from .activeset import declared_set_weight
        from ..storage import misc as miscstore
        if ref is None:
            active = None
            if self.activeset_gen is not None:
                try:
                    _, _, active = self.activeset_gen.generate(layer, epoch)
                except LookupError:
                    active = None
            if active is None:
                active = [a for a, _ in self.cache.iter_epoch(epoch)]
            root = active_set_root(active)
            miscstore.add_active_set(self.db, root, epoch, sorted(active))
            declared_total = declared_set_weight(
                self.db, self.cache, epoch, root) \
                if self.oracle.trusts_declared(epoch) else None
            epoch_data = EpochData(
                beacon=beacon, active_set_root=root,
                eligibility_count=self.oracle.num_slots(epoch, atx_id,
                                                        declared_total))
        else:
            ref_id = ref.id
            declared_total = None
            if ref.epoch_data is not None \
                    and self.oracle.trusts_declared(epoch):
                declared_total = declared_set_weight(
                    self.db, self.cache, epoch,
                    ref.epoch_data.active_set_root)

        slots = self.oracle.eligible_slots_for_layer(
            vrf, beacon, epoch, atx_id, layer, declared_total)
        if not slots:
            return None

        ballot = Ballot(
            layer=layer, atx_id=atx_id, epoch_data=epoch_data,
            ref_ballot=ref_id,
            eligibilities=[VotingEligibility(j=j, sig=proof)
                           for j, proof in slots],
            opinion=self.tortoise.encode_votes(layer),
            node_id=self.signer.node_id, signature=bytes(64))
        ballot = dataclasses.replace(
            ballot,
            signature=self.signer.sign(Domain.BALLOT, ballot.signed_bytes()))
        proposal = Proposal(
            ballot=ballot,
            tx_ids=self.cstate.select_proposal_txs(MAX_TXS_PER_PROPOSAL),
            mesh_hash=bytes(32), signature=bytes(64))
        proposal = dataclasses.replace(
            proposal, signature=self.signer.sign(Domain.BALLOT,
                                                 proposal.signed_bytes()))
        await self.pubsub.publish(TOPIC_PROPOSAL, proposal.to_bytes())
        return proposal


class ProposalHandler:
    def __init__(self, *, db: Database, cache: AtxCache, oracle: Oracle,
                 tortoise: Tortoise, store: ProposalStore,
                 verifier: EdVerifier, pubsub: PubSub,
                 layers_per_epoch: int, beacon_getter,
                 on_malfeasance=None, farm=None):
        self.db = db
        self.cache = cache
        self.oracle = oracle
        self.tortoise = tortoise
        self.store = store
        self.verifier = verifier
        # verification farm (verify/farm.py); None = inline verification
        self.farm = farm
        self.layers_per_epoch = layers_per_epoch
        self.beacon_getter = beacon_getter
        self.on_malfeasance = on_malfeasance
        # async root -> bool; wired to fetch.get_hashes(HINT_ACTIVESET)
        # once the network starts (app.start_network) — a ballot's
        # declared active set must be FETCHABLE, not just locally
        # resolvable, or validators fall back to their local epoch
        # weight and disagree with the builder (code-review r5)
        self.fetch_active_set = None
        # async ballot_id -> bool; wired to HINT_BALLOT fetch — a
        # secondary ballot arriving before its ref ballot must fetch it,
        # not be permanently rejected by delivery order
        self.fetch_ballot = None
        pubsub.register(TOPIC_PROPOSAL, self._gossip)

    async def _gossip(self, peer: bytes, data: bytes) -> bool:
        try:
            proposal = Proposal.from_bytes(data)
        except (codec.DecodeError, ValueError):
            return False
        return await self.process(proposal)

    async def _declared_set_weight(self, epoch: int, epoch_data
                                   ) -> int | None:
        """Weight of the active set the ballot DECLARES (via its own or
        its ref ballot's EpochData.active_set_root) — see
        activeset.declared_set_weight. On a local miss the set is
        fetched from peers (content-addressed by its root) before
        falling back to the local epoch weight."""
        from .activeset import declared_set_weight

        if epoch_data is None:
            return None
        root = epoch_data.active_set_root
        total = declared_set_weight(self.db, self.cache, epoch, root)
        if total is None and self.fetch_active_set is not None:
            try:
                await self.fetch_active_set(root)
            except Exception:
                return None
            total = declared_set_weight(self.db, self.cache, epoch, root)
        return total

    async def _verify_sig(self, public_key: bytes, msg: bytes, sig: bytes,
                          lane) -> bool:
        """Ballot-domain signature check, farm-batched when a farm runs
        (verify/farm.py), inline otherwise — same verdict either way."""
        if self.farm is not None:
            from ..verify.farm import SigRequest

            return await self.farm.submit(
                SigRequest(int(Domain.BALLOT), public_key, msg, sig),
                lane=lane)
        return self.verifier.verify(Domain.BALLOT, public_key, msg, sig)

    async def ingest_ballot(self, ballot, lane=None) -> bool:
        """Full ballot validation + store + tortoise feed. ONE path for
        gossip proposals and synced ballots — sync must not be a weaker
        copy of the gossip checks (sync callers pass lane=Lane.SYNC so
        backfill floods queue behind live gossip in the farm). Returns
        False (rejected), True (ingested), or BAD_BEACON (ingested,
        truthy, but the ballot's beacon mismatches ours — its proposal
        must not feed hare)."""
        from ..verify.farm import Lane

        lane = Lane.GOSSIP if lane is None else lane
        if not await self._verify_sig(ballot.node_id, ballot.signed_bytes(),
                                      ballot.signature, lane):
            return False
        epoch = ballot.layer // self.layers_per_epoch
        info = self.cache.get(epoch, ballot.atx_id)
        if info is None or info.node_id != ballot.node_id:
            return False
        # eligibility verifies against the ballot's DECLARED beacon (its
        # own EpochData, or its ref ballot's) — reference
        # proposals/handler + miner/oracle semantics. A beacon MISMATCH
        # with our epoch beacon doesn't reject the ballot: it is
        # ingested with bad_beacon=True and its tortoise votes are
        # delayed (tortoise.go BadBeaconVoteDelayLayers), so the
        # majority chain's ballots survive a local beacon divergence
        # while a grinding adversary can't steer margins immediately.
        local_beacon = await self.beacon_getter(epoch)
        trusted = self.oracle.trusts_declared(epoch)
        if ballot.epoch_data is not None:
            # REF ballot: the smesher's first of the epoch. Its
            # eligibility count is computed from the DECLARED active
            # set's weight and checked against the declared count —
            # exactly ONCE per (smesher, epoch); every later ballot
            # reuses the validated number (reference
            # eligibility_validator.go validateReference).
            epoch_data = ballot.epoch_data
            declared_total = await self._declared_set_weight(
                epoch, epoch_data) if trusted else None
            if trusted and declared_total is None:
                # an unresolvable declared set must REJECT, not fall
                # back: skipping the count check would store an
                # attacker-chosen eligibility_count that every later
                # secondary ballot (and restart recovery) trusts as its
                # slot bound (code-review r5; reference
                # validateReference errors when the set can't be
                # resolved — sync redelivers once it is fetchable)
                return False
            bound = self.oracle.num_slots(epoch, ballot.atx_id,
                                          declared_total)
            if trusted and epoch_data.eligibility_count != bound:
                return False
        else:
            # SECONDARY ballot: must share smesher AND atx with its ref
            # ballot, whose validated eligibility count bounds j. A
            # missing ref is fetched (gossip order must not decide
            # validity — code-review r5), then the ballot is dropped if
            # still unresolvable; sync redelivers in layer order.
            ref = ballotstore.get(self.db, ballot.ref_ballot)
            if ref is None and self.fetch_ballot is not None:
                try:
                    await self.fetch_ballot(ballot.ref_ballot)
                except Exception as e:  # noqa: BLE001 — a failed fetch
                    # only delays validation (sync redelivers in layer
                    # order); log it so a systematically failing peer
                    # set is visible (spacecheck SC006)
                    _log.debug("ref-ballot fetch failed for %s: %r",
                               ballot.ref_ballot.hex()[:12], e)
                ref = ballotstore.get(self.db, ballot.ref_ballot)
            epoch_data = ballotstore.resolve_epoch_data(
                self.db, ballot, self.layers_per_epoch)
            if epoch_data is None:
                return False
            bound = epoch_data.eligibility_count if trusted \
                else self.oracle.num_slots(epoch, ballot.atx_id)
        beacon = epoch_data.beacon
        bad_beacon = beacon != local_beacon
        for el in ballot.eligibilities:
            if not self.oracle.validate_slot(beacon, epoch, ballot.atx_id,
                                             ballot.layer, el.j, el.sig,
                                             num_slots_override=bound):
                return False
        # double ballot in one (layer, signer) slot set -> malfeasance
        existing = ballotstore.by_node_in_layer(self.db, ballot.node_id,
                                                ballot.layer)
        for other in existing:
            if other.id != ballot.id:
                self.cache.set_malicious(ballot.node_id)
                if self.on_malfeasance:
                    self.on_malfeasance(ballot.node_id, other, ballot)
                return False
        with self.db.tx():
            ballotstore.add(self.db, ballot)
        unit = info.weight // max(bound, 1)
        self.tortoise.on_ballot(ballot, unit * len(ballot.eligibilities),
                                bad_beacon=bad_beacon)
        return True if not bad_beacon else BAD_BEACON

    async def process(self, proposal: Proposal) -> bool:
        from ..verify.farm import Lane

        ballot = proposal.ballot
        if not await self._verify_sig(ballot.node_id,
                                      proposal.signed_bytes(),
                                      proposal.signature, Lane.GOSSIP):
            return False
        ok = await self.ingest_ballot(ballot)
        if not ok:
            return False
        if ok is not BAD_BEACON:
            # only good-beacon proposals feed hare's candidate pool —
            # a ground beacon must not buy hare influence (reference:
            # hare only counts proposals matching the local beacon)
            self.store.add(proposal)
        return True
