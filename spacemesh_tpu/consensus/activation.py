"""Activation: building and validating ATXs (the identity/weight layer).

Mirrors the reference activation package (SURVEY.md §2.2): the Builder is
each smesher's per-epoch loop — POST init once, then per epoch: register
the NIPoST challenge at the poet, wait out the round, prove over the poet
statement with the POST prover, assemble + sign + publish the ATX
(reference activation/activation.go:421 run, nipost.go:188 BuildNIPost).
The Handler ingests gossip/sync ATXs: signature, poet membership, POST
proof verification (through post/verifier.py — the TPU-vmapped path),
then store + cache + consensus notifications
(reference activation/handler.go:189).

Commitment derivation: commitment = blake3(node_id || golden_atx)
binding the label set to the identity and chain genesis.
NIPoST challenge for epoch E: blake3(prev_atx_id or zeros || le32(E)).
POST challenge: blake3(poet_root || nipost_challenge).
"""

from __future__ import annotations

import asyncio
import dataclasses
import struct
from typing import Awaitable, Callable, Optional

from ..core import codec
from ..core.hashing import sum256
from ..core.signing import Domain, EdSigner, EdVerifier
from ..core.types import (
    EMPTY32,
    ActivationTx,
    MerkleProof,
    NIPost,
    PoetProof,
    Post,
    PostMetadataWire,
)
from ..p2p.pubsub import TOPIC_ATX, PubSub
from ..post.prover import Proof as PostProof, ProofParams
from ..post import verifier as post_verifier
from ..storage import atxs as atxstore
from ..storage import misc as miscstore
from ..storage.cache import AtxCache, AtxInfo
from ..storage.db import Database
from ..verify.farm import Lane, MembershipRequest, PostRequest, SigRequest
from .poet import PoetService, verify_membership


def commitment_of(node_id: bytes, golden_atx: bytes) -> bytes:
    return sum256(node_id, golden_atx)


def store_poet_blob(db: Database, blob) -> None:
    """Persist a poet proof + its member count (single writer for the two
    rows the validator reads: proof by ref, count by derived key)."""
    proof = blob.proof
    with db.tx():
        # first write wins: member_count is not covered by proof.id, so a
        # re-gossiped blob with a forged count must not overwrite the
        # count recorded when the proof first arrived
        db.exec(
            "INSERT OR IGNORE INTO poet_proofs (ref, poet_id, round_id,"
            " ticks, data) VALUES (?,?,?,?,?)",
            (proof.id, proof.poet_id, proof.round_id, proof.ticks,
             proof.to_bytes()))
        db.exec(
            "INSERT OR IGNORE INTO active_sets (id, epoch, data)"
            " VALUES (?,?,?)",
            (b"poetcnt!" + proof.id[:24],
             int(proof.round_id) if proof.round_id.isdigit() else 0,
             blob.member_count.to_bytes(8, "little")))


def nipost_challenge(prev_atx: bytes, epoch: int) -> bytes:
    return sum256(prev_atx, struct.pack("<I", epoch))


def poet_leaf_count(db: Database, poet: PoetProof) -> int:
    """Member count recorded beside the proof (store_poet_blob); unknown
    counts are bounded above — membership still binds."""
    row = db.one("SELECT data FROM active_sets WHERE id=?",
                 (b"poetcnt!" + poet.id[:24],))
    if row is None:
        return 1 << 20
    return int.from_bytes(row["data"], "little")


def post_challenge(poet_root: bytes, challenge: bytes) -> bytes:
    return sum256(poet_root, challenge)


class Handler:
    """Gossip/sync ATX ingestion + validation."""

    def __init__(self, *, db: Database, cache: AtxCache, verifier: EdVerifier,
                 golden_atx: bytes, post_params: ProofParams,
                 labels_per_unit: int, scrypt_n: int, pubsub: PubSub,
                 on_atx: Optional[Callable[[ActivationTx], None]] = None,
                 now: Optional[Callable[[], float]] = None,
                 farm=None):
        import time as _time

        self.now = now or _time.time  # the NODE's clock domain: receipt
        # times must be comparable to the layer clock (virtual in tests)
        self.db = db
        self.cache = cache
        self.verifier = verifier
        self.golden_atx = golden_atx
        self.post_params = post_params
        self.labels_per_unit = labels_per_unit
        self.scrypt_n = scrypt_n
        self.on_atx = on_atx
        # verification farm (verify/farm.py); None = synchronous inline
        # verification, the contract unit tests and tools rely on
        self.farm = farm
        pubsub.register(TOPIC_ATX, self._gossip)

    async def _gossip(self, peer: bytes, data: bytes) -> bool:
        try:
            atx = ActivationTx.from_bytes(data)
        except (codec.DecodeError, ValueError):
            return False
        return await self.process_async(atx, lane=Lane.GOSSIP)

    # NOTE: process() and process_async() are the same validation
    # sequence — sync/inline vs farm-batched. tests/test_atx_v2.py::
    # test_v1_process_async_parity_with_inline pins their decisions to
    # each other; edit them together.

    def process(self, atx: ActivationTx) -> bool:
        if atxstore.has(self.db, atx.id):
            return True
        if not self.verifier.verify(Domain.ATX, atx.node_id,
                                    atx.signed_bytes(), atx.signature):
            return False
        # VRF key must BE the identity: ed25519 and the ECVRF suite share
        # the same seed->pubkey derivation, so an honest smesher's VRF key
        # equals its node id (signing.EdSigner.vrf_signer). Accepting an
        # arbitrary signed key would let a smesher grind fresh VRF keys
        # per epoch to bias beacon/eligibility draws (reference keys VRF
        # verification by the node id itself, signing/vrf.go NewPublicKey).
        if atx.vrf_public_key != atx.node_id:
            return False
        # poet proof must be known and the challenge a member of its round
        poet = miscstore.poet_proof(self.db, atx.nipost.post_metadata.challenge)
        if poet is None:
            return False
        challenge = nipost_challenge(atx.prev_atx, atx.publish_epoch)
        if not verify_membership(challenge, atx.nipost.membership, poet.root,
                                 leaf_count=self._leaf_count(poet)):
            return False
        # POST proof: recompute labels at spot-checked indices
        if not post_verifier.verify(self._verify_item(atx, poet, challenge),
                                    self.post_params):
            return False
        return self._finish(atx, poet)

    async def process_async(self, atx: ActivationTx,
                            lane: Lane = Lane.GOSSIP) -> bool:
        """process(), with every crypto check routed through the farm's
        micro-batches; falls back to the inline path when no farm runs."""
        if self.farm is None:
            return self.process(atx)
        if atxstore.has(self.db, atx.id):
            return True
        if not await self.farm.submit(
                SigRequest(int(Domain.ATX), atx.node_id,
                           atx.signed_bytes(), atx.signature), lane=lane):
            return False
        if atx.vrf_public_key != atx.node_id:
            return False
        poet = miscstore.poet_proof(self.db, atx.nipost.post_metadata.challenge)
        if poet is None:
            return False
        challenge = nipost_challenge(atx.prev_atx, atx.publish_epoch)
        if not await self.farm.submit(
                MembershipRequest(challenge, atx.nipost.membership,
                                  poet.root, self._leaf_count(poet)),
                lane=lane):
            return False
        if not await self.farm.submit(
                PostRequest(self._verify_item(atx, poet, challenge)),
                lane=lane):
            return False
        return self._finish(atx, poet)

    def _verify_item(self, atx: ActivationTx, poet,
                     challenge: bytes) -> post_verifier.VerifyItem:
        return post_verifier.VerifyItem(
            proof=PostProof(nonce=atx.nipost.post.nonce,
                            indices=list(atx.nipost.post.indices),
                            pow_nonce=atx.nipost.post.pow_nonce,
                            k2=self.post_params.k2),
            challenge=post_challenge(poet.root, challenge),
            node_id=atx.node_id,
            commitment=commitment_of(atx.node_id, self.golden_atx),
            scrypt_n=self.scrypt_n,
            total_labels=atx.num_units * self.labels_per_unit)

    def _finish(self, atx: ActivationTx, poet) -> bool:
        # double-publish detection (same node, same epoch, different atx)
        existing = atxstore.by_node_in_epoch(self.db, atx.node_id,
                                             atx.publish_epoch)
        if existing is not None and existing.id != atx.id:
            self.cache.set_malicious(atx.node_id)
            return False
        self.store(atx, ticks=poet.ticks)
        return True

    def _leaf_count(self, poet: PoetProof) -> int:
        return poet_leaf_count(self.db, poet)

    def store(self, atx: ActivationTx, ticks: int) -> None:
        prev_height = 0
        if atx.prev_atx != EMPTY32:
            prev_height = atxstore.tick_height(self.db, atx.prev_atx) or 0
        height = prev_height + ticks
        with self.db.tx():
            # receipt time feeds active-set grading
            # (consensus/activeset.py grade_atx)
            atxstore.add(self.db, atx, tick_height=height,
                         received=self.now())
        self.cache.add(atx.target_epoch(), atx.id, AtxInfo(
            node_id=atx.node_id, weight=atx.num_units * ticks,
            base_height=prev_height, height=height, num_units=atx.num_units,
            vrf_nonce=atx.vrf_nonce, vrf_public_key=atx.vrf_public_key))
        if self.on_atx:
            self.on_atx(atx)


class Builder:
    """One smesher's ATX publication loop (single-shot per epoch; the app
    drives it at epoch boundaries). Multi-identity: one Builder per signer,
    as the reference registers many signers into one builder."""

    def __init__(self, *, signer: EdSigner, db: Database, pubsub: PubSub,
                 poet: PoetService, post_client, golden_atx: bytes,
                 coinbase: bytes, handler: Handler,
                 num_units: int):
        self.signer = signer
        self.db = db
        self.pubsub = pubsub
        self.poet = poet
        self.post_client = post_client   # post.service.PostClient
        self.golden_atx = golden_atx
        self.coinbase = coinbase
        self.handler = handler
        self.num_units = num_units

    async def register_challenge(self, publish_epoch: int) -> None:
        """Phase 0: register the NIPoST challenge at the poet BEFORE the
        round starts (reference nipost.go:349 submitPoetChallenges). Split
        from finish() so a multi-identity node registers every signer
        before any of them executes/awaits the round."""
        node_id = self.signer.node_id
        prev = atxstore.latest_by_node(self.db, node_id)
        prev_id = prev.id if prev is not None else EMPTY32
        challenge = nipost_challenge(prev_id, publish_epoch)
        round_id = str(publish_epoch)
        self._pending = (publish_epoch, prev, prev_id, challenge, round_id)
        # cert-gated poets (reference certifier deposits,
        # activation/certifier.go:246): poet_cert is obtained once from
        # the certifier (App.start_smeshing, poet_certifier config); the
        # registration is bound to this identity by a POET-domain
        # signature over (round_id, challenge)
        cert = getattr(self, "poet_cert", None)
        await self.poet.register(
            round_id, challenge, node_id=node_id,
            signature=self.signer.sign(Domain.POET,
                                       round_id.encode() + challenge),
            cert=cert)

    async def build_and_publish(self, publish_epoch: int,
                                execute_round: bool = False) -> ActivationTx:
        """One NIPoST cycle for ``publish_epoch``.

        Standalone mode sets execute_round=True: this node drives the poet
        round itself (reference launchStandalone runs an in-proc poet).
        """
        await self.register_challenge(publish_epoch)
        return await self.finish(publish_epoch, execute_round)

    async def finish(self, publish_epoch: int,
                     execute_round: bool = False) -> ActivationTx:
        """Phases 1-2: await the poet round, prove POST over its statement,
        assemble + sign + publish the ATX."""
        pending = getattr(self, "_pending", None)
        if pending is None or pending[0] != publish_epoch:
            raise RuntimeError("register_challenge was not called")
        _, prev, prev_id, challenge, round_id = pending
        node_id = self.signer.node_id

        # phase 1: poet round runs (await its result)
        if execute_round:
            result = await self.poet.execute_round(round_id)
        else:
            # result() may do blocking I/O (remote poet) — poll off-loop
            while (result := await asyncio.to_thread(
                    self.poet.result, round_id)) is None:
                # spacecheck: ok=SC001 off-loop poll pacing, not a protocol delay; elapses instantly in virtual time
                await asyncio.sleep(0.05)
        membership = result.membership(challenge)
        if membership is None:
            raise RuntimeError("challenge missing from poet round")
        # persist + gossip the poet proof so every node can validate the
        # ATXs that reference this round (reference gossips poet proofs)
        proof = result.proof
        from ..p2p.pubsub import TOPIC_POET
        from .poet import PoetBlob

        blob = PoetBlob(proof=proof, member_count=len(result.members))
        store_poet_blob(self.db, blob)
        await self.pubsub.publish(TOPIC_POET, blob.to_bytes())

        # phase 2: POST proof over the poet statement
        ch = post_challenge(proof.root, challenge)
        post_proof, meta = await asyncio.to_thread(self.post_client.proof, ch)
        # off-loop: remote clients (JSON-RPC or the gRPC Register stream)
        # block on IO and must never run on the event loop itself
        info = await asyncio.to_thread(self.post_client.info)

        atx = ActivationTx(
            publish_epoch=publish_epoch,
            prev_atx=prev_id,
            pos_atx=prev_id if prev is not None else self.golden_atx,
            commitment_atx=(commitment_of(node_id, self.golden_atx)
                            if prev is None else None),
            initial_post=None,
            nipost=NIPost(
                membership=membership,
                post=Post(nonce=post_proof.nonce,
                          indices=post_proof.indices,
                          pow_nonce=post_proof.pow_nonce),
                post_metadata=PostMetadataWire(
                    challenge=proof.id,
                    labels_per_unit=info.labels_per_unit)),
            num_units=info.num_units,
            vrf_nonce=info.vrf_nonce,
            vrf_public_key=self.signer.vrf_signer().public_key,
            coinbase=self.coinbase,
            node_id=node_id,
            signature=bytes(64))
        atx = dataclasses.replace(
            atx, signature=self.signer.sign(Domain.ATX, atx.signed_bytes()))
        await self.pubsub.publish(TOPIC_ATX, atx.to_bytes())
        return atx
