"""VRF eligibility oracle: proposal slots and hare committees.

Mirrors the reference's two oracles:

- miner slots (reference miner/proposal_builder.go:482 initSignerData +
  proposals/eligibility_validator.go): an ATX of weight w gets
  ceil(w * slots_per_epoch / W_total) proposal eligibilities per epoch;
  slot j's VRF output places it in a layer of the epoch.
- hare committee (reference hare3/eligibility/oracle.go:344
  CalcEligibility): per (layer, round), an identity's seat count is a
  binomial sample — ``weight`` Bernoulli trials at p = committee/W_total,
  drawn by inverse CDF at the VRF output's uniform fraction
  (core/fixedpoint.py); the eligibility proof is the VRF signature,
  verifiable by anyone (oracle.go:297 Validate).

VRF message shapes (domain-separated through the VRF alpha):
  proposal slot:  "PROP" || beacon || epoch u32 || j u32
  hare round:     "HARE" || beacon || layer u32 || round u8
"""

from __future__ import annotations

import struct

from ..core import fixedpoint
from ..core.signing import VrfVerifier, vrf_output
from ..storage.cache import AtxCache


def proposal_alpha(beacon: bytes, epoch: int, j: int) -> bytes:
    return b"PROP" + beacon + struct.pack("<II", epoch, j)


def hare_alpha(beacon: bytes, layer: int, round_: int) -> bytes:
    return b"HARE" + beacon + struct.pack("<IB", layer, round_)


class Oracle:
    def __init__(self, cache: AtxCache, layers_per_epoch: int,
                 slots_per_layer: int = 50,
                 min_weight_table: list[tuple[int, int]] | None = None):
        self.cache = cache
        self.layers_per_epoch = layers_per_epoch
        self.slots_per_layer = slots_per_layer
        # (epoch, weight) ascending — reference miner/minweight table,
        # wired from config (mainnet.go MinimalActiveSetWeight)
        self.min_weight_table = min_weight_table or []
        self._vrf = VrfVerifier()

    # --- proposal eligibility -----------------------------------------

    def trusts_declared(self, epoch: int) -> bool:
        """Whether declared-active-set denominators are honored for this
        epoch. Only on networks with a NONZERO consensus min-weight
        floor: the floor is what stops an attacker from declaring a
        dust set (e.g. only their own ATX) and collecting the whole
        epoch's slot allotment — num_eligible_slots divides by
        max(floor, declared). With floor == 0 that defense is absent,
        so the declared total is ignored and eligibility falls back to
        the validator's local epoch weight (code-review r5; reference
        config/mainnet.go MinimalActiveSetWeight is nonzero from
        genesis for the same reason)."""
        from .activeset import select_min_weight

        return select_min_weight(epoch, self.min_weight_table) > 0

    def num_slots(self, epoch: int, atx_id: bytes,
                  total_override: int | None = None) -> int:
        """Proposal slots for this ATX in the epoch: weight-proportional
        with the epoch min-weight floor in the denominator
        (proposals/util/util.go:29-39 + miner/minweight Select) — the
        gating that stops dust identities from harvesting outsized slot
        counts on young or shrunken networks.

        ``total_override`` carries the weight of the active set DECLARED
        by the ballot under validation (reference validates against the
        ref ballot's declared set, not the local view — ADVICE r4: nodes
        with divergent ATX views must not disagree on ballot validity
        when the declared set is resolvable). The min-weight table is a
        CONSENSUS parameter: it enters this denominator, so it must
        match network-wide (like genesis config)."""
        from .activeset import num_eligible_slots, select_min_weight

        info = self.cache.get(epoch, atx_id)
        if info is None or info.malicious:
            return 0
        if total_override is not None and self.trusts_declared(epoch):
            total = total_override
        else:
            total = self.cache.epoch_weight(epoch)
        if total == 0:
            return 0
        return num_eligible_slots(
            info.weight, select_min_weight(epoch, self.min_weight_table),
            total, self.slots_per_layer, self.layers_per_epoch)

    def slot_layer(self, epoch: int, vrf_proof: bytes) -> int:
        """The layer (within the epoch) where a proposal slot lands."""
        out = vrf_output(vrf_proof)
        first = epoch * self.layers_per_epoch
        return first + int.from_bytes(out[8:16], "little") % self.layers_per_epoch

    def eligible_slots_for_layer(self, vrf_signer, beacon: bytes, epoch: int,
                                 atx_id: bytes, layer: int,
                                 total_override: int | None = None,
                                 ) -> list[tuple[int, bytes]]:
        """All (j, proof) proposal slots of this signer landing in ``layer``."""
        out = []
        for j in range(self.num_slots(epoch, atx_id, total_override)):
            proof = vrf_signer.prove(proposal_alpha(beacon, epoch, j))
            if self.slot_layer(epoch, proof) == layer:
                out.append((j, proof))
        return out

    def vrf_key(self, epoch: int, atx_id: bytes) -> bytes | None:
        info = self.cache.get(epoch, atx_id)
        return info.vrf_public_key if info else None

    def validate_slot(self, beacon: bytes, epoch: int, atx_id: bytes,
                      layer: int, j: int, proof: bytes,
                      total_override: int | None = None,
                      num_slots_override: int | None = None) -> bool:
        """``num_slots_override`` is the eligibility count already
        validated on the smesher's ref ballot — secondary ballots are
        bounded by THAT count, not a recomputation (reference
        eligibility_validator.go validateSecondary returns the ref
        ballot's stored EligibilityCount)."""
        info = self.cache.get(epoch, atx_id)
        if info is None or info.malicious:
            # the override must NOT bypass the malfeasance gate a
            # num_slots recomputation would apply — a detected
            # equivocator's later ballots lose eligibility immediately
            # (code-review r5)
            return False
        key = self.vrf_key(epoch, atx_id)
        bound = num_slots_override if num_slots_override is not None \
            else self.num_slots(epoch, atx_id, total_override)
        if key is None or j >= bound:
            return False
        if not self._vrf.verify(key, proposal_alpha(beacon, epoch, j), proof):
            return False
        return self.slot_layer(epoch, proof) == layer

    # --- hare committee ------------------------------------------------

    def _binomial_params(self, epoch: int, atx_id: bytes,
                         committee_size: int) -> tuple[int, int, int]:
        """(n_trials, p_num, p_den) of this identity's seat-count binomial:
        ``weight`` Bernoulli trials at p = committee / total_weight
        (reference oracle.go:271-292 prepareEligibilityCheck, including the
        committee>total rescale that keeps p <= 1)."""
        info = self.cache.get(epoch, atx_id)
        if info is None or info.malicious:
            return 0, 0, 1
        total = self.cache.epoch_weight(epoch)
        if total == 0:
            return 0, 0, 1
        n = info.weight
        if committee_size > total:
            n *= committee_size
            total *= committee_size
        return n, committee_size, total

    def _count_from_proof(self, proof: bytes, n: int, p_num: int,
                          p_den: int) -> int:
        """Seat count = inverse binomial CDF at the VRF output's uniform
        fraction (reference oracle.go:344-375 CalcEligibility via
        fixed.BinCDF) — both prover and validator compute the same count."""
        frac = fixedpoint.frac_from_bytes(vrf_output(proof))
        return fixedpoint.binomial_count(n, p_num, p_den, frac)

    def hare_eligibility(self, vrf_signer, beacon: bytes, layer: int,
                         round_: int, epoch: int, atx_id: bytes,
                         committee_size: int) -> tuple[bytes, int] | None:
        """(VRF proof, seat count) if on the committee, else None."""
        n, p_num, p_den = self._binomial_params(epoch, atx_id, committee_size)
        if n == 0 or p_num == 0:
            return None
        proof = vrf_signer.prove(hare_alpha(beacon, layer, round_))
        count = self._count_from_proof(proof, n, p_num, p_den)
        return (proof, count) if count > 0 else None

    def validate_hare(self, beacon: bytes, layer: int, round_: int,
                      epoch: int, atx_id: bytes, committee_size: int,
                      proof: bytes, claimed_count: int) -> bool:
        """Membership AND the claimed seat count must match the proof —
        the count is derived, never trusted (a forged count would multiply
        an attacker's vote weight). Equivalent to the reference's interval
        check BinCDF(n,p,x-1) <= vrfFrac < BinCDF(n,p,x) (oracle.go:324)."""
        key = self.vrf_key(epoch, atx_id)
        if key is None:
            return False
        if not self._vrf.verify(key, hare_alpha(beacon, layer, round_), proof):
            return False
        n, p_num, p_den = self._binomial_params(epoch, atx_id, committee_size)
        return (claimed_count > 0
                and claimed_count == self._count_from_proof(
                    proof, n, p_num, p_den))
