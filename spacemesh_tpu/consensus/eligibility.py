"""VRF eligibility oracle: proposal slots and hare committees.

Mirrors the reference's two oracles:

- miner slots (reference miner/proposal_builder.go:482 initSignerData +
  proposals/eligibility_validator.go): an ATX of weight w gets
  ceil(w * slots_per_epoch / W_total) proposal eligibilities per epoch;
  slot j's VRF output places it in a layer of the epoch.
- hare committee (reference hare3/eligibility/oracle.go:344
  CalcEligibility): per (layer, round), an identity is eligible with
  probability committee_size * w_i / W_total, decided by its VRF output;
  the eligibility proof is the VRF signature, verifiable by anyone
  (oracle.go:297 Validate).

VRF message shapes (domain-separated through the VRF alpha):
  proposal slot:  "PROP" || beacon || epoch u32 || j u32
  hare round:     "HARE" || beacon || layer u32 || round u8
"""

from __future__ import annotations

import struct

from ..core.signing import VrfVerifier, vrf_output
from ..storage.cache import AtxCache

FIXED = 1 << 52  # fixed-point scale for probability compare


def _frac_of_output(out: bytes) -> int:
    """Map a VRF output to a uniform fixed-point fraction in [0, FIXED)."""
    return int.from_bytes(out[:8], "little") % FIXED


def proposal_alpha(beacon: bytes, epoch: int, j: int) -> bytes:
    return b"PROP" + beacon + struct.pack("<II", epoch, j)


def hare_alpha(beacon: bytes, layer: int, round_: int) -> bytes:
    return b"HARE" + beacon + struct.pack("<IB", layer, round_)


class Oracle:
    def __init__(self, cache: AtxCache, layers_per_epoch: int,
                 slots_per_layer: int = 50):
        self.cache = cache
        self.layers_per_epoch = layers_per_epoch
        self.slots_per_layer = slots_per_layer
        self._vrf = VrfVerifier()

    # --- proposal eligibility -----------------------------------------

    def num_slots(self, epoch: int, atx_id: bytes) -> int:
        """Proposal slots for this ATX in the epoch (weight-proportional,
        minimum 1 for any active ATX)."""
        info = self.cache.get(epoch, atx_id)
        if info is None or info.malicious:
            return 0
        total = self.cache.epoch_weight(epoch)
        if total == 0:
            return 0
        slots_per_epoch = self.slots_per_layer * self.layers_per_epoch
        return max(1, info.weight * slots_per_epoch // total)

    def slot_layer(self, epoch: int, vrf_proof: bytes) -> int:
        """The layer (within the epoch) where a proposal slot lands."""
        out = vrf_output(vrf_proof)
        first = epoch * self.layers_per_epoch
        return first + int.from_bytes(out[8:16], "little") % self.layers_per_epoch

    def eligible_slots_for_layer(self, vrf_signer, beacon: bytes, epoch: int,
                                 atx_id: bytes, layer: int) -> list[tuple[int, bytes]]:
        """All (j, proof) proposal slots of this signer landing in ``layer``."""
        out = []
        for j in range(self.num_slots(epoch, atx_id)):
            proof = vrf_signer.prove(proposal_alpha(beacon, epoch, j))
            if self.slot_layer(epoch, proof) == layer:
                out.append((j, proof))
        return out

    def vrf_key(self, epoch: int, atx_id: bytes) -> bytes | None:
        info = self.cache.get(epoch, atx_id)
        return info.vrf_public_key if info else None

    def validate_slot(self, beacon: bytes, epoch: int, atx_id: bytes,
                      layer: int, j: int, proof: bytes) -> bool:
        key = self.vrf_key(epoch, atx_id)
        if key is None or j >= self.num_slots(epoch, atx_id):
            return False
        if not self._vrf.verify(key, proposal_alpha(beacon, epoch, j), proof):
            return False
        return self.slot_layer(epoch, proof) == layer

    # --- hare committee ------------------------------------------------

    def _expected_slots(self, epoch: int, atx_id: bytes,
                        committee_size: int) -> tuple[int, int]:
        """(whole slots, fractional part in FIXED) of this identity's
        expected committee seats: committee * w_i / W (the reference's
        binomial sampling by weight, oracle.go:344, in expectation)."""
        info = self.cache.get(epoch, atx_id)
        if info is None or info.malicious:
            return 0, 0
        total = self.cache.epoch_weight(epoch)
        if total == 0:
            return 0, 0
        whole = committee_size * info.weight // total
        frac = (committee_size * info.weight * FIXED // total) % FIXED
        return whole, frac

    def _count_from_proof(self, proof: bytes, whole: int, frac: int) -> int:
        """Deterministic seat count derived from the VRF output: the
        fractional expected seat materializes iff the uniform draw falls
        under it — both prover and validator compute the same count."""
        extra = 1 if _frac_of_output(vrf_output(proof)) < frac else 0
        return whole + extra

    def hare_eligibility(self, vrf_signer, beacon: bytes, layer: int,
                         round_: int, epoch: int, atx_id: bytes,
                         committee_size: int) -> tuple[bytes, int] | None:
        """(VRF proof, seat count) if on the committee, else None."""
        whole, frac = self._expected_slots(epoch, atx_id, committee_size)
        if whole == 0 and frac == 0:
            return None
        proof = vrf_signer.prove(hare_alpha(beacon, layer, round_))
        count = self._count_from_proof(proof, whole, frac)
        return (proof, count) if count > 0 else None

    def validate_hare(self, beacon: bytes, layer: int, round_: int,
                      epoch: int, atx_id: bytes, committee_size: int,
                      proof: bytes, claimed_count: int) -> bool:
        """Membership AND the claimed seat count must match the proof —
        the count is derived, never trusted (a forged count would multiply
        an attacker's vote weight)."""
        key = self.vrf_key(epoch, atx_id)
        if key is None:
            return False
        if not self._vrf.verify(key, hare_alpha(beacon, layer, round_), proof):
            return False
        whole, frac = self._expected_slots(epoch, atx_id, committee_size)
        return (claimed_count > 0
                and claimed_count == self._count_from_proof(proof, whole, frac))
