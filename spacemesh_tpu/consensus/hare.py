"""Hare: per-layer BFT agreement on the proposal set.

Mirrors the reference hare's role and message flow (reference hare4/: a
per-layer session of VRF-eligible committee members emitting a
ConsensusOutput of proposal ids consumed by the block generator,
hare4/hare.go:708; equivocation -> malfeasance).  Decisions come from the
PROVEN graded protocol core in ``hare3.py`` (reference hare3/protocol.go,
reused by hare4): graded-gossip, gradecast and thresh-gossip over the
8-round iteration

  preround | hardlock softlock propose wait1 wait2 commit notify | ...

Late or equivocating leaders are handled by GRADES (arrival delay vs. the
propose round, conflict-surfacing delay), not acceptance windows.  On the
WIRE only the four message rounds exist (preround/propose/commit/notify,
same encoding as before — commit/notify carry the full value set; the
protocol's reference hash is the values root).  Rounds are wall-clock
slots (round_duration) measured from the layer start, so honest nodes
move in lockstep like the reference's 25 s mainnet rounds; sessions are
driven concurrently with the layer loop because one session legitimately
outlives its layer (reference runs per-layer goroutines the same way).

On top of the proven core this implementation keeps NOTIFY commit
certificates: a NOTIFY must carry observed COMMIT messages proving the
threshold, so a bare keypair cannot fabricate agreement for gossip
consumers that missed the commits (a deliberate strengthening; the
reference relies on thresh-gossip alone)."""

from __future__ import annotations

import asyncio
import dataclasses
from typing import Awaitable, Callable, Optional

from ..core import codec
from ..core.codec import fixed, u8, u16, u32, vec
from ..core.signing import Domain, EdSigner, EdVerifier
from ..core.types import EMPTY32
from ..p2p.pubsub import TOPIC_HARE, PubSub
from . import hare3
from .eligibility import Oracle

# wire round tags (unchanged encoding); the protocol's internal 8-round
# structure maps onto these four message rounds
PREROUND, PROPOSE, COMMIT, NOTIFY = 0, 1, 2, 3

_WIRE_TO_PROTO = {PREROUND: hare3.PREROUND, PROPOSE: hare3.PROPOSE,
                  COMMIT: hare3.COMMIT, NOTIFY: hare3.NOTIFY}
_PROTO_TO_WIRE = {v: k for k, v in _WIRE_TO_PROTO.items()}


@codec.register
class HareMessage:
    layer: int
    iteration: int
    round: int
    values: list[bytes]          # proposal ids (sorted)
    eligibility_proof: bytes     # VRF
    eligibility_count: int
    atx_id: bytes
    node_id: bytes
    # NOTIFY only: the commit certificate — encoded COMMIT messages whose
    # summed seats reach the threshold (reference hare carries commit
    # certificates so nodes that missed the commits still accept)
    cert_msgs: list[bytes]
    signature: bytes

    FIELDS = [("layer", u32), ("iteration", u8), ("round", u8),
              ("values", vec(fixed(32), 1 << 12)),
              ("eligibility_proof", fixed(80)), ("eligibility_count", u16),
              ("atx_id", fixed(32)), ("node_id", fixed(32)),
              ("cert_msgs", vec(codec.var_bytes, 1 << 11)),
              ("signature", fixed(64))]

    def signed_bytes(self) -> bytes:
        return dataclasses.replace(self, signature=bytes(64)).to_bytes()


COMPACT_ID_SIZE = 4


def compact_id(full: bytes) -> bytes:
    return full[:COMPACT_ID_SIZE]


def values_root(values: list[bytes]) -> bytes:
    from ..core.hashing import sum256

    return sum256(*values) if values else bytes(32)


@codec.register
class CompactHareMessage:
    """hare4-style compaction (reference hare4/types.go + hare.go:328):
    messages carry 4-byte proposal-id prefixes plus a root over the full
    ids; receivers reconstruct from their proposal store and fall back to
    a full exchange (hf/1) with the delivering peer on a miss. The
    signature covers THIS compact form; the root binds the full values."""

    layer: int
    iteration: int
    round: int
    compact_ids: list[bytes]     # 4-byte prefixes of sorted proposal ids
    root: bytes                  # hash over the full sorted ids
    eligibility_proof: bytes
    eligibility_count: int
    atx_id: bytes
    node_id: bytes
    cert_msgs: list[bytes]       # NOTIFY: encoded COMPACT commit messages
    signature: bytes

    FIELDS = [("layer", u32), ("iteration", u8), ("round", u8),
              ("compact_ids", vec(fixed(COMPACT_ID_SIZE), 1 << 12)),
              ("root", fixed(32)),
              ("eligibility_proof", fixed(80)), ("eligibility_count", u16),
              ("atx_id", fixed(32)), ("node_id", fixed(32)),
              ("cert_msgs", vec(codec.var_bytes, 1 << 11)),
              ("signature", fixed(64))]

    def signed_bytes(self) -> bytes:
        return dataclasses.replace(self, signature=bytes(64)).to_bytes()


TOPIC_HARE_COMPACT = "b4"
P_FULL_EXCHANGE = "hf/1"   # (layer, iteration, round, node_id) -> full ids


@dataclasses.dataclass
class ConsensusOutput:
    layer: int
    proposals: list[bytes]       # agreed proposal ids (may be empty)
    # False when the session hit its iteration limit WITHOUT agreement:
    # the layer is undecided and belongs to the tortoise, which is a
    # different thing from hare positively agreeing on "empty"
    # (reference hare reports no output on failure; layerpatrol hands
    # the layer to the syncer/tortoise)
    completed: bool = True
    # weak coin for the layer: LSB of the lowest preround eligibility
    # VRF seen (reference hare weakcoin; tortoise healing tie-break)
    coin: Optional[bool] = None


@dataclasses.dataclass
class Equivocation:
    node_id: bytes
    msg1: bytes
    sig1: bytes
    msg2: bytes
    sig2: bytes


class HareSession:
    """One layer's protocol instance."""

    def __init__(self, hare: "Hare", layer: int, proposals: list[bytes]):
        self.h = hare
        self.layer = layer
        self.my_proposals = sorted(proposals)
        # the proven graded machine makes every decision (hare3.py)
        self.protocol = hare3.Protocol(
            hare.committee_for(layer) // 2 + 1)
        self.commits: dict[bytes, tuple[int, tuple]] = {}
        # (iteration, values) -> node_id -> (raw COMMIT, its own seat
        # count) — kept to assemble the NOTIFY commit certificate; the
        # count MUST come from the stored message, not the node's latest
        # commit (per-round VRF counts differ and receivers sum the raws)
        self.commit_raw: dict[tuple, dict[bytes, tuple[bytes, int]]] = {}
        self.output: Optional[list[bytes]] = None
        self.seen: dict[tuple, tuple[bytes, bytes]] = {}  # equivocation watch
        self.excluded: set[bytes] = set()  # equivocators (reporting dedup)

    # --- message handling ------------------------------------------

    def on_message(self, msg: HareMessage, raw_signed: bytes | None = None,
                   raw_full: bytes | None = None) -> bool:
        """Feed one validated wire message to the graded protocol; returns
        the graded-gossip relay decision.  ``raw_signed``/``raw_full``
        override the wire bytes used for the equivocation report and
        certificate assembly — compact-mode messages keep their COMPACT
        encoding (that's what signatures cover and certificates carry)."""
        from ..core.hashing import sum256
        from ..core.signing import vrf_output

        raw = raw_signed if raw_signed is not None else msg.signed_bytes()
        key = (msg.node_id, msg.iteration, msg.round)
        prev = self.seen.setdefault(key, (raw, msg.signature))
        sorted_values = sorted(msg.values)
        inp = hare3.Input(
            sender=msg.node_id,
            ir=hare3.IterRound(msg.iteration, _WIRE_TO_PROTO[msg.round]),
            eligibility_count=msg.eligibility_count,
            vrf=vrf_output(msg.eligibility_proof),
            msg_hash=sum256(raw),
            values=(sorted_values if msg.round in (PREROUND, PROPOSE)
                    else None),
            reference=(values_root(sorted_values)
                       if msg.round in (COMMIT, NOTIFY) else None))
        relay, equivocation = self.protocol.on_input(inp)
        if equivocation is not None and msg.node_id not in self.excluded:
            self.excluded.add(msg.node_id)  # report once per identity
            self.h._report_equivocation(msg.node_id, prev, raw,
                                        msg.signature)
        if not relay:
            return False
        if msg.round == COMMIT:
            # certificate bookkeeping only — weight DECISIONS live in the
            # graded protocol (hare3.Protocol)
            w = msg.eligibility_count
            self.commits[msg.node_id] = (w, tuple(sorted_values))
            self.commit_raw.setdefault(
                (msg.iteration, tuple(sorted_values)), {})[msg.node_id] = \
                (raw_full if raw_full is not None else msg.to_bytes(), w)
        return True

    def commit_weight(self, values: tuple) -> int:
        return sum(w for n, (w, v) in self.commits.items()
                   if v == values and n not in self.excluded)

    def build_certificate(self, iteration: int, values: tuple,
                          threshold: int) -> list[bytes]:
        """Enough observed COMMIT messages for ``values`` to prove the
        threshold was reached (carried in NOTIFY)."""
        raws = self.commit_raw.get((iteration, values), {})
        out, total = [], 0
        for node_id, (raw, w) in raws.items():
            if node_id in self.excluded:
                continue
            out.append(raw)
            total += w
            if total >= threshold:
                return out
        return out if total >= threshold else []


class Hare:
    def __init__(self, *, signer: EdSigner | None = None,
                 signers: list[EdSigner] | None = None,
                 verifier: EdVerifier,
                 oracle: Oracle, pubsub: PubSub, committee_size: int,
                 round_duration: float, iteration_limit: int,
                 layers_per_epoch: int,
                 beacon_of: Callable[[int], Awaitable[bytes]],
                 atx_for: Callable[[int, bytes], Optional[bytes]],
                 proposals_for: Callable[[int], list[bytes]],
                 on_output: Callable[[ConsensusOutput], Awaitable[None]],
                 on_equivocation=None, preround_delay: float = 0.0,
                 wall=None, compact: bool = False, server=None,
                 committee_upgrade: tuple[int, int] | None = None,
                 compact_enable_layer: int | None = None):
        """Multi-identity: every signer in ``signers`` participates with
        its own eligibility (reference hare iterates registered signers);
        atx_for(epoch, node_id) resolves each signer's ATX.

        ``compact=True`` switches sends to hare4-style 4-byte proposal-id
        prefixes + a values root (topic b4); receivers reconstruct from
        their proposal store and fall back to the hf/1 full exchange on
        ``server`` (reference hare4/hare.go:328 fetchFull)."""
        import time as _time

        self.signers = signers if signers is not None else [signer]
        self.verifier = verifier
        self.oracle = oracle
        self.pubsub = pubsub
        self.committee = committee_size
        self.round_duration = round_duration
        self.iteration_limit = iteration_limit
        self.preround_delay = preround_delay
        self.wall = wall or _time.time
        self.layers_per_epoch = layers_per_epoch
        self.beacon_of = beacon_of
        self.atx_for = atx_for
        self.proposals_for = proposals_for
        self.on_output = on_output
        self.on_equivocation = on_equivocation
        self.sessions: dict[int, HareSession] = {}
        # COMMIT messages already fully validated via gossip: their raw
        # bytes skip the crypto re-check inside NOTIFY certificates
        # (ECVRF verifies are the expensive part of cert validation)
        self._valid_commits: dict[bytes, None] = {}
        # messages for layers whose session hasn't started here yet — peers'
        # clocks are never perfectly aligned (reference buffers early
        # messages the same way)
        self._pending: dict[int, list] = {}  # (msg, raw_signed, raw_full)
        self._pending_cap = 1 << 10
        self.compact = compact
        # (layer, size): from that layer on the committee size switches
        # (reference hare4/hare.go:52 CommitteeUpgrade + :74 CommitteeFor)
        self.committee_upgrade = tuple(committee_upgrade) \
            if committee_upgrade else None
        # layer-gated plain->compact protocol switch (reference
        # node/node.go:915-943: hare3 serves layers below the hare4
        # enable layer, hare4 takes over from it)
        self.compact_enable_layer = compact_enable_layer
        self.server = server
        # full value lists we can serve over hf/1:
        # (layer, iteration, round, node_id) -> list of full ids
        self._full_values: dict[tuple, list[bytes]] = {}
        pubsub.register(TOPIC_HARE, self._gossip)
        if compact or compact_enable_layer is not None:
            pubsub.register(TOPIC_HARE_COMPACT, self._gossip_compact)
        if server is not None:
            server.register(P_FULL_EXCHANGE, self._serve_full)

    # --- per-layer protocol parameters ------------------------------

    def committee_for(self, layer: int) -> int:
        """Committee size for a layer (reference hare4/hare.go:73-78
        CommitteeFor: the upgrade takes effect at its layer)."""
        if self.committee_upgrade and layer >= self.committee_upgrade[0]:
            return self.committee_upgrade[1]
        return self.committee

    def compact_for(self, layer: int) -> bool:
        """Whether this layer speaks the compact (hare4) wire format."""
        if self.compact_enable_layer is not None:
            return layer >= self.compact_enable_layer
        return self.compact

    # --- gossip ingestion ------------------------------------------

    async def _gossip(self, peer: bytes, data: bytes) -> bool:
        try:
            msg = HareMessage.from_bytes(data)
        except (codec.DecodeError, ValueError):
            return False
        if not self.verifier.verify(Domain.HARE, msg.node_id,
                                    msg.signed_bytes(), msg.signature):
            return False
        epoch = msg.layer // self.layers_per_epoch
        beacon = await self.beacon_of(epoch)
        round_tag = msg.iteration * 4 + msg.round
        if not self.oracle.validate_hare(
                beacon, msg.layer, round_tag, epoch, msg.atx_id,
                self.committee_for(msg.layer), msg.eligibility_proof,
                msg.eligibility_count):
            return False
        if msg.round == COMMIT:
            self._remember_valid_commit(data)
        # NOTIFY must PROVE its commit threshold: a valid commit
        # certificate travels with it (reference hare certificates) — a
        # bare keypair cannot fabricate agreement
        if msg.round == NOTIFY and not await self._validate_cert(
                msg.layer, msg.iteration, values_root(sorted(msg.values)),
                msg.cert_msgs):
            return False
        return self._dispatch(msg)

    def _remember_valid_commit(self, raw: bytes) -> None:
        self._valid_commits[raw] = None
        if len(self._valid_commits) > (1 << 12):
            for k in list(self._valid_commits)[:1 << 10]:
                del self._valid_commits[k]

    def _dispatch(self, msg: HareMessage, raw_signed: bytes | None = None,
                  raw_full: bytes | None = None):
        """Graded-gossip relay decision: True = relay, None = accept but
        suppress relay (duplicate / post-equivocation copy) — NEVER False
        here, because the delivering peer did nothing wrong and must not
        be penalized for a duplicate (reference protocol.go:349-376)."""
        session = self.sessions.get(msg.layer)
        if session is not None:
            return True if session.on_message(msg, raw_signed, raw_full) \
                else None
        buf = self._pending.setdefault(msg.layer, [])
        if len(buf) < self._pending_cap:
            buf.append((msg, raw_signed, raw_full))
        return True  # not judged yet: let it propagate

    # --- compaction (reference hare4) -------------------------------

    async def _serve_full(self, peer: bytes, data: bytes) -> bytes:
        """hf/1: (layer u32, iteration u8, round u8, node_id 32) -> the
        full 32-byte proposal ids behind a compact message we hold."""
        import struct

        if len(data) != 4 + 1 + 1 + 32:
            return b""
        layer, iteration, round_ = struct.unpack_from("<IBB", data)
        node_id = data[6:38]
        fulls = self._full_values.get((layer, iteration, round_, node_id))
        return b"".join(fulls) if fulls else b""

    def _remember_full(self, key: tuple, values: list[bytes]) -> None:
        self._full_values[key] = list(values)
        if len(self._full_values) > (1 << 12):
            for k in list(self._full_values)[:1 << 10]:
                del self._full_values[k]

    async def _reconstruct(self, peer: bytes,
                           cm: "CompactHareMessage") -> list[bytes] | None:
        """Recover the full proposal ids behind a compact message: local
        proposal store first (prefix match + root check), then the full
        exchange with the delivering peer (reference hare4
        reconstructProposals + fetchFull)."""
        cached = self._full_values.get(
            (cm.layer, cm.iteration, cm.round, cm.node_id))
        if cached is not None and values_root(cached) == cm.root:
            return cached  # own sends / already reconstructed
        by_prefix = {compact_id(f): f
                     for f in self.proposals_for(cm.layer)}
        fulls = [by_prefix.get(c) for c in cm.compact_ids]
        if all(f is not None for f in fulls):
            candidate = sorted(fulls)
            if values_root(candidate) == cm.root:
                return candidate
        if self.server is None or peer not in self.server.peers():
            return None
        import struct

        try:
            resp = await self.server.request(
                peer, P_FULL_EXCHANGE,
                struct.pack("<IBB", cm.layer, cm.iteration, cm.round)
                + cm.node_id, timeout=5.0)
        except Exception:  # noqa: BLE001 — peer gone: reconstruction fails
            return None
        if len(resp) % 32:
            return None
        candidate = sorted(resp[i:i + 32] for i in range(0, len(resp), 32))
        if values_root(candidate) != cm.root:
            return None
        if [compact_id(f) for f in candidate] != list(cm.compact_ids):
            return None
        return candidate

    async def _gossip_compact(self, peer: bytes, data: bytes) -> bool:
        try:
            cm = CompactHareMessage.from_bytes(data)
        except (codec.DecodeError, ValueError):
            return False
        if not self.verifier.verify(Domain.HARE, cm.node_id,
                                    cm.signed_bytes(), cm.signature):
            return False
        epoch = cm.layer // self.layers_per_epoch
        beacon = await self.beacon_of(epoch)
        round_tag = cm.iteration * 4 + cm.round
        if not self.oracle.validate_hare(
                beacon, cm.layer, round_tag, epoch, cm.atx_id,
                self.committee_for(cm.layer), cm.eligibility_proof,
                cm.eligibility_count):
            return False
        if cm.round == NOTIFY and not await self._validate_cert(
                cm.layer, cm.iteration, cm.root, cm.cert_msgs):
            return False
        values = await self._reconstruct(peer, cm)
        if values is None:
            return False
        key = (cm.layer, cm.iteration, cm.round, cm.node_id)
        self._remember_full(key, values)  # we can now serve hf/1 ourselves
        if cm.round == COMMIT:
            self._remember_valid_commit(data)
        full = HareMessage(
            layer=cm.layer, iteration=cm.iteration, round=cm.round,
            values=values, eligibility_proof=cm.eligibility_proof,
            eligibility_count=cm.eligibility_count, atx_id=cm.atx_id,
            node_id=cm.node_id, cert_msgs=[], signature=cm.signature)
        return self._dispatch(full, raw_signed=cm.signed_bytes(),
                              raw_full=data)

    async def _validate_cert(self, layer: int, iteration: int,
                             expected_root: bytes,
                             cert_msgs: list[bytes]) -> bool:
        """ONE cert validator for both wire formats: every inner COMMIT
        (full or compact encoding) decodes, is signed,
        eligibility-validated for the same (layer, iteration), binds to
        the SAME value set (compared by values root — the canonical form
        both encodings share), senders distinct, summed seats reaching
        the commit threshold. Mixed networks therefore interoperate: a
        full-encoded commit can certify a compact NOTIFY and vice versa."""
        threshold = self.committee_for(layer) // 2 + 1
        epoch = layer // self.layers_per_epoch
        beacon = await self.beacon_of(epoch)
        total = 0
        senders: set[bytes] = set()
        for raw in cert_msgs:
            cm = None
            root = None
            for cls in (HareMessage, CompactHareMessage):
                try:
                    cm = cls.from_bytes(raw)
                    root = (cm.root if cls is CompactHareMessage
                            else values_root(sorted(cm.values)))
                    break
                except (codec.DecodeError, ValueError):
                    continue
            if cm is None:
                return False
            if (cm.round != COMMIT or cm.layer != layer
                    or cm.iteration != iteration
                    or root != expected_root
                    or cm.node_id in senders):
                return False
            if raw not in self._valid_commits:  # gossip-validated skip
                if not self.verifier.verify(Domain.HARE, cm.node_id,
                                            cm.signed_bytes(), cm.signature):
                    return False
                tag = cm.iteration * 4 + COMMIT
                if not self.oracle.validate_hare(
                        beacon, cm.layer, tag, epoch, cm.atx_id,
                        self.committee_for(cm.layer),
                        cm.eligibility_proof, cm.eligibility_count):
                    return False
                self._remember_valid_commit(raw)
            senders.add(cm.node_id)
            total += cm.eligibility_count
        return total >= threshold

    def _report_equivocation(self, node_id: bytes, prev,
                             raw_signed: bytes, signature: bytes) -> None:
        if self.on_equivocation:
            self.on_equivocation(Equivocation(
                node_id=node_id, msg1=prev[0], sig1=prev[1],
                msg2=raw_signed, sig2=signature))

    # --- session driving -------------------------------------------

    async def run_layer(self, layer: int,
                        layer_start: float | None = None) -> ConsensusOutput:
        """Run the full graded session for a layer.

        One protocol round per wall-clock slot, ABSOLUTE from
        ``layer_start`` (reference hare rounds are fixed slots within the
        layer): tick t fires at layer_start + preround_delay +
        t*round_duration, so nodes stay in lockstep however late their
        session code entered.  Sessions legitimately outlive their layer
        (8 rounds/iteration; the reference's mainnet sessions do too) —
        the caller runs them concurrently with the layer loop.
        """
        if layer_start is None:
            layer_start = self.wall()

        async def until_tick(t: int) -> None:
            target = (layer_start + self.preround_delay
                      + t * self.round_duration)
            delay = target - self.wall()
            if delay > 0:
                await asyncio.sleep(delay)

        epoch = layer // self.layers_per_epoch
        beacon = await self.beacon_of(epoch)
        # every local signer with an ATX participates with its own seats
        participants = [
            (s, s.vrf_signer(), atx)
            for s in self.signers
            if s is not None
            and (atx := self.atx_for(epoch, s.node_id)) is not None]
        session = HareSession(self, layer, [])
        self.sessions[layer] = session
        for msg, rs, rf in self._pending.pop(layer, ()):  # early arrivals
            session.on_message(msg, rs, rf)
        for stale in [x for x in self._pending if x < layer]:
            del self._pending[stale]

        # > half the committee seats. Seat counts are weight-derived (the
        # committee's total seats sum to ~committee_size network-wide), so
        # the same constant is safe for any network size — a lone smesher
        # with all the weight holds ~all committee seats itself.
        threshold = self.committee_for(layer) // 2 + 1
        protocol = session.protocol

        async def send(om: hare3.OutMessage) -> None:
            iteration, wire_round = om.ir.iter, _PROTO_TO_WIRE[om.ir.round]
            if om.values is not None:
                values = sorted(om.values)
            else:
                values = protocol.valid_proposals.get(om.reference)
                if values is None:
                    return  # nothing provable to carry on the wire
            cert: list[bytes] | None = None
            if wire_round == NOTIFY:
                # certificate strengthening: prove the commit threshold
                cert = session.build_certificate(iteration, tuple(values),
                                                 threshold)
                if not cert:
                    return  # we saw the threshold via grading but cannot
                    # prove it to cert-checking receivers yet
            round_tag = iteration * 4 + wire_round
            for signer, vrf, atx in participants:
                el = self.oracle.hare_eligibility(
                    vrf, beacon, layer, round_tag, epoch, atx,
                    self.committee_for(layer))
                if el is None:
                    continue
                proof, count = el
                if self.compact_for(layer):
                    cm = CompactHareMessage(
                        layer=layer, iteration=iteration, round=wire_round,
                        compact_ids=[compact_id(v) for v in values],
                        root=values_root(values),
                        eligibility_proof=proof, eligibility_count=count,
                        atx_id=atx, node_id=signer.node_id,
                        cert_msgs=list(cert or []), signature=bytes(64))
                    cm.signature = signer.sign(Domain.HARE,
                                               cm.signed_bytes())
                    self._remember_full(
                        (layer, iteration, wire_round, signer.node_id),
                        list(values))
                    await self.pubsub.publish(TOPIC_HARE_COMPACT,
                                              cm.to_bytes())
                    continue
                msg = HareMessage(
                    layer=layer, iteration=iteration, round=wire_round,
                    values=list(values), eligibility_proof=proof,
                    eligibility_count=count, atx_id=atx,
                    node_id=signer.node_id, cert_msgs=list(cert or []),
                    signature=bytes(64))
                msg.signature = signer.sign(Domain.HARE, msg.signed_bytes())
                await self.pubsub.publish(TOPIC_HARE, msg.to_bytes())

        # preround_delay gives proposals time to build + propagate
        # (reference PreroundDelay); the proposal snapshot happens at the
        # preround SEND, not at session entry.
        await until_tick(0)
        session.my_proposals = sorted(self.proposals_for(layer))
        protocol.on_initial(session.my_proposals)

        result: Optional[list[bytes]] = None
        emitted: Optional[ConsensusOutput] = None
        coin: Optional[bool] = None
        tick = 0
        try:
            while True:
                out = protocol.next()
                if out.coin is not None:
                    coin = out.coin
                if out.result is not None and result is None:
                    result = out.result
                    session.output = list(result)
                    # deliver the moment agreement lands (block generation
                    # must not wait out the helper iteration)
                    emitted = ConsensusOutput(layer=layer, proposals=result,
                                              completed=True, coin=coin)
                    await self.on_output(emitted)
                if out.message is not None:
                    await send(out.message)
                if out.terminated:
                    break  # result emitted + one helper iteration completed
                if protocol.current.iter >= self.iteration_limit \
                        and protocol.current.round > hare3.HARDLOCK:
                    # the hardlock of iteration `limit` was the last chance to
                    # surface a result from the final notify round
                    break
                tick += 1
                await until_tick(tick)

            if emitted is None:
                emitted = ConsensusOutput(layer=layer, proposals=[],
                                          completed=False, coin=coin)
                await self.on_output(emitted)
        finally:
            # exception or cancellation must not leak the session: a dead
            # session left in self.sessions would keep absorbing gossip
            # for this layer forever (code-review r3)
            self.sessions.pop(layer, None)
        return emitted
