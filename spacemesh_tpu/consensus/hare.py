"""Hare: per-layer BFT agreement on the proposal set.

Mirrors the reference hare's role and message flow (reference hare4/: a
per-layer session of VRF-eligible committee members running
preround -> [propose -> commit -> notify]* and emitting a ConsensusOutput
of proposal ids consumed by the block generator, hare4/hare.go:708; round
state machine hare4/protocol.go; equivocation -> malfeasance). The round
structure here is the classic hare:

  PREROUND  everyone eligible broadcasts its proposal-id set
  PROPOSE   the leader (lowest VRF output among round-eligible members)
            proposes the union of preround sets it saw
  COMMIT    members that accept the proposal commit to it
  NOTIFY    threshold weight of commits -> notify; threshold of notifies
            (or a valid commit certificate) -> output

Weights are eligibility counts; the threshold is > half the committee
size. Rounds are wall-clock slots within the layer (round_duration), so
all honest nodes move in lockstep like the reference's 700 ms rounds.
"""

from __future__ import annotations

import asyncio
import dataclasses
from typing import Awaitable, Callable, Optional

from ..core import codec
from ..core.codec import fixed, u8, u16, u32, vec
from ..core.signing import Domain, EdSigner, EdVerifier
from ..core.types import EMPTY32
from ..p2p.pubsub import TOPIC_HARE, PubSub
from .eligibility import Oracle

PREROUND, PROPOSE, COMMIT, NOTIFY = 0, 1, 2, 3


@codec.register
class HareMessage:
    layer: int
    iteration: int
    round: int
    values: list[bytes]          # proposal ids (sorted)
    eligibility_proof: bytes     # VRF
    eligibility_count: int
    atx_id: bytes
    node_id: bytes
    # NOTIFY only: the commit certificate — encoded COMMIT messages whose
    # summed seats reach the threshold (reference hare carries commit
    # certificates so nodes that missed the commits still accept)
    cert_msgs: list[bytes]
    signature: bytes

    FIELDS = [("layer", u32), ("iteration", u8), ("round", u8),
              ("values", vec(fixed(32), 1 << 12)),
              ("eligibility_proof", fixed(80)), ("eligibility_count", u16),
              ("atx_id", fixed(32)), ("node_id", fixed(32)),
              ("cert_msgs", vec(codec.var_bytes, 1 << 11)),
              ("signature", fixed(64))]

    def signed_bytes(self) -> bytes:
        return dataclasses.replace(self, signature=bytes(64)).to_bytes()


COMPACT_ID_SIZE = 4


def compact_id(full: bytes) -> bytes:
    return full[:COMPACT_ID_SIZE]


def values_root(values: list[bytes]) -> bytes:
    from ..core.hashing import sum256

    return sum256(*values) if values else bytes(32)


@codec.register
class CompactHareMessage:
    """hare4-style compaction (reference hare4/types.go + hare.go:328):
    messages carry 4-byte proposal-id prefixes plus a root over the full
    ids; receivers reconstruct from their proposal store and fall back to
    a full exchange (hf/1) with the delivering peer on a miss. The
    signature covers THIS compact form; the root binds the full values."""

    layer: int
    iteration: int
    round: int
    compact_ids: list[bytes]     # 4-byte prefixes of sorted proposal ids
    root: bytes                  # hash over the full sorted ids
    eligibility_proof: bytes
    eligibility_count: int
    atx_id: bytes
    node_id: bytes
    cert_msgs: list[bytes]       # NOTIFY: encoded COMPACT commit messages
    signature: bytes

    FIELDS = [("layer", u32), ("iteration", u8), ("round", u8),
              ("compact_ids", vec(fixed(COMPACT_ID_SIZE), 1 << 12)),
              ("root", fixed(32)),
              ("eligibility_proof", fixed(80)), ("eligibility_count", u16),
              ("atx_id", fixed(32)), ("node_id", fixed(32)),
              ("cert_msgs", vec(codec.var_bytes, 1 << 11)),
              ("signature", fixed(64))]

    def signed_bytes(self) -> bytes:
        return dataclasses.replace(self, signature=bytes(64)).to_bytes()


TOPIC_HARE_COMPACT = "b4"
P_FULL_EXCHANGE = "hf/1"   # (layer, iteration, round, node_id) -> full ids


@dataclasses.dataclass
class ConsensusOutput:
    layer: int
    proposals: list[bytes]       # agreed proposal ids (may be empty)
    # False when the session hit its iteration limit WITHOUT agreement:
    # the layer is undecided and belongs to the tortoise, which is a
    # different thing from hare positively agreeing on "empty"
    # (reference hare reports no output on failure; layerpatrol hands
    # the layer to the syncer/tortoise)
    completed: bool = True
    # weak coin for the layer: LSB of the lowest preround eligibility
    # VRF seen (reference hare weakcoin; tortoise healing tie-break)
    coin: Optional[bool] = None


@dataclasses.dataclass
class Equivocation:
    node_id: bytes
    msg1: bytes
    sig1: bytes
    msg2: bytes
    sig2: bytes


class HareSession:
    """One layer's protocol instance."""

    def __init__(self, hare: "Hare", layer: int, proposals: list[bytes]):
        self.h = hare
        self.layer = layer
        self.my_proposals = sorted(proposals)
        self.preround_sets: dict[bytes, tuple[int, list[bytes]]] = {}
        # iteration -> (vrf_output, values) of best PROPOSE; lowest VRF wins
        self._best_propose: dict[int, tuple[bytes, list[bytes]]] = {}
        self.commits: dict[bytes, tuple[int, tuple]] = {}
        # (iteration, values) -> node_id -> (raw COMMIT, its own seat
        # count) — kept to assemble the NOTIFY commit certificate; the
        # count MUST come from the stored message, not the node's latest
        # commit (per-round VRF counts differ and receivers sum the raws)
        self.commit_raw: dict[tuple, dict[bytes, tuple[bytes, int]]] = {}
        self.notifies: dict[bytes, tuple[int, tuple]] = {}
        self.output: Optional[list[bytes]] = None
        self.seen: dict[tuple, tuple[bytes, bytes]] = {}  # equivocation watch
        self.excluded: set[bytes] = set()  # equivocators: zero weight
        self.layer_start: float | None = None  # set when the driver runs
        self.coin_vrf: Optional[bytes] = None  # lowest preround VRF output

    # --- timing (grade windows) ------------------------------------

    def _slot_of(self, iteration: int, round_: int) -> int:
        base = {PREROUND: 0, PROPOSE: 1, COMMIT: 2, NOTIFY: 3}[round_]
        return 0 if round_ == PREROUND else base + 3 * iteration

    def too_late(self, msg: HareMessage) -> bool:
        """Acceptance window (the gradecast equivalent): COMMIT/NOTIFY
        messages count only within a few slots of their own round — a
        message that surfaces much later must not flip decisions. The
        window is deliberately wider than one slot: weights are read at
        fixed instants anyway (late arrivals cannot rewrite a past read,
        and late NOTIFYs are commit-certificate-backed so counting them
        in the grace pass is safe), while validation latency must not
        disqualify honest messages. PREROUND/PROPOSE stay open (their
        reads are one-shot, and late prerounds only help liveness)."""
        if self.layer_start is None or msg.round in (PREROUND, PROPOSE):
            return False
        slot = self._slot_of(msg.iteration, msg.round)
        deadline = (self.layer_start + self.h.preround_delay
                    + (slot + 4) * self.h.round_duration)
        return self.h.wall() > deadline

    # --- message handling ------------------------------------------

    def on_message(self, msg: HareMessage, raw_signed: bytes | None = None,
                   raw_full: bytes | None = None) -> None:
        """``raw_signed``/``raw_full`` override the wire bytes used for
        the equivocation watch and certificate assembly — compact-mode
        messages keep their COMPACT encoding (that's what signatures
        cover and what certificates must carry)."""
        key = (msg.node_id, msg.iteration, msg.round)
        prev = self.seen.get(key)
        raw = raw_signed if raw_signed is not None else msg.signed_bytes()
        if prev is not None and prev[0] != raw:
            # equivocator: report AND exclude its weight from every round
            self.excluded.add(msg.node_id)
            # report with the WIRE bytes the signature actually covers
            # (compact-mode signatures sign the compact encoding)
            self.h._report_equivocation(msg.node_id, prev, raw,
                                        msg.signature)
            return
        self.seen[key] = (raw, msg.signature)
        if msg.node_id in self.excluded or self.too_late(msg):
            return
        w = msg.eligibility_count
        if msg.round == PREROUND:
            self.preround_sets[msg.node_id] = (w, msg.values)
            # weak coin: lowest preround VRF output's LSB (reference
            # hare weakcoin — unforgeable, shared by every listener)
            from ..core.signing import vrf_output

            out = vrf_output(msg.eligibility_proof)
            if self.coin_vrf is None or out < self.coin_vrf:
                self.coin_vrf = out
        elif msg.round == PROPOSE:
            # leader = lowest VRF output among eligible proposers
            # (reference hare3 leader rule; ADVICE r1 — first-arrival was
            # adversary-steerable via gossip ordering)
            from ..core.signing import vrf_output

            out = vrf_output(msg.eligibility_proof)
            best = self._best_propose.get(msg.iteration)
            if best is None or out < best[0]:
                self._best_propose[msg.iteration] = (out, sorted(msg.values))
        elif msg.round == COMMIT:
            self.commits[msg.node_id] = (w, tuple(msg.values))
            self.commit_raw.setdefault(
                (msg.iteration, tuple(msg.values)), {})[msg.node_id] = \
                (raw_full if raw_full is not None else msg.to_bytes(), w)
        elif msg.round == NOTIFY:
            self.notifies[msg.node_id] = (w, tuple(msg.values))

    # --- round actions ---------------------------------------------

    def candidates(self) -> list[bytes]:
        union: set[bytes] = set(self.my_proposals)
        for node_id, (_, values) in self.preround_sets.items():
            if node_id not in self.excluded:
                union.update(values)
        return sorted(union)

    def commit_weight(self, values: tuple) -> int:
        return sum(w for n, (w, v) in self.commits.items()
                   if v == values and n not in self.excluded)

    def notify_weight(self, values: tuple) -> int:
        return sum(w for n, (w, v) in self.notifies.items()
                   if v == values and n not in self.excluded)

    def build_certificate(self, iteration: int, values: tuple,
                          threshold: int) -> list[bytes]:
        """Enough observed COMMIT messages for ``values`` to prove the
        threshold was reached (carried in NOTIFY)."""
        raws = self.commit_raw.get((iteration, values), {})
        out, total = [], 0
        for node_id, (raw, w) in raws.items():
            if node_id in self.excluded:
                continue
            out.append(raw)
            total += w
            if total >= threshold:
                return out
        return out if total >= threshold else []


class Hare:
    def __init__(self, *, signer: EdSigner | None = None,
                 signers: list[EdSigner] | None = None,
                 verifier: EdVerifier,
                 oracle: Oracle, pubsub: PubSub, committee_size: int,
                 round_duration: float, iteration_limit: int,
                 layers_per_epoch: int,
                 beacon_of: Callable[[int], Awaitable[bytes]],
                 atx_for: Callable[[int, bytes], Optional[bytes]],
                 proposals_for: Callable[[int], list[bytes]],
                 on_output: Callable[[ConsensusOutput], Awaitable[None]],
                 on_equivocation=None, preround_delay: float = 0.0,
                 wall=None, compact: bool = False, server=None):
        """Multi-identity: every signer in ``signers`` participates with
        its own eligibility (reference hare iterates registered signers);
        atx_for(epoch, node_id) resolves each signer's ATX.

        ``compact=True`` switches sends to hare4-style 4-byte proposal-id
        prefixes + a values root (topic b4); receivers reconstruct from
        their proposal store and fall back to the hf/1 full exchange on
        ``server`` (reference hare4/hare.go:328 fetchFull)."""
        import time as _time

        self.signers = signers if signers is not None else [signer]
        self.verifier = verifier
        self.oracle = oracle
        self.pubsub = pubsub
        self.committee = committee_size
        self.round_duration = round_duration
        self.iteration_limit = iteration_limit
        self.preround_delay = preround_delay
        self.wall = wall or _time.time
        self.layers_per_epoch = layers_per_epoch
        self.beacon_of = beacon_of
        self.atx_for = atx_for
        self.proposals_for = proposals_for
        self.on_output = on_output
        self.on_equivocation = on_equivocation
        self.sessions: dict[int, HareSession] = {}
        # COMMIT messages already fully validated via gossip: their raw
        # bytes skip the crypto re-check inside NOTIFY certificates
        # (ECVRF verifies are the expensive part of cert validation)
        self._valid_commits: dict[bytes, None] = {}
        # messages for layers whose session hasn't started here yet — peers'
        # clocks are never perfectly aligned (reference buffers early
        # messages the same way)
        self._pending: dict[int, list] = {}  # (msg, raw_signed, raw_full)
        self._pending_cap = 1 << 10
        self.compact = compact
        self.server = server
        # full value lists we can serve over hf/1:
        # (layer, iteration, round, node_id) -> list of full ids
        self._full_values: dict[tuple, list[bytes]] = {}
        pubsub.register(TOPIC_HARE, self._gossip)
        if compact:
            pubsub.register(TOPIC_HARE_COMPACT, self._gossip_compact)
        if server is not None:
            server.register(P_FULL_EXCHANGE, self._serve_full)

    # --- gossip ingestion ------------------------------------------

    async def _gossip(self, peer: bytes, data: bytes) -> bool:
        try:
            msg = HareMessage.from_bytes(data)
        except (codec.DecodeError, ValueError):
            return False
        if not self.verifier.verify(Domain.HARE, msg.node_id,
                                    msg.signed_bytes(), msg.signature):
            return False
        epoch = msg.layer // self.layers_per_epoch
        beacon = await self.beacon_of(epoch)
        round_tag = msg.iteration * 4 + msg.round
        if not self.oracle.validate_hare(
                beacon, msg.layer, round_tag, epoch, msg.atx_id,
                self.committee, msg.eligibility_proof,
                msg.eligibility_count):
            return False
        if msg.round == COMMIT:
            self._remember_valid_commit(data)
        # NOTIFY must PROVE its commit threshold: a valid commit
        # certificate travels with it (reference hare certificates) — a
        # bare keypair cannot fabricate agreement
        if msg.round == NOTIFY and not await self._validate_cert(
                msg.layer, msg.iteration, values_root(sorted(msg.values)),
                msg.cert_msgs):
            return False
        self._dispatch(msg)
        return True

    def _remember_valid_commit(self, raw: bytes) -> None:
        self._valid_commits[raw] = None
        if len(self._valid_commits) > (1 << 12):
            for k in list(self._valid_commits)[:1 << 10]:
                del self._valid_commits[k]

    def _dispatch(self, msg: HareMessage, raw_signed: bytes | None = None,
                  raw_full: bytes | None = None) -> None:
        session = self.sessions.get(msg.layer)
        if session is not None:
            session.on_message(msg, raw_signed, raw_full)
        else:
            buf = self._pending.setdefault(msg.layer, [])
            if len(buf) < self._pending_cap:
                buf.append((msg, raw_signed, raw_full))

    # --- compaction (reference hare4) -------------------------------

    async def _serve_full(self, peer: bytes, data: bytes) -> bytes:
        """hf/1: (layer u32, iteration u8, round u8, node_id 32) -> the
        full 32-byte proposal ids behind a compact message we hold."""
        import struct

        if len(data) != 4 + 1 + 1 + 32:
            return b""
        layer, iteration, round_ = struct.unpack_from("<IBB", data)
        node_id = data[6:38]
        fulls = self._full_values.get((layer, iteration, round_, node_id))
        return b"".join(fulls) if fulls else b""

    def _remember_full(self, key: tuple, values: list[bytes]) -> None:
        self._full_values[key] = list(values)
        if len(self._full_values) > (1 << 12):
            for k in list(self._full_values)[:1 << 10]:
                del self._full_values[k]

    async def _reconstruct(self, peer: bytes,
                           cm: "CompactHareMessage") -> list[bytes] | None:
        """Recover the full proposal ids behind a compact message: local
        proposal store first (prefix match + root check), then the full
        exchange with the delivering peer (reference hare4
        reconstructProposals + fetchFull)."""
        cached = self._full_values.get(
            (cm.layer, cm.iteration, cm.round, cm.node_id))
        if cached is not None and values_root(cached) == cm.root:
            return cached  # own sends / already reconstructed
        by_prefix = {compact_id(f): f
                     for f in self.proposals_for(cm.layer)}
        fulls = [by_prefix.get(c) for c in cm.compact_ids]
        if all(f is not None for f in fulls):
            candidate = sorted(fulls)
            if values_root(candidate) == cm.root:
                return candidate
        if self.server is None or peer not in self.server.peers():
            return None
        import struct

        try:
            resp = await self.server.request(
                peer, P_FULL_EXCHANGE,
                struct.pack("<IBB", cm.layer, cm.iteration, cm.round)
                + cm.node_id, timeout=5.0)
        except Exception:  # noqa: BLE001 — peer gone: reconstruction fails
            return None
        if len(resp) % 32:
            return None
        candidate = sorted(resp[i:i + 32] for i in range(0, len(resp), 32))
        if values_root(candidate) != cm.root:
            return None
        if [compact_id(f) for f in candidate] != list(cm.compact_ids):
            return None
        return candidate

    async def _gossip_compact(self, peer: bytes, data: bytes) -> bool:
        try:
            cm = CompactHareMessage.from_bytes(data)
        except (codec.DecodeError, ValueError):
            return False
        if not self.verifier.verify(Domain.HARE, cm.node_id,
                                    cm.signed_bytes(), cm.signature):
            return False
        epoch = cm.layer // self.layers_per_epoch
        beacon = await self.beacon_of(epoch)
        round_tag = cm.iteration * 4 + cm.round
        if not self.oracle.validate_hare(
                beacon, cm.layer, round_tag, epoch, cm.atx_id,
                self.committee, cm.eligibility_proof,
                cm.eligibility_count):
            return False
        if cm.round == NOTIFY and not await self._validate_cert(
                cm.layer, cm.iteration, cm.root, cm.cert_msgs):
            return False
        values = await self._reconstruct(peer, cm)
        if values is None:
            return False
        key = (cm.layer, cm.iteration, cm.round, cm.node_id)
        self._remember_full(key, values)  # we can now serve hf/1 ourselves
        if cm.round == COMMIT:
            self._remember_valid_commit(data)
        full = HareMessage(
            layer=cm.layer, iteration=cm.iteration, round=cm.round,
            values=values, eligibility_proof=cm.eligibility_proof,
            eligibility_count=cm.eligibility_count, atx_id=cm.atx_id,
            node_id=cm.node_id, cert_msgs=[], signature=cm.signature)
        self._dispatch(full, raw_signed=cm.signed_bytes(), raw_full=data)
        return True

    async def _validate_cert(self, layer: int, iteration: int,
                             expected_root: bytes,
                             cert_msgs: list[bytes]) -> bool:
        """ONE cert validator for both wire formats: every inner COMMIT
        (full or compact encoding) decodes, is signed,
        eligibility-validated for the same (layer, iteration), binds to
        the SAME value set (compared by values root — the canonical form
        both encodings share), senders distinct, summed seats reaching
        the commit threshold. Mixed networks therefore interoperate: a
        full-encoded commit can certify a compact NOTIFY and vice versa."""
        threshold = self.committee // 2 + 1
        epoch = layer // self.layers_per_epoch
        beacon = await self.beacon_of(epoch)
        total = 0
        senders: set[bytes] = set()
        for raw in cert_msgs:
            cm = None
            root = None
            for cls in (HareMessage, CompactHareMessage):
                try:
                    cm = cls.from_bytes(raw)
                    root = (cm.root if cls is CompactHareMessage
                            else values_root(sorted(cm.values)))
                    break
                except (codec.DecodeError, ValueError):
                    continue
            if cm is None:
                return False
            if (cm.round != COMMIT or cm.layer != layer
                    or cm.iteration != iteration
                    or root != expected_root
                    or cm.node_id in senders):
                return False
            if raw not in self._valid_commits:  # gossip-validated skip
                if not self.verifier.verify(Domain.HARE, cm.node_id,
                                            cm.signed_bytes(), cm.signature):
                    return False
                tag = cm.iteration * 4 + COMMIT
                if not self.oracle.validate_hare(
                        beacon, cm.layer, tag, epoch, cm.atx_id,
                        self.committee, cm.eligibility_proof,
                        cm.eligibility_count):
                    return False
                self._remember_valid_commit(raw)
            senders.add(cm.node_id)
            total += cm.eligibility_count
        return total >= threshold

    def _report_equivocation(self, node_id: bytes, prev,
                             raw_signed: bytes, signature: bytes) -> None:
        if self.on_equivocation:
            self.on_equivocation(Equivocation(
                node_id=node_id, msg1=prev[0], sig1=prev[1],
                msg2=raw_signed, sig2=signature))

    # --- session driving -------------------------------------------

    async def run_layer(self, layer: int,
                        layer_start: float | None = None) -> ConsensusOutput:
        """Run the full session for a layer.

        Rounds are ABSOLUTE wall-clock slots measured from ``layer_start``
        (reference hare rounds are fixed slots within the layer): slot k
        ends at layer_start + preround_delay + (k+1) * round_duration, so
        nodes stay in lockstep however late their session code entered —
        a node whose proposal build ran long still reads each round's
        messages at the same instant as its peers.
        """
        if layer_start is None:
            layer_start = self.wall()

        async def until_slot(k: int) -> None:
            target = (layer_start + self.preround_delay
                      + (k + 1) * self.round_duration)
            delay = target - self.wall()
            if delay > 0:
                await asyncio.sleep(delay)

        epoch = layer // self.layers_per_epoch
        beacon = await self.beacon_of(epoch)
        # every local signer with an ATX participates with its own seats
        participants = [
            (s, s.vrf_signer(), atx)
            for s in self.signers
            if s is not None
            and (atx := self.atx_for(epoch, s.node_id)) is not None]
        session = HareSession(self, layer, [])
        session.layer_start = layer_start
        self.sessions[layer] = session
        for msg, rs, rf in self._pending.pop(layer, ()):  # early arrivals
            session.on_message(msg, rs, rf)
        for stale in [x for x in self._pending if x < layer]:
            del self._pending[stale]

        # preround_delay gives proposals time to build + propagate
        # (reference PreroundDelay); the proposal snapshot happens at the
        # preround SEND, not at session entry. slot -1 ends exactly at
        # layer_start + preround_delay.
        await until_slot(-1)
        session.my_proposals = sorted(self.proposals_for(layer))

        async def maybe_send(iteration: int, round_: int, values: list[bytes],
                             cert: list[bytes] | None = None):
            round_tag = iteration * 4 + round_
            for signer, vrf, atx in participants:
                el = self.oracle.hare_eligibility(
                    vrf, beacon, layer, round_tag, epoch, atx, self.committee)
                if el is None:
                    continue
                proof, count = el
                full_values = sorted(values)
                if self.compact:
                    cm = CompactHareMessage(
                        layer=layer, iteration=iteration, round=round_,
                        compact_ids=[compact_id(v) for v in full_values],
                        root=values_root(full_values),
                        eligibility_proof=proof, eligibility_count=count,
                        atx_id=atx, node_id=signer.node_id,
                        cert_msgs=list(cert or []), signature=bytes(64))
                    cm.signature = signer.sign(Domain.HARE,
                                               cm.signed_bytes())
                    self._remember_full(
                        (layer, iteration, round_, signer.node_id),
                        full_values)
                    await self.pubsub.publish(TOPIC_HARE_COMPACT,
                                              cm.to_bytes())
                    continue
                msg = HareMessage(
                    layer=layer, iteration=iteration, round=round_,
                    values=full_values, eligibility_proof=proof,
                    eligibility_count=count, atx_id=atx,
                    node_id=signer.node_id, cert_msgs=list(cert or []),
                    signature=bytes(64))
                msg.signature = signer.sign(Domain.HARE, msg.signed_bytes())
                await self.pubsub.publish(TOPIC_HARE, msg.to_bytes())

        # > half the committee seats. Seat counts are weight-derived (the
        # committee's total seats sum to ~committee_size network-wide), so
        # the same constant is safe for any network size — a lone smesher
        # with all the weight holds ~all committee seats itself.
        threshold = self.committee // 2 + 1

        await maybe_send(0, PREROUND, session.my_proposals)
        await until_slot(0)

        for it in range(self.iteration_limit):
            # PROPOSE (leader: lowest VRF output among eligible proposers)
            await maybe_send(it, PROPOSE, session.candidates())
            await until_slot(1 + 3 * it)
            best = session._best_propose.get(it)
            proposal = best[1] if best else session.candidates()
            # COMMIT
            await maybe_send(it, COMMIT, proposal)
            await until_slot(2 + 3 * it)
            committed = tuple(sorted(proposal))
            have = session.commit_weight(committed)
            # NOTIFY happens if enough commit weight was observed — and it
            # carries the commit certificate PROVING that threshold
            if have >= threshold:
                cert = session.build_certificate(it, committed, threshold)
                if cert:
                    await maybe_send(it, NOTIFY, list(committed), cert=cert)
            await until_slot(3 + 3 * it)
            if session.notify_weight(committed) >= threshold:
                session.output = list(committed)
                break

        if session.output is None:
            # grace pass: NOTIFYs are certificate-backed, so if threshold
            # notify weight for ANY value set arrives a beat late, it is
            # still a safe output — better than wrongly concluding empty
            # while the rest of the network agreed
            await until_slot(3 + 3 * (self.iteration_limit - 1) + 1)
            for values in {v for _, v in session.notifies.values()}:
                if session.notify_weight(values) >= threshold:
                    session.output = list(values)
                    break

        out = ConsensusOutput(
            layer=layer, proposals=session.output or [],
            completed=session.output is not None,
            coin=(bool(session.coin_vrf[-1] & 1)
                  if session.coin_vrf is not None else None))
        await self.on_output(out)
        del self.sessions[layer]
        return out
