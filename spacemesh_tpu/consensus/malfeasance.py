"""Malfeasance: proofs of protocol violations + the gossip handler.

Mirrors the reference malfeasance package (reference malfeasance/handler.go:
proof types MultipleATXs / MultipleBallots / HareEquivocation with
per-domain validators registered from each package; on a valid proof the
identity is persisted as malicious and marked everywhere — tortoise, ATX
cache — and the proof regossiped; self-defense check skips proofs against
the local node unless real).

A proof here is two distinct signed messages from one identity in the same
protocol slot (core/types.MalfeasanceProof): domain picks the conflict rule.
"""

from __future__ import annotations

from typing import Awaitable, Callable, Optional

from ..core import codec
from ..core.signing import Domain, EdVerifier
from ..core.types import ActivationTx, Ballot, MalfeasanceProof
from ..p2p.pubsub import TOPIC_MALFEASANCE, PubSub
from ..storage import misc as miscstore
from ..storage.cache import AtxCache
from ..storage.db import Database


def proof_from_ballots(b1: Ballot, b2: Ballot) -> MalfeasanceProof:
    return MalfeasanceProof(
        domain=int(Domain.BALLOT), msg1=b1.signed_bytes(), sig1=b1.signature,
        msg2=b2.signed_bytes(), sig2=b2.signature, node_id=b1.node_id)


def proof_from_atxs(a1: ActivationTx, a2: ActivationTx) -> MalfeasanceProof:
    return MalfeasanceProof(
        domain=int(Domain.ATX), msg1=a1.signed_bytes(), sig1=a1.signature,
        msg2=a2.signed_bytes(), sig2=a2.signature, node_id=a1.node_id)


def proof_from_hare(node_id: bytes, msg1: bytes, sig1: bytes, msg2: bytes,
                    sig2: bytes) -> MalfeasanceProof:
    return MalfeasanceProof(domain=int(Domain.HARE), msg1=msg1, sig1=sig1,
                            msg2=msg2, sig2=sig2, node_id=node_id)


# non-signature domain tag: a single ATX whose POST proof carries an index
# that does not qualify (reference malfeasance/handler.go InvalidPostIndex)
DOMAIN_INVALID_POST = 100


def proof_invalid_post(atx: ActivationTx, index_pos: int) -> MalfeasanceProof:
    """msg1 = the signed ATX, msg2 = the offending index position."""
    return MalfeasanceProof(
        domain=DOMAIN_INVALID_POST, msg1=atx.signed_bytes(),
        sig1=atx.signature, msg2=index_pos.to_bytes(4, "little"),
        sig2=bytes(64), node_id=atx.node_id)


def _conflicting(domain: int, msg1: bytes, msg2: bytes) -> bool:
    """Domain rule: the two messages occupy the same protocol slot."""
    try:
        if domain == int(Domain.BALLOT):
            b1 = Ballot.from_bytes(msg1)
            b2 = Ballot.from_bytes(msg2)
            return b1.layer == b2.layer and b1.node_id == b2.node_id
        if domain == int(Domain.ATX):
            a1 = ActivationTx.from_bytes(msg1)
            a2 = ActivationTx.from_bytes(msg2)
            if a1.node_id != a2.node_id:
                return False
            # double publish in one epoch, OR two ATXs claiming the same
            # prev (InvalidPrevATX, reference malfeasance/handler.go:33-42
            # — a forked ATX chain)
            from ..core.types import EMPTY32

            return (a1.publish_epoch == a2.publish_epoch
                    or (a1.prev_atx == a2.prev_atx
                        and a1.prev_atx != EMPTY32))
        if domain == int(Domain.HARE):
            from .hare import CompactHareMessage, HareMessage

            def slot(raw: bytes):
                # both wire encodings are conflict-provable
                for cls in (HareMessage, CompactHareMessage):
                    try:
                        m = cls.from_bytes(raw)
                        return (m.layer, m.iteration, m.round, m.node_id)
                    except (codec.DecodeError, ValueError):
                        continue
                return None

            s1, s2 = slot(msg1), slot(msg2)
            return s1 is not None and s1 == s2
    except (codec.DecodeError, ValueError, TypeError):
        return False
    return False


class Handler:
    def __init__(self, *, db: Database, cache: AtxCache,
                 verifier: EdVerifier, pubsub: PubSub,
                 tortoise=None,
                 on_malicious: Optional[Callable[[bytes], None]] = None,
                 post_checker=None, farm=None):
        self.db = db
        self.cache = cache
        self.verifier = verifier
        self.pubsub = pubsub
        self.tortoise = tortoise
        self.on_malicious = on_malicious
        # post_checker(atx, index_pos) -> True when the ATX's POST index
        # at that position does NOT qualify (InvalidPostIndex validation;
        # wired by the node with its POST params)
        self.post_checker = post_checker
        # verification farm (verify/farm.py); None = inline verification
        self.farm = farm
        pubsub.register(TOPIC_MALFEASANCE, self._gossip)

    def validate(self, proof: MalfeasanceProof) -> bool:
        if proof.domain == DOMAIN_INVALID_POST:
            return self._validate_invalid_post(proof)
        if proof.msg1 == proof.msg2:
            return False
        dom = Domain(proof.domain) if proof.domain in set(Domain) else None
        if dom is None:
            return False
        if not (self.verifier.verify(dom, proof.node_id, proof.msg1, proof.sig1)
                and self.verifier.verify(dom, proof.node_id, proof.msg2,
                                         proof.sig2)):
            return False
        return _conflicting(proof.domain, proof.msg1, proof.msg2)

    def _validate_invalid_post(self, proof: MalfeasanceProof) -> bool:
        """The ATX really is signed by the accused AND the named POST
        index really fails the recompute (reference InvalidPostIndex)."""
        if self.post_checker is None:
            return False
        if not self.verifier.verify(Domain.ATX, proof.node_id, proof.msg1,
                                    proof.sig1):
            return False
        try:
            atx = ActivationTx.from_bytes(proof.msg1)
            index_pos = int.from_bytes(proof.msg2[:4], "little")
        except (codec.DecodeError, ValueError):
            return False
        if atx.node_id != proof.node_id:
            return False
        if index_pos >= len(atx.nipost.post.indices):
            return False
        return bool(self.post_checker(atx, index_pos))

    async def validate_async(self, proof: MalfeasanceProof, lane) -> bool:
        """validate(), with the signature pair farm-batched (the two
        checks of one proof dispatch concurrently, and batch with every
        other in-flight verification)."""
        from ..verify.farm import SigRequest

        if proof.domain == DOMAIN_INVALID_POST:
            # post_checker recomputes ONE label inline (k2=1) — cheap
            # enough that routing it through the farm buys nothing
            return self._validate_invalid_post(proof)
        if proof.msg1 == proof.msg2:
            return False
        dom = Domain(proof.domain) if proof.domain in set(Domain) else None
        if dom is None:
            return False
        import asyncio

        ok1, ok2 = await asyncio.gather(
            self.farm.submit(SigRequest(int(dom), proof.node_id,
                                        proof.msg1, proof.sig1), lane=lane),
            self.farm.submit(SigRequest(int(dom), proof.node_id,
                                        proof.msg2, proof.sig2), lane=lane))
        if not (ok1 and ok2):
            return False
        return _conflicting(proof.domain, proof.msg1, proof.msg2)

    def process(self, proof: MalfeasanceProof) -> bool:
        if miscstore.is_malicious(self.db, proof.node_id):
            return True  # already known; don't regossip storms
        if not self.validate(proof):
            return False
        return self._condemn(proof)

    async def process_async(self, proof: MalfeasanceProof,
                            lane=None) -> bool:
        """process() with farm-batched signature checks; inline when no
        farm runs (the sync-fallback contract, docs/VERIFY_FARM.md)."""
        if self.farm is None:
            return self.process(proof)
        from ..verify.farm import Lane

        lane = Lane.GOSSIP if lane is None else lane
        if miscstore.is_malicious(self.db, proof.node_id):
            return True
        if not await self.validate_async(proof, lane):
            return False
        return self._condemn(proof)

    def _condemn(self, proof: MalfeasanceProof) -> bool:
        # the whole equivocation set falls with any member (reference
        # married identities share fate, handler_v2.go/sql/marriage)
        condemned = [proof.node_id]
        marriage = miscstore.marriage_of(self.db, proof.node_id)
        if marriage is not None:
            condemned += [n for n in miscstore.married_set(self.db, marriage)
                          if n != proof.node_id]
        with self.db.tx():
            for node_id in condemned:
                miscstore.set_malicious(self.db, node_id, proof)
        for node_id in condemned:
            self.cache.set_malicious(node_id)
            if self.tortoise is not None:
                self.tortoise.on_malfeasance(node_id)
            if self.on_malicious:
                self.on_malicious(node_id)
        return True

    async def _gossip(self, peer: bytes, data: bytes) -> bool:
        try:
            proof = MalfeasanceProof.from_bytes(data)
        except (codec.DecodeError, ValueError):
            return False
        return await self.process_async(proof)

    async def publish(self, proof: MalfeasanceProof) -> None:
        if await self.process_async(proof):
            await self.pubsub.publish(TOPIC_MALFEASANCE, proof.to_bytes())
