"""The proven graded hare protocol: graded-gossip, gradecast, thresh-gossip.

A faithful re-implementation of the reference's proven protocol core
(reference hare3/protocol.go — the same machine hare4 reuses; round/grade
arithmetic hare3/types.go:43-75; Protocol 1 graded-gossip p.10, Protocol 2
gradecast p.13, Protocol 3 thresh-gossip p.15 of the hare3 paper).  Late
and equivocating leaders are handled by GRADES — how many rounds late a
message arrived and whether a conflicting copy surfaced in time — not by
acceptance windows.

The machine is PURE: no clock, no IO.  A driver advances it one round per
call to ``next()`` and feeds messages through ``on_input`` stamped with
the round they arrived in.  That makes every adversarial timing scenario
(late leader, grade-boundary equivocation) expressible as a deterministic
unit test, mirroring the reference's protocol_test.go.

Round layout per iteration (reference hare3/types.go:17):

  preround | hardlock softlock propose wait1 wait2 commit notify | ...

preround runs once (iteration 0 skips hardlock); wait1/wait2 exist so a
message's arrival delay maps onto meaningful grade boundaries
(grade = max(6 - delay, 0)).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

# round indices (reference hare3/types.go:25-32)
PREROUND, HARDLOCK, SOFTLOCK, PROPOSE, WAIT1, WAIT2, COMMIT, NOTIFY = \
    range(8)

GRADE0, GRADE1, GRADE2, GRADE3, GRADE4, GRADE5 = range(6)

ROUND_NAMES = ("preround", "hardlock", "softlock", "propose",
               "wait1", "wait2", "commit", "notify")


@dataclasses.dataclass(frozen=True, order=True)
class IterRound:
    iter: int
    round: int

    def absolute(self) -> int:
        # reference types.go:73: iter*notify + round
        return self.iter * NOTIFY + self.round

    def delay(self, since: "IterRound") -> int:
        if self.absolute() <= since.absolute():
            return 0
        d = self.absolute() - since.absolute()
        # iteration 0 skips hardlock (types.go:46-49)
        if since.iter == 0 and since.round == PREROUND and d != 0:
            d -= 1
        return d

    def grade(self, since: "IterRound") -> int:
        return max(6 - self.delay(since), GRADE0)

    def is_message_round(self) -> bool:
        return self.round in (PREROUND, PROPOSE, COMMIT, NOTIFY)

    def __str__(self) -> str:  # pragma: no cover — debug aid
        return f"{self.iter}/{ROUND_NAMES[self.round]}"


def values_ref(values: list[bytes]) -> bytes:
    """Canonical reference hash of a proposal set (reference
    CalcProposalHash32Presorted)."""
    from ..core.hashing import sum256

    return sum256(*sorted(values)) if values else bytes(32)


@dataclasses.dataclass
class Input:
    """One validated message entering the protocol.

    ``values`` for preround/propose; ``reference`` for commit/notify.
    ``atxgrade`` comes from the oracle — the legacy oracle grades every
    eligible message grade5 (reference legacy_oracle.go:25-44); the slot
    exists so the full atx-grading of the paper can plug in.
    """

    sender: bytes
    ir: IterRound
    eligibility_count: int
    vrf: bytes                         # eligibility proof (leader order, coin)
    msg_hash: bytes
    values: Optional[list[bytes]] = None
    reference: Optional[bytes] = None
    malicious: bool = False
    atxgrade: int = GRADE5

    def key(self) -> tuple:
        return (self.ir, self.sender)


@dataclasses.dataclass
class _GossipInput:
    inp: Input
    received: IterRound
    other_received: Optional[IterRound] = None


@dataclasses.dataclass
class Equivocation:
    """Two conflicting messages for one (iter, round, sender) — the raw
    material of a hare malfeasance proof (reference wire.HareProof)."""

    sender: bytes
    first_hash: bytes
    second_hash: bytes


@dataclasses.dataclass
class OutMessage:
    ir: IterRound
    values: Optional[list[bytes]] = None
    reference: Optional[bytes] = None


@dataclasses.dataclass
class Output:
    coin: Optional[bool] = None        # from preround VRFs, after softlock
    result: Optional[list[bytes]] = None
    terminated: bool = False
    message: Optional[OutMessage] = None


@dataclasses.dataclass
class _GSet:
    values: list[bytes]
    grade: int
    smallest: bytes


class GradedGossip:
    """Protocols 1 & 3 state: one slot per (iter, round, sender), with
    equivocation tracking (reference protocol.go:337-376)."""

    def __init__(self, threshold: int):
        self.threshold = threshold
        self.state: dict[tuple, _GossipInput] = {}

    def receive(self, current: IterRound,
                inp: Input) -> tuple[bool, Optional[Equivocation]]:
        other = self.state.get(inp.key())
        if other is not None:
            if other.inp.msg_hash != inp.msg_hash and not other.inp.malicious:
                # conflicting copy: keep the max-atxgrade one, mark
                # malicious, remember when the other surfaced (feeds the
                # gradecast (a)/(b) delay conditions)
                if inp.atxgrade > other.inp.atxgrade:
                    inp.malicious = True
                    self.state[inp.key()] = _GossipInput(
                        inp=inp, received=current,
                        other_received=other.received)
                else:
                    other.inp.malicious = True
                    other.other_received = current
                return True, Equivocation(
                    sender=inp.sender, first_hash=other.inp.msg_hash,
                    second_hash=inp.msg_hash)
            return False, None  # duplicate
        self.state[inp.key()] = _GossipInput(inp=inp, received=current)
        return True, None

    # -- Protocol 2: gradecast (protocol.go:386-421) --

    def gradecast(self, target: IterRound) -> list[_GSet]:
        rst = []
        for key, v in self.state.items():
            if key[0] != target:
                continue
            if v.inp.malicious and v.other_received is None:
                continue
            if (v.inp.atxgrade == GRADE5 and v.received.delay(target) <= 1
                    and (v.other_received is None
                         or v.other_received.delay(target) > 3)):
                rst.append(_GSet(values=list(v.inp.values or []),
                                 grade=GRADE2, smallest=v.inp.vrf))
            elif (v.inp.atxgrade >= GRADE4 and v.received.delay(target) <= 2
                    and (v.other_received is None
                         or v.other_received.delay(target) > 2)):
                rst.append(_GSet(values=list(v.inp.values or []),
                                 grade=GRADE1, smallest=v.inp.vrf))
        # p-Weak leader election: order candidate leaders by VRF so the
        # whole cluster picks the same one (protocol.go:414-419)
        rst.sort(key=lambda g: g.smallest)
        return rst

    # -- Protocol 3: thresh-gossip (protocol.go:424-512) --

    def _tallies(self, target: IterRound, msg_grade: int,
                 by_ref: bool) -> dict:
        # min atxgrade among non-equivocating senders in the window
        # (protocol.go:491-498)
        min_grade = GRADE5
        for key, v in self.state.items():
            if (key[0] == target and not v.inp.malicious
                    and v.received.grade(target) >= msg_grade
                    and v.inp.atxgrade < min_grade):
                min_grade = v.inp.atxgrade
        tallies: dict = {}
        for key, v in self.state.items():
            if key[0] != target or v.inp.atxgrade < min_grade \
                    or v.received.grade(target) < msg_grade:
                continue
            items = ([v.inp.reference] if by_ref
                     else list(v.inp.values or []))
            for item in items:
                if item is None:
                    continue
                total, valid = tallies.get(item, (0, 0))
                total += v.inp.eligibility_count
                if not v.inp.malicious:
                    valid += v.inp.eligibility_count
                tallies[item] = (total, valid)
        return tallies

    def threshold_gossip(self, target: IterRound,
                         msg_grade: int) -> list[bytes]:
        """Values with >= threshold total weight and at least one
        non-equivocating vote, sorted."""
        t = self._tallies(target, msg_grade, by_ref=False)
        return sorted(v for v, (total, valid) in t.items()
                      if total >= self.threshold and valid > 0)

    def threshold_gossip_ref(self, target: IterRound,
                             msg_grade: int) -> list[bytes]:
        t = self._tallies(target, msg_grade, by_ref=True)
        return sorted(r for r, (total, valid) in t.items()
                      if total >= self.threshold and valid > 0)


class Protocol:
    """The per-layer machine (reference protocol.go:92-290)."""

    def __init__(self, threshold: int):
        self.current = IterRound(0, PREROUND)
        self.gossip = GradedGossip(threshold)
        self.initial: list[bytes] = []
        self.result: Optional[bytes] = None
        self.locked: Optional[bytes] = None
        self.hard_locked = False
        self.valid_proposals: dict[bytes, list[bytes]] = {}
        self.coin_vrf: Optional[bytes] = None
        self._coin_out = False

    def on_initial(self, proposals: list[bytes]) -> None:
        self.initial = sorted(proposals)

    def on_input(self, inp: Input) -> tuple[bool, Optional[Equivocation]]:
        """Feed a validated message; returns (relay?, equivocation)."""
        gossip, equivocation = self.gossip.receive(self.current, inp)
        if not gossip:
            return False, equivocation
        if inp.ir.round == PREROUND and inp.values is not None:
            if self.coin_vrf is None or inp.vrf < self.coin_vrf:
                self.coin_vrf = inp.vrf  # smallest preround VRF -> coin
        return gossip, equivocation

    # -- execution helpers (protocol.go:134-151) --

    def _threshold_proposals(self, ir: IterRound,
                             grade: int) -> tuple[Optional[bytes],
                                                  Optional[list[bytes]]]:
        for ref in self.gossip.threshold_gossip_ref(ir, grade):
            if ref in self.valid_proposals:
                return ref, self.valid_proposals[ref]
        return None, None

    def _commit_exists(self, it: int, match: bytes, grade: int) -> bool:
        return match in self.gossip.threshold_gossip_ref(
            IterRound(it, COMMIT), grade)

    # -- one round of execution (protocol.go:152-259) --

    def _execution(self, out: Output) -> None:
        it, rnd = self.current.iter, self.current.round
        if rnd == PREROUND:
            out.message = OutMessage(ir=self.current,
                                     values=list(self.initial))
        elif rnd == HARDLOCK and it > 0:
            if self.result is not None:
                out.terminated = True
            ref, values = self._threshold_proposals(
                IterRound(it - 1, NOTIFY), GRADE5)
            if ref is not None and self.result is None:
                self.result = ref
                out.result = values if values is not None else []
            cref, _ = self._threshold_proposals(
                IterRound(it - 1, COMMIT), GRADE4)
            if cref is not None:
                self.locked, self.hard_locked = cref, True
            else:
                self.locked, self.hard_locked = None, False
        elif rnd == SOFTLOCK and it > 0 and not self.hard_locked:
            cref, _ = self._threshold_proposals(
                IterRound(it - 1, COMMIT), GRADE3)
            self.locked = cref
        elif rnd == PROPOSE:
            values = self.gossip.threshold_gossip(
                IterRound(0, PREROUND), GRADE4)
            if it > 0:
                ref, overwrite = self._threshold_proposals(
                    IterRound(it - 1, COMMIT), GRADE2)
                if ref is not None:
                    values = overwrite
            out.message = OutMessage(ir=self.current, values=values)
        elif rnd == COMMIT:
            proposed = self.gossip.gradecast(IterRound(it, PROPOSE))
            g2 = set(self.gossip.threshold_gossip(
                IterRound(0, PREROUND), GRADE2))
            for graded in proposed:
                # conditions (a),(b): proposal values must be g2-supported
                if not set(graded.values) <= g2:
                    continue
                self.valid_proposals[values_ref(graded.values)] = \
                    sorted(graded.values)
            if self.hard_locked and self.locked is not None:
                out.message = OutMessage(ir=self.current,
                                         reference=self.locked)
            else:
                g3 = set(self.gossip.threshold_gossip(
                    IterRound(0, PREROUND), GRADE3))
                g5 = set(self.gossip.threshold_gossip(
                    IterRound(0, PREROUND), GRADE5))
                for graded in proposed:   # VRF-ordered: weak leader election
                    ref = values_ref(graded.values)
                    if ref not in self.valid_proposals:       # (c)
                        continue
                    if graded.grade != GRADE2:                # (e)
                        continue
                    if not set(graded.values) <= g3:          # (f)
                        continue
                    if not g5 <= set(graded.values) and \
                            not self._commit_exists(it - 1, ref, GRADE1):
                        continue                              # (g)
                    if self.locked is not None and self.locked != ref:
                        continue                              # (h)
                    out.message = OutMessage(ir=self.current, reference=ref)
                    break
        elif rnd == NOTIFY:
            ref = self.result
            if ref is None:
                ref, _ = self._threshold_proposals(
                    IterRound(it, COMMIT), GRADE5)
            if ref is not None:
                out.message = OutMessage(ir=self.current, reference=ref)

    def next(self) -> Output:
        """Advance one round; returns what to emit this round."""
        out = Output()
        self._execution(out)
        if (self.current.round >= SOFTLOCK and self.coin_vrf is not None
                and not self._coin_out):
            out.coin = bool(self.coin_vrf[-1] & 1)
            self._coin_out = True
        cur = self.current
        if cur.round == PREROUND and cur.iter == 0:
            # skip hardlock in iteration 0 (protocol.go:276-279)
            self.current = IterRound(0, SOFTLOCK)
        elif cur.round == NOTIFY:
            self.current = IterRound(cur.iter + 1, HARDLOCK)
        else:
            self.current = IterRound(cur.iter, cur.round + 1)
        return out
