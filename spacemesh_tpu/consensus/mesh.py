"""Mesh: the per-layer DAG bookkeeping + state application.

Mirrors reference mesh/ (mesh.go:302 ProcessLayer applies tortoise
updates and reverts on reorg; :497 per-hare-output fast path; executor.go
runs the VM optimistically on hare output) and proposals/store (in-RAM
current-epoch proposal store with eviction).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Optional

from ..core.types import Block, Proposal, Reward, Transaction
from ..utils import metrics
from ..storage import blocks as blockstore
from ..storage import layers as layerstore
from ..storage import misc as miscstore
from ..storage import transactions as txstore
from ..storage.cache import AtxCache
from ..storage.db import Database
from ..txs import ConservativeState
from ..vm import VM
from .hare import ConsensusOutput
from .tortoise import EMPTY, Tortoise


class ProposalStore:
    """In-RAM proposals for recent layers (reference proposals/store)."""

    def __init__(self) -> None:
        self._by_layer: dict[int, dict[bytes, Proposal]] = {}
        self._lock = threading.RLock()

    def add(self, p: Proposal) -> None:
        with self._lock:
            self._by_layer.setdefault(p.ballot.layer, {})[p.id] = p

    def get(self, pid: bytes) -> Optional[Proposal]:
        with self._lock:
            for layer in self._by_layer.values():
                if pid in layer:
                    return layer[pid]
        return None

    def in_layer(self, layer: int) -> list[Proposal]:
        with self._lock:
            return list(self._by_layer.get(layer, {}).values())

    def ids_in_layer(self, layer: int) -> list[bytes]:
        with self._lock:
            return sorted(self._by_layer.get(layer, {}))

    def evict(self, before_layer: int) -> None:
        with self._lock:
            for lyr in [x for x in self._by_layer if x < before_layer]:
                del self._by_layer[lyr]


class Executor:
    """Optimistic block execution (reference mesh/executor.go)."""

    def __init__(self, db: Database, vm: VM, cstate: ConservativeState):
        self.db = db
        self.vm = vm
        self.cstate = cstate

    def execute(self, block: Block) -> bytes:
        txs = []
        for tx_id in block.tx_ids:
            tx = self.cstate.get(tx_id)
            if tx is not None:
                txs.append(tx)
        _, root = self.vm.apply(block.layer, block.id, txs,
                                list(block.rewards))
        layerstore.set_applied(self.db, block.layer, block.id, root)
        self._aggregate(block.layer, block.id)
        self.cstate.on_applied()
        return root

    def execute_empty(self, layer: int) -> bytes:
        prev = layerstore.state_hash(self.db, layer - 1) or bytes(32)
        layerstore.set_applied(self.db, layer, EMPTY, prev)
        self._aggregate(layer, EMPTY)
        return prev

    def _aggregate(self, layer: int, block_id: bytes) -> None:
        """Chained per-layer mesh hash (reference aggregated layer hash):
        agg(L) = H(agg(L-1) || applied block id). Peers comparing these
        detect forks and bisect to the divergence point (fork finder)."""
        from ..core.hashing import sum256

        prev = layerstore.aggregated_hash(self.db, layer - 1) or bytes(32)
        layerstore.set_aggregated_hash(self.db, layer,
                                       sum256(prev, block_id))

    def revert(self, to_layer: int) -> None:
        self.vm.revert(to_layer)
        self.db.exec("DELETE FROM layers WHERE id>?", (to_layer,))


class Mesh:
    def __init__(self, *, db: Database, tortoise: Tortoise,
                 executor: Executor, proposals: ProposalStore,
                 cache: AtxCache):
        self.db = db
        self.tortoise = tortoise
        self.executor = executor
        self.proposals = proposals
        self.cache = cache
        # recover the applied frontier from storage on restart (reference
        # mesh.go:123 recoverFromDB)
        self.latest_applied = max(layerstore.last_applied(db), 0)
        # layers applied differently than their (later-arriving)
        # committee certificate — healed by process_layer
        self._cert_dirty: set[int] = set()
        # earliest layer whose reapply was deferred (content in flight):
        # tortoise.updates() is drained once, so the retry intent must
        # survive the pass (code-review r5)
        self._pending_reapply: int | None = None

    def add_block(self, block: Block) -> None:
        with self.db.tx():
            blockstore.add(self.db, block)
        self.tortoise.on_block(block.layer, block.id)

    def process_hare_output(self, block: Optional[Block], layer: int) -> None:
        """Fast path: hare agreed -> apply immediately (reference
        mesh.go:497 ProcessLayerPerHareOutput)."""
        if block is None:
            self.tortoise.on_hare_output(layer, EMPTY)
            if self.latest_applied == layer - 1:
                self.executor.execute_empty(layer)
                self.latest_applied = layer
        else:
            self.add_block(block)
            self.tortoise.on_hare_output(layer, block.id)
            if self.latest_applied == layer - 1:
                self.executor.execute(block)
                self.latest_applied = layer
        layerstore.set_processed(self.db, layer)

    def process_layer(self, layer: int) -> None:
        """Tortoise-driven path: tally votes, apply validity updates,
        revert + reapply on opinion change (reference mesh.go:302)."""
        t0 = time.perf_counter()
        try:
            self._process_layer(layer)
        finally:
            # the layer-apply latency SLI (obs/sli.py): observed at the
            # ONE choke point every caller (layer loop, hare drain,
            # sync apply) funnels through
            metrics.layer_apply_seconds.observe(time.perf_counter() - t0)

    def _process_layer(self, layer: int) -> None:
        self.tortoise.tally_votes(layer)
        min_changed = None
        for upd in self.tortoise.updates():
            with self.db.tx():
                if upd.valid:
                    blockstore.set_valid(self.db, upd.block_id)
                else:
                    blockstore.set_invalid(self.db, upd.block_id)
            applied = layerstore.applied_block(self.db, upd.layer)
            should = self._block_to_apply(upd.layer)
            if applied is not None and applied != should:
                if min_changed is None or upd.layer < min_changed:
                    min_changed = upd.layer
        # layers whose COMMITTEE decision (adopted certificate) arrived
        # after we applied them differently — heal them here, where the
        # reapply is prechecked, not in the gossip handler
        for lyr in sorted(self._cert_dirty):
            cert = miscstore.certified_block(self.db, lyr)
            if cert is None or lyr > self.latest_applied:
                self._cert_dirty.discard(lyr)
                continue
            if layerstore.applied_block(self.db, lyr) == cert:
                self._cert_dirty.discard(lyr)
                continue
            if blockstore.get(self.db, cert) is None:
                continue  # block still in flight; keep the mark
            if min_changed is None or lyr < min_changed:
                min_changed = lyr
        if self._pending_reapply is not None:
            if min_changed is None or self._pending_reapply < min_changed:
                min_changed = self._pending_reapply
        if min_changed is not None:
            if self._reapply_from(min_changed):
                self._pending_reapply = None
                # drop only marks the reapply actually SETTLED — a
                # cert-dirty layer whose block is still in flight was
                # applied per fallback and must stay marked
                self._cert_dirty = {
                    x for x in self._cert_dirty
                    if layerstore.applied_block(self.db, x)
                    != miscstore.certified_block(self.db, x)}
            else:
                self._pending_reapply = min_changed
        # advance the applied frontier through tortoise-DECIDED layers:
        # a layer whose hare never concluded stalls the hare fast path
        # forever; once the tortoise verifies it (margins/healing), the
        # mesh must apply it (reference mesh.go:302 ProcessLayer applies
        # up to the verified frontier)
        nxt = self.latest_applied + 1
        while nxt <= self.tortoise.verified:
            bid = self._block_to_apply(nxt)
            if bid == EMPTY:
                self.executor.execute_empty(nxt)
            else:
                block = self._executable(bid)
                if block is None:
                    break  # content/txs not fetched yet: retry next pass
                self.executor.execute(block)
            layerstore.set_processed(self.db, nxt)
            self.latest_applied = nxt
            nxt += 1

    def _block_to_apply(self, layer: int) -> bytes:
        """Positive tortoise verdicts win; otherwise the hare output
        (including an adopted certificate) decides — a thin-margin
        "nothing proven valid" must not override the committee's
        certified agreement (reference mesh.go: applied block follows
        hare output until the tortoise verifies otherwise)."""
        valid = self.tortoise.valid_blocks(layer)
        if valid:
            return valid[0]
        hare = self.tortoise.hare_of(layer)
        if hare is not None and hare != EMPTY \
                and self.tortoise.verdict(hare) is not False \
                and blockstore.get(self.db, hare) is not None:
            # hare/cert output holds only while the tortoise has not
            # verified OTHERWISE (code-review r5: an explicitly
            # invalidated block must not stay applied)
            return hare
        return EMPTY

    def adopt_certified(self, layer: int, block_id: bytes) -> None:
        """A VALIDATED threshold certificate IS the network's hare
        output for the layer — adopt it even when our own hare failed
        or we already applied the layer differently (e.g. this node
        raced ahead on a skewed clock and settled on empty; round-5
        chaos test). Without this, a node whose local hare missed a
        layer diverges PERMANENTLY whenever the tortoise margin never
        crosses (small committees). Reference: certificate adoption in
        syncer/state_syncer.go + mesh.go:497 ProcessLayerPerHareOutput.

        Only RECORDS the adoption (hare output + dirty mark): the
        revert/reapply runs inside the next process_layer pass, which
        prechecks that the whole affected span is executable — a
        mid-gossip partial revert would leave holes in the applied
        chain. The certified BLOCK may not be local yet (the cert can
        assemble before the block gossip lands); the dirty mark
        persists until the block arrives and the reapply succeeds."""
        self.tortoise.on_hare_output(layer, block_id)
        applied = layerstore.applied_block(self.db, layer)
        if applied is not None and applied != block_id:
            self._cert_dirty.add(layer)

    def _executable(self, bid: bytes) -> Optional[Block]:
        """The block, if its content AND all its txs are local. Executing
        with missing txs silently diverges the state root (Executor
        skips unknown txs); callers must defer instead — the sync path
        refetches and retries (code-review r3)."""
        block = blockstore.get(self.db, bid)
        if block is None:
            return None
        for tx_id in block.tx_ids:
            if self.executor.cstate.get(tx_id) is None:
                return None
        return block

    def _reapply_from(self, layer: int) -> bool:
        """Revert to ``layer``-1 and re-execute forward. PRECHECKS that
        every affected layer is executable before reverting: a revert
        that cannot be fully reapplied leaves holes in the applied
        chain — every later state root diverges and the sync frontier
        skips the gap (round-5 chaos debugging). Returns True when the
        reapply ran to the old frontier."""
        for lyr in range(layer, self.latest_applied + 1):
            bid = self._block_to_apply(lyr)
            if bid != EMPTY and self._executable(bid) is None:
                return False  # content/txs in flight; retry next pass
        self.executor.revert(layer - 1)
        target = self.latest_applied
        self.latest_applied = layer - 1
        for lyr in range(layer, target + 1):
            bid = self._block_to_apply(lyr)
            if bid == EMPTY:
                self.executor.execute_empty(lyr)
            else:
                block = self._executable(bid)
                if block is None:  # pragma: no cover - precheck holds
                    return False
                self.executor.execute(block)
            # revert dropped the layer rows; the re-executed layers are
            # processed again (keeps the processed frontier monotone)
            layerstore.set_processed(self.db, lyr)
            self.latest_applied = lyr
        return True
