"""Active-set generation + epoch min-weight gating.

Mirrors three reference pieces:

- miner/minweight/minweight.go `Select`: a per-epoch table of minimal
  active-set weights; the value for a target epoch is the last entry at
  or below it.
- proposals/util/util.go:29-39 `GetNumEligibleSlots`: proposal slots =
  w * committee * layers_per_epoch / max(min_weight, total_weight). The
  min-weight denominator is what bounds dust amplification on young or
  partitioned networks: with a mainnet-scale floor, a tiny identity's
  quotient is 0 and only the reference's explicit one-slot floor remains
  (util.go:36-38 — kept here for parity; the floor is worth at most one
  ballot whose eligibility WEIGHT is still w/num_slots, so it buys no
  voting power).
- miner/active_set_generator.go: the three-path generator — trusted
  fallback (bootstrap update), grading by receipt time, or the epoch's
  first applied block — persisted in the node-local DB so a restart
  doesn't redo the work.

ATX grading (active_set_generator.go:269-293, community.spacemesh.io
"Grading ATXs for the active set"): with s = epoch start and d = network
delay,
  good        received < s-4d and no malfeasance proof before s
  acceptable  received < s-3d and no proof before s-d
  evil        otherwise
Only GOOD activations enter the generated set; the set is used only when
good/total clears ``good_atx_percent`` (generator.go:164-176).
"""

from __future__ import annotations

from ..core.hashing import sum256
from ..storage import atxs as atxstore
from ..storage.cache import AtxCache
from ..storage.db import Database

GRADE_EVIL, GRADE_ACCEPTABLE, GRADE_GOOD = 0, 1, 2

# prepared_activeset.kind (reference sql/localsql/activeset kinds)
KIND_TORTOISE = 0


def select_min_weight(epoch: int, weights: list[tuple[int, int]]) -> int:
    """Min active-set weight for ``epoch`` from a sorted (epoch, weight)
    table — the last entry at or below it (minweight/minweight.go:5-20)."""
    rst, prev = 0, 0
    for at, weight in weights:
        if at < prev:
            raise ValueError("min-weight table not sorted by epoch")
        if epoch >= at:
            rst = weight
        prev = at
    return rst


def num_eligible_slots(weight: int, min_weight: int, total_weight: int,
                       committee_size: int, layers_per_epoch: int) -> int:
    """Proposal slots for one epoch (proposals/util/util.go:29-39)."""
    if total_weight == 0:
        return 0
    num = weight * committee_size * layers_per_epoch \
        // max(min_weight, total_weight)
    return max(num, 1)


_SET_WEIGHT_MEMO_MAX = 256


def declared_set_weight(db: Database, cache: AtxCache, epoch: int,
                        root: bytes) -> int | None:
    """Total weight of the stored active set with this root, when every
    member resolves in the cache. The eligibility denominator must come
    from the set a ballot DECLARES, not the validator's local ATX view —
    nodes with divergent views would otherwise disagree on ballot
    validity (reference proposals/eligibility_validator.go validates
    against the ref ballot's declared set; ADVICE r4). None → caller
    falls back to the local epoch weight.

    Fully-resolved sums are memoized by (epoch, root) ON THE NODE'S
    cache (per-node state, not module-global — separate nodes in one
    process have separate views): thousands of ref ballots per epoch
    declare the same root, and ATX weight is intrinsic (num_units x
    ticks), so the sum is stable once every member resolved —
    re-summing a mainnet-shape set per ballot is O(smeshers x set_size)
    wasted work (code-review r5)."""
    from ..storage import misc as miscstore

    memo = getattr(cache, "_set_weight_memo", None)
    if memo is None:
        memo = cache._set_weight_memo = {}
    hit = memo.get((epoch, root))
    if hit is not None:
        return hit
    ids = miscstore.active_set(db, root)
    if ids is None:
        return None
    total = 0
    for atx_id in ids:
        member = cache.get(epoch, atx_id)
        if member is None:
            return None
        total += member.weight
    if total:
        if len(memo) >= _SET_WEIGHT_MEMO_MAX:
            memo.pop(next(iter(memo)))
        memo[(epoch, root)] = total
    return total or None


def grade_atx(epoch_start: float, network_delay: float,
              atx_received: float, proof_received: float | None) -> int:
    """Grade by receipt time vs epoch start (generator.go:283-293)."""
    if atx_received < epoch_start - 4 * network_delay and (
            proof_received is None or proof_received >= epoch_start):
        return GRADE_GOOD
    if atx_received < epoch_start - 3 * network_delay and (
            proof_received is None
            or proof_received >= epoch_start - network_delay):
        return GRADE_ACCEPTABLE
    return GRADE_EVIL


def active_set_hash(atx_ids: list[bytes]) -> bytes:
    return sum256(*sorted(atx_ids)) if atx_ids else bytes(32)


class ActiveSetGenerator:
    """Three-path generator with local persistence
    (miner/active_set_generator.go:117-216)."""

    def __init__(self, state: Database, local: Database, cache: AtxCache, *,
                 layers_per_epoch: int, layer_duration: float,
                 genesis_time, network_delay: float,
                 good_atx_percent: int = 50):
        self.state = state
        self.local = local
        self.cache = cache
        self.layers_per_epoch = layers_per_epoch
        self.layer_duration = layer_duration
        # float, or a callable returning the EFFECTIVE genesis time — the
        # node's clock may be rebased after wiring (--genesis-now)
        self.genesis_time = genesis_time
        self.network_delay = network_delay
        self.good_atx_percent = good_atx_percent
        self._fallback: dict[int, list[bytes]] = {}

    def update_fallback(self, target_epoch: int, atx_ids: list[bytes]) -> None:
        """Trusted (bootstrap-service) active set for an epoch; first
        update wins (generator.go:78-91)."""
        self._fallback.setdefault(target_epoch, list(atx_ids))

    def _epoch_start(self, epoch: int) -> float:
        genesis = self.genesis_time() if callable(self.genesis_time) \
            else self.genesis_time
        return genesis + epoch * self.layers_per_epoch * self.layer_duration

    def _set_weight(self, target_epoch: int, atx_ids: list[bytes]) -> int:
        total = 0
        for atx_id in atx_ids:
            info = self.cache.get(target_epoch, atx_id)
            if info is None:
                raise LookupError(f"atx {atx_id.hex()[:12]} not in atxsdata")
            total += info.weight
        return total

    def _from_grades(self, target_epoch: int) -> tuple[list[bytes], int, int]:
        """(good set, weight, total counted) over ATXs published in the
        prior epoch (generator.go:223-254)."""
        epoch_start = self._epoch_start(target_epoch)
        good, weight, total = [], 0, 0
        for row in atxstore.rows_for_grading(self.state, target_epoch - 1):
            total += 1
            if grade_atx(epoch_start, self.network_delay, row["received"],
                         row["proof_received"]) == GRADE_GOOD:
                good.append(row["id"])
                info = self.cache.get(target_epoch, row["id"])
                weight += info.weight if info else 0
        return good, weight, total

    def _from_first_block(self, target_epoch: int) -> list[bytes] | None:
        """Union of active sets referenced by the epoch's first applied
        block's rewarded ref ballots (generator.go:296-334)."""
        from ..storage import ballots as ballotstore
        from ..storage import blocks as blockstore
        from ..storage import layers as layerstore
        from ..storage import misc as miscstore

        first = target_epoch * self.layers_per_epoch
        block = None
        for layer in range(first, first + self.layers_per_epoch):
            bid = layerstore.applied_block(self.state, layer)
            if bid:
                block = blockstore.get(self.state, bid)
                break
        if block is None:
            return None
        out: set[bytes] = set()
        epoch_first = target_epoch * self.layers_per_epoch
        for reward in block.rewards:
            out.add(reward.atx_id)
            ref = ballotstore.refballot_by_atx(
                self.state, reward.atx_id, epoch_first,
                epoch_first + self.layers_per_epoch)
            if ref is None or ref.epoch_data is None:
                continue
            stored = miscstore.active_set(
                self.state, ref.epoch_data.active_set_root)
            for atx_id in stored or ():
                out.add(atx_id)
        return sorted(out)

    def get_prepared(self, target_epoch: int
                     ) -> tuple[bytes, int, list[bytes]] | None:
        row = self.local.one(
            "SELECT id, weight, data FROM prepared_activeset"
            " WHERE kind=? AND epoch=?", (KIND_TORTOISE, target_epoch))
        if row is None:
            return None
        data = row["data"]
        ids = [data[i:i + 32] for i in range(0, len(data), 32)]
        return row["id"], row["weight"], ids

    def generate(self, current_layer: int, target_epoch: int
                 ) -> tuple[bytes, int, list[bytes]]:
        """(hash, weight, sorted atx ids). Raises LookupError when no path
        can produce a set yet (caller retries; generator.go:94-115)."""
        prepared = self.get_prepared(target_epoch)
        if prepared is not None:
            return prepared

        set_, weight = None, 0
        fallback = self._fallback.get(target_epoch)
        if fallback is not None:
            weight = self._set_weight(target_epoch, fallback)
            set_ = list(fallback)
        else:
            good, gweight, total = self._from_grades(target_epoch)
            if total and len(good) * 100 // total > self.good_atx_percent:
                set_, weight = good, gweight
        if set_ is None and current_layer > target_epoch * self.layers_per_epoch:
            from_block = self._from_first_block(target_epoch)
            if from_block:
                set_ = from_block
                weight = self._set_weight(target_epoch, set_)
        if not set_ or weight == 0:
            raise LookupError(
                f"cannot generate active set for epoch {target_epoch}")
        set_.sort()
        set_id = active_set_hash(set_)
        self.local.exec(
            "INSERT OR REPLACE INTO prepared_activeset"
            " (kind, epoch, id, weight, data) VALUES (?,?,?,?,?)",
            (KIND_TORTOISE, target_epoch, set_id, weight, b"".join(set_)))
        return set_id, weight, set_
