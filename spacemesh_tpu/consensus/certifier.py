"""Poet certifier: POST-backed certificates gating poet registration.

The reference poet deployments front registration with a certifier
service: the node submits its POST proof ONCE to the certifier
(reference activation/certifier.go:246 Certify -> POST /certify with
proof + metadata), receives a signed certificate, and registers at poets
with the lightweight cert instead of a full proof per round (anti-DoS:
the poet only needs to verify one ed25519 signature).  Here:

* ``CertifierService``     verifies the submitted proof against the
                           node's claimed commitment and signs the cert
* ``CertifierDaemon``      serves it over framed JSON (tools CLI)
* ``CertifierClient``      the node side; caches the cert per identity
* ``PoetService.register`` (consensus/poet.py) verifies certs when the
                           poet is configured with a trusted certifier
                           pubkey
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import socket
import struct
import time

from ..core.signing import Domain, EdSigner, EdVerifier
from ..post.prover import Proof, ProofParams
from ..post.verifier import VerifyItem, verify

MAX_MSG = 4 << 20


@dataclasses.dataclass
class PoetCert:
    """What a poet accepts in lieu of a full proof (reference
    certifier/PoetCert: data + signature)."""

    node_id: bytes
    expiry: float          # unix seconds; 0 = no expiry
    signature: bytes       # certifier key over signed_bytes()

    def signed_bytes(self) -> bytes:
        return b"poet-cert" + self.node_id + struct.pack(
            "<Q", int(self.expiry))

    def to_dict(self) -> dict:
        return {"node_id": self.node_id.hex(), "expiry": self.expiry,
                "signature": self.signature.hex()}

    @classmethod
    def from_dict(cls, d: dict) -> "PoetCert":
        return cls(node_id=bytes.fromhex(d["node_id"]),
                   expiry=float(d["expiry"]),
                   signature=bytes.fromhex(d["signature"]))


def verify_cert(cert: PoetCert, certifier_pubkey: bytes,
                verifier: EdVerifier, now: float | None = None) -> bool:
    if cert.expiry and (now if now is not None else time.time()) > cert.expiry:
        return False
    return verifier.verify(Domain.POET_CERT, certifier_pubkey,
                           cert.signed_bytes(), cert.signature)


class CertifierService:
    """Verify a POST proof, sign a certificate (certifier.go:246 flow)."""

    def __init__(self, signer: EdSigner, params: ProofParams,
                 scrypt_n: int, validity: float = 0.0,
                 time_source=time.time):
        self.signer = signer
        self.params = params
        self.scrypt_n = scrypt_n
        self.validity = validity  # seconds; 0 = certs never expire
        # injected so sim/chaos scenarios can skew cert expiries along
        # with the rest of the node (SC001 clock discipline)
        self._now = time_source

    @property
    def pubkey(self) -> bytes:
        return self.signer.public_key

    def certify(self, *, proof: Proof, challenge: bytes, node_id: bytes,
                commitment: bytes, num_units: int,
                labels_per_unit: int) -> PoetCert:
        ok = verify(VerifyItem(
            proof=proof, challenge=challenge, node_id=node_id,
            commitment=commitment, scrypt_n=self.scrypt_n,
            total_labels=num_units * labels_per_unit), self.params)
        if not ok:
            raise ValueError("POST proof failed verification")
        cert = PoetCert(
            node_id=node_id,
            expiry=self._now() + self.validity if self.validity else 0.0,
            signature=b"")
        cert.signature = self.signer.sign(Domain.POET_CERT,
                                          cert.signed_bytes())
        return cert


# --- framed-JSON daemon + client (the pattern poet_remote.py rides) -------


def _send_msg(sock: socket.socket, obj: dict) -> None:
    data = json.dumps(obj).encode()
    sock.sendall(struct.pack("<I", len(data)) + data)


def _recv_msg(sock: socket.socket) -> dict:
    head = b""
    while len(head) < 4:
        chunk = sock.recv(4 - len(head))
        if not chunk:
            raise ConnectionError("closed")
        head += chunk
    (length,) = struct.unpack("<I", head)
    if length > MAX_MSG:
        raise ConnectionError("oversized")
    buf = b""
    while len(buf) < length:
        chunk = sock.recv(length - len(buf))
        if not chunk:
            raise ConnectionError("closed")
        buf += chunk
    return json.loads(buf)


class CertifierDaemon:
    def __init__(self, service: CertifierService,
                 listen: str = "127.0.0.1:0"):
        self.service = service
        self.listen = listen
        self.address: tuple[str, int] | None = None
        self._server: asyncio.AbstractServer | None = None

    async def start(self) -> tuple[str, int]:
        host, _, port = self.listen.rpartition(":")
        self._server = await asyncio.start_server(
            self._client, host or "127.0.0.1", int(port or 0))
        self.address = self._server.sockets[0].getsockname()[:2]
        return self.address

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    async def _client(self, reader, writer) -> None:
        try:
            while True:
                head = await reader.readexactly(4)
                (length,) = struct.unpack("<I", head)
                if length > MAX_MSG:
                    break
                req = json.loads(await reader.readexactly(length))
                resp = await self._dispatch(req)
                data = json.dumps(resp).encode()
                writer.write(struct.pack("<I", len(data)) + data)
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError, OSError,
                json.JSONDecodeError):
            pass
        finally:
            writer.close()

    async def _dispatch(self, req: dict) -> dict:
        try:
            method = req.get("method")
            if method == "pubkey":
                return {"ok": True, "pubkey": self.service.pubkey.hex()}
            if method == "certify":
                # verification recomputes K3 labels — off the loop
                cert = await asyncio.to_thread(
                    self.service.certify,
                    proof=Proof.from_dict(req["proof"]),
                    challenge=bytes.fromhex(req["challenge"]),
                    node_id=bytes.fromhex(req["node_id"]),
                    commitment=bytes.fromhex(req["commitment"]),
                    num_units=int(req["num_units"]),
                    labels_per_unit=int(req["labels_per_unit"]))
                return {"ok": True, "certificate": cert.to_dict()}
            return {"ok": False, "error": f"unknown method {method!r}"}
        except Exception as e:  # noqa: BLE001 — error travels to the node
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}


class CertifierClient:
    """Node side: obtain + cache one cert per identity (reference
    Certifier.Certificate caches in the local DB)."""

    def __init__(self, address: tuple[str, int], timeout: float = 120.0,
                 time_source=time.time):
        self.address = tuple(address)
        self.timeout = timeout
        self._now = time_source  # cert-expiry checks follow the node clock
        self._certs: dict[bytes, PoetCert] = {}

    def _call(self, req: dict) -> dict:
        with socket.create_connection(self.address,
                                      timeout=self.timeout) as s:
            _send_msg(s, req)
            resp = _recv_msg(s)
        if not resp.get("ok"):
            raise RuntimeError(f"certifier: {resp.get('error')}")
        return resp

    def pubkey(self) -> bytes:
        return bytes.fromhex(self._call({"method": "pubkey"})["pubkey"])

    def certificate(self, *, proof: Proof, challenge: bytes, node_id: bytes,
                    commitment: bytes, num_units: int,
                    labels_per_unit: int) -> PoetCert:
        cached = self._certs.get(node_id)
        if cached is not None and (not cached.expiry
                                   or cached.expiry > self._now()):
            return cached
        d = self._call({
            "method": "certify", "proof": proof.to_dict(),
            "challenge": challenge.hex(), "node_id": node_id.hex(),
            "commitment": commitment.hex(), "num_units": num_units,
            "labels_per_unit": labels_per_unit})
        cert = PoetCert.from_dict(d["certificate"])
        self._certs[node_id] = cert
        return cert


__all__ = ["PoetCert", "CertifierService", "CertifierDaemon",
           "CertifierClient", "verify_cert"]
