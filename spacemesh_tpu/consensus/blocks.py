"""Block generation + certification.

Mirrors reference blocks/: the Generator consumes hare ConsensusOutput,
aggregates the agreed proposals into one block (tx union with
deterministic ordering, weight-proportional rewards, generator.go:182),
saves + certifies; the Certifier collects eligibility-weighted signatures
over the hare output block until the threshold and stores/gossips the
Certificate (certifier.go:224, threshold :331).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ..core import codec
from ..core.signing import Domain, EdSigner, EdVerifier
from ..core.types import Block, CertifyMessage, Certificate, Proposal, Reward
from ..p2p.pubsub import TOPIC_CERTIFY, PubSub
from ..storage import misc as miscstore
from ..storage.cache import AtxCache
from ..storage.db import Database
from .hare import ConsensusOutput
from .mesh import Mesh, ProposalStore


class Generator:
    def __init__(self, *, mesh: Mesh, proposals: ProposalStore,
                 cache: AtxCache, layers_per_epoch: int):
        self.mesh = mesh
        self.proposals = proposals
        self.cache = cache
        self.layers_per_epoch = layers_per_epoch

    def generate(self, out: ConsensusOutput) -> Optional[Block]:
        """Build the layer block from the agreed proposal ids."""
        props = [p for pid in out.proposals
                 if (p := self.proposals.get(pid)) is not None]
        if not props:
            return None
        epoch = out.layer // self.layers_per_epoch
        tx_ids: list[bytes] = []
        seen = set()
        rewards: dict[bytes, tuple[bytes, int]] = {}  # atx -> (coinbase, w)
        height = 0
        for p in sorted(props, key=lambda p: p.id):
            for tx in p.tx_ids:
                if tx not in seen:
                    seen.add(tx)
                    tx_ids.append(tx)
            weight = len(p.ballot.eligibilities)
            atx_id = p.ballot.atx_id
            coinbase = self._coinbase_of(epoch, p)
            prev = rewards.get(atx_id, (coinbase, 0))[1]
            rewards[atx_id] = (coinbase, prev + weight)
            info = self.cache.get(epoch, atx_id)
            if info is not None:
                height = max(height, info.height)
        block = Block(
            layer=out.layer, tick_height=height,
            rewards=[Reward(atx_id=a, coinbase=c, weight=w)
                     for a, (c, w) in sorted(rewards.items())],
            tx_ids=tx_ids)
        return block

    def _coinbase_of(self, epoch: int, p: Proposal) -> bytes:
        from ..storage import atxs as atxstore
        # version-independent: v2 (merged) identity rows share the
        # envelope blob but carry the coinbase column directly
        cb = atxstore.coinbase_of(self.mesh.db, p.ballot.atx_id)
        return cb if cb is not None else bytes(24)

    def process_hare_output(self, out: ConsensusOutput) -> Optional[Block]:
        block = self.generate(out)
        self.mesh.process_hare_output(block, out.layer)
        return block


class Certifier:
    """Collects threshold certificates over hare output blocks."""

    def __init__(self, *, db: Database, signer: EdSigner,
                 verifier: EdVerifier, pubsub: PubSub, oracle,
                 committee_size: int, threshold: int,
                 layers_per_epoch: int, beacon_getter, farm=None):
        self.db = db
        self.signer = signer
        self.verifier = verifier
        self.pubsub = pubsub
        self.oracle = oracle
        self.committee = committee_size
        self.threshold = threshold
        self.layers_per_epoch = layers_per_epoch
        self.beacon_getter = beacon_getter
        # verification farm (verify/farm.py); certificates are
        # block-critical, so their checks ride the BLOCK lane — a sync
        # flood must never delay certificate assembly
        self.farm = farm
        self._pending: dict[tuple[int, bytes], list[CertifyMessage]] = {}
        # callback(layer, block_id) on every ASSEMBLED threshold cert
        self.on_certificate = None
        pubsub.register(TOPIC_CERTIFY, self._gossip)

    async def _verify_sig(self, node_id: bytes, msg: bytes,
                          sig: bytes) -> bool:
        if self.farm is not None:
            from ..verify.farm import Lane, SigRequest

            return await self.farm.submit(
                SigRequest(int(Domain.CERTIFY), node_id, msg, sig),
                lane=Lane.BLOCK)
        return self.verifier.verify(Domain.CERTIFY, node_id, msg, sig)

    CERT_ROUND = 250  # distinct VRF round tag for certifier eligibility

    async def certify_if_eligible(self, layer: int, block_id: bytes,
                                  atx_id: bytes | None,
                                  signer: EdSigner | None = None) -> None:
        """Sign a certificate share if this (identity, layer) holds
        committee seats; multi-identity nodes call once per signer."""
        signer = signer or self.signer
        if atx_id is None:
            return
        epoch = layer // self.layers_per_epoch
        beacon = await self.beacon_getter(epoch)
        el = self.oracle.hare_eligibility(
            signer.vrf_signer(), beacon, layer, self.CERT_ROUND, epoch,
            atx_id, self.committee)
        if el is None:
            return
        proof, count = el
        msg = CertifyMessage(layer=layer, block_id=block_id,
                             eligibility_count=count, proof=proof,
                             atx_id=atx_id, node_id=signer.node_id,
                             signature=bytes(64))
        msg.signature = signer.sign(Domain.CERTIFY, msg.signed_bytes())
        await self.pubsub.publish(TOPIC_CERTIFY, msg.to_bytes())

    async def validate_certificate(self, layer: int,
                                   cert: Certificate) -> bool:
        """Verify a full certificate fetched from a peer (sync adoption,
        reference blocks/handler.go + certifier threshold check): every
        share signed, eligibility-validated, distinct, and the summed
        seat count reaching the threshold. A synced certificate is NEVER
        trusted on a peer's word."""
        epoch = layer // self.layers_per_epoch
        beacon = await self.beacon_getter(epoch)
        total = 0
        seen: set[bytes] = set()
        for msg in cert.signatures:
            if msg.layer != layer or msg.block_id != cert.block_id:
                return False
            if msg.node_id in seen:
                return False
            seen.add(msg.node_id)
            if not await self._verify_sig(msg.node_id, msg.signed_bytes(),
                                          msg.signature):
                return False
            info = self.oracle.cache.get(epoch, msg.atx_id)
            if info is None or info.node_id != msg.node_id:
                return False
            if not self.oracle.validate_hare(
                    beacon, msg.layer, self.CERT_ROUND, epoch, msg.atx_id,
                    self.committee, msg.proof, msg.eligibility_count):
                return False
            total += msg.eligibility_count
        return total >= self.threshold

    async def _gossip(self, peer: bytes, data: bytes) -> bool:
        try:
            msg = CertifyMessage.from_bytes(data)
        except (codec.DecodeError, ValueError):
            return False
        if not await self._verify_sig(msg.node_id, msg.signed_bytes(),
                                      msg.signature):
            return False
        epoch = msg.layer // self.layers_per_epoch
        # the certifier must actually hold the committee seats it claims:
        # VRF-validated against its ATX weight (a bare keypair must not be
        # able to mint certificates)
        from ..storage.cache import AtxInfo  # noqa: F401 (doc anchor)
        info = self.oracle.cache.get(epoch, msg.atx_id)
        if info is None or info.node_id != msg.node_id:
            return False
        beacon = await self.beacon_getter(epoch)
        if not self.oracle.validate_hare(
                beacon, msg.layer, self.CERT_ROUND, epoch, msg.atx_id,
                self.committee, msg.proof, msg.eligibility_count):
            return False
        key = (msg.layer, msg.block_id)
        msgs = self._pending.setdefault(key, [])
        if any(m.node_id == msg.node_id for m in msgs):
            return True
        msgs.append(msg)
        if (sum(m.eligibility_count for m in msgs) >= self.threshold
                and miscstore.certificate(self.db, msg.layer) is None):
            cert = Certificate(block_id=msg.block_id, signatures=list(msgs))
            with self.db.tx():
                miscstore.add_certificate(self.db, msg.layer, cert)
            # a full certificate is the committee's decision for the
            # layer — the node must ADOPT it even if its own hare
            # failed there (App wires this to mesh.adopt_certified)
            if self.on_certificate is not None:
                self.on_certificate(msg.layer, msg.block_id)
        return True
