"""Consensus layer: eligibility oracle, beacon, hare, tortoise, certifier,
malfeasance, plus the mesh/miner/block-generator pipeline they drive
(SURVEY.md §1 layers 4-6)."""
