"""Poet: proof-of-elapsed-time rounds with merkle membership.

The reference outsources sequential work to an external poet service
(reference activation/poet.go HTTP client; SURVEY.md §2.3) and runs one
in-proc for --standalone (node/node.go:1293). This module is that in-proc
service: per round it collects member challenges, performs the sequential
hash chain (tiny tick counts in fastnet/standalone), and emits a PoetProof
whose statement is a merkle root over the members; members fetch their
inclusion proof.

Merkle: leaves = blake3(member), internal = blake3(left || right), odd
nodes promoted. Verification walks MerkleProof.nodes with the leaf index.
"""

from __future__ import annotations

import asyncio
import dataclasses

from ..core.hashing import sum256
from ..core.types import MerkleProof, PoetProof


def merkle_root(leaves: list[bytes]) -> bytes:
    if not leaves:
        return bytes(32)
    level = [sum256(m) for m in leaves]
    while len(level) > 1:
        nxt = []
        for i in range(0, len(level) - 1, 2):
            nxt.append(sum256(level[i], level[i + 1]))
        if len(level) % 2:
            nxt.append(level[-1])
        level = nxt
    return level[0]


def merkle_path(leaves: list[bytes], index: int) -> MerkleProof:
    nodes = []
    level = [sum256(m) for m in leaves]
    i = index
    while len(level) > 1:
        sib = i ^ 1
        if sib < len(level):
            nodes.append(level[sib])
        nxt = []
        for k in range(0, len(level) - 1, 2):
            nxt.append(sum256(level[k], level[k + 1]))
        if len(level) % 2:
            nxt.append(level[-1])
        level = nxt
        i //= 2
    return MerkleProof(leaf_index=index, nodes=nodes)


def verify_membership(member: bytes, proof: MerkleProof, root: bytes,
                      leaf_count: int) -> bool:
    if not 0 <= proof.leaf_index < leaf_count:
        return False
    h = sum256(member)
    i = proof.leaf_index
    width = leaf_count
    nodes = list(proof.nodes)
    while width > 1:
        sib = i ^ 1
        if sib < width:
            if not nodes:
                return False
            s = nodes.pop(0)
            h = sum256(h, s) if i % 2 == 0 else sum256(s, h)
        i //= 2
        width = (width + 1) // 2
    return not nodes and h == root


def sequential_work(seed: bytes, ticks: int) -> bytes:
    """The honest-to-goodness sequential part (hash chain). Standalone and
    fastnet use tiny tick counts; a real deployment points at an external
    poet instead."""
    h = seed
    for _ in range(ticks):
        h = sum256(h)
    return h


from ..core import codec as _codec
from ..core.codec import u64


@_codec.register
class PoetBlob:
    """Poet proof + the member count its membership proofs verify against
    (gossiped on pt1 and served through fetch so every node can validate
    ATXs referencing the round)."""

    proof: PoetProof
    member_count: int

    FIELDS = [("proof", _codec.struct(PoetProof)), ("member_count", u64)]


@dataclasses.dataclass
class RoundResult:
    proof: PoetProof
    members: list[bytes]

    def membership(self, member: bytes) -> MerkleProof | None:
        try:
            return merkle_path(self.members, self.members.index(member))
        except ValueError:
            return None


class PoetService:
    """In-proc poet: register(challenge) during the open round, run() at
    round end, results keyed by round id."""

    def __init__(self, poet_id: bytes, ticks: int = 64,
                 certifier_pubkey: bytes | None = None,
                 verifier=None):
        self.poet_id = poet_id
        self.ticks = ticks
        # when set, registration requires a certificate signed by this
        # certifier (reference poet deployments gate /submit the same
        # way; consensus/certifier.py issues them against a POST proof)
        self.certifier_pubkey = certifier_pubkey
        self.verifier = verifier
        self._open: dict[str, list[bytes]] = {}
        self._results: dict[str, RoundResult] = {}
        self._lock = asyncio.Lock()

    async def register(self, round_id: str, challenge: bytes,
                       node_id: bytes | None = None,
                       signature: bytes | None = None,
                       cert=None) -> None:
        """Cert-gated mode requires the registration to be BOUND to the
        certified identity: a cert for node_id plus node_id's signature
        over (round_id, challenge) — a stolen/replayed cert without the
        identity's key registers nothing, and rate limits apply per
        certified identity (the reference poet's /submit carries the
        submitter's pubkey + signature the same way)."""
        if self.certifier_pubkey is not None:
            from ..core.signing import Domain
            from .certifier import verify_cert

            if cert is None or node_id is None or signature is None:
                raise PermissionError(
                    "registration requires a certificate + identity proof")
            if cert.node_id != node_id:
                raise PermissionError("certificate is for another identity")
            if not verify_cert(cert, self.certifier_pubkey, self.verifier):
                raise PermissionError("invalid poet certificate")
            if not self.verifier.verify(
                    Domain.POET, node_id,
                    round_id.encode() + challenge, signature):
                raise PermissionError("registration signature invalid")
        async with self._lock:
            if round_id in self._results:
                raise ValueError(f"round {round_id} already closed")
            members = self._open.setdefault(round_id, [])
            if challenge not in members:
                members.append(challenge)

    async def execute_round(self, round_id: str) -> RoundResult:
        async with self._lock:
            members = sorted(self._open.pop(round_id, []))
            root = merkle_root(members)
            # bind the sequential work to the statement
            sequential_work(root, self.ticks)
            proof = PoetProof(poet_id=self.poet_id, round_id=round_id,
                              root=root, ticks=self.ticks)
            result = RoundResult(proof=proof, members=members)
            self._results[round_id] = result
            return result

    def result(self, round_id: str) -> RoundResult | None:
        return self._results.get(round_id)
